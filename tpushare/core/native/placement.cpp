// Native placement engine for tpushare.
//
// Behavioral twin of tpushare/core/placement.py::select_chips_py — the Python
// file is the specification, this file is the speed. Parity is enforced by
// tests/test_native_parity.py over randomized fleets. Keep the two in
// lockstep: iteration order, tie-breaking, and score arithmetic all matter.
//
// Exposed C ABI (ctypes, see engine.py):
//   tpushare_select_chips(...) -> 1 placed / 0 no-fit / -1 engine error
//
// Design notes: a single TPU host has <= 16 chips and rank <= 3, so all
// loops are tiny; the win over Python is constant-factor (no allocation, no
// interpreter) which matters because the extender's Filter fans out over
// every candidate node in the cluster per pending pod (SURVEY §3.2).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <atomic>
#include <ctime>
#include <mutex>
#include <vector>

// -- ABI v8: black-box event ring --------------------------------------------
//
// A process-global, fixed-slot, lock-free event ring the GIL-released
// entry points (tpushare_wire_probe, tpushare_cycle_fleet_topo,
// tpushare_solve_gang) write into when enabled: operation kind, outcome,
// CLOCK_MONOTONIC completion tick, duration ticks, and (for wire probes)
// the first 8 bytes of the span/remainder digests so the Python pump
// (tpushare/obs/blackbox.py) can join an event back to the pod it
// served. Classic bounded MPMC design (per-slot sequence counters): a
// producer that finds the ring full DROPS the event and bumps an atomic
// counter — it never blocks, spins unboundedly, or overwrites a record
// a drain is reading. Disabled (the default) the whole feature is one
// relaxed atomic load and a predictable branch per call.

namespace blackbox {

constexpr int kWireProbe = 1;
constexpr int kCycleTopo = 2;
constexpr int kSolveGang = 3;

constexpr uint64_t kCapacity = 4096;  // power of two; ~192 KiB of BSS

struct Slot {
  std::atomic<uint64_t> seq;
  int64_t kind;
  int64_t outcome;
  int64_t t_ns;
  int64_t dur_ns;
  int64_t span8;
  int64_t rem8;
};

struct Ring {
  std::atomic<uint64_t> head{0};     // producers claim
  std::atomic<uint64_t> tail{0};     // drainers claim
  std::atomic<uint64_t> dropped{0};  // ring-full events discarded
  std::atomic<int> enabled{0};
  Slot slots[kCapacity];
};

Ring g_ring;                // zero-initialized: every slot seq starts 0
std::mutex g_enable_mu;     // enable/disable only (never on a hot path)

inline bool on() {
  return g_ring.enabled.load(std::memory_order_acquire) != 0;
}

inline uint64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000000000ULL + (uint64_t)ts.tv_nsec;
}

inline int64_t prefix8(const uint8_t* digest16) {
  int64_t v;
  std::memcpy(&v, digest16, 8);  // little-endian hosts only, same as wire
  return v;
}

void emit(int64_t kind, int64_t outcome, int64_t span8, int64_t rem8,
          uint64_t t0_ns) {
  uint64_t pos = g_ring.head.load(std::memory_order_relaxed);
  for (;;) {
    Slot* s = &g_ring.slots[pos & (kCapacity - 1)];
    uint64_t seq = s->seq.load(std::memory_order_acquire);
    int64_t dif = (int64_t)seq - (int64_t)pos;
    if (dif == 0) {
      if (g_ring.head.compare_exchange_weak(pos, pos + 1,
                                            std::memory_order_relaxed))
        break;
      // lost the claim race: pos was reloaded by compare_exchange
    } else if (dif < 0) {
      // ring full (the slot still holds an undrained record): drop
      g_ring.dropped.fetch_add(1, std::memory_order_relaxed);
      return;
    } else {
      pos = g_ring.head.load(std::memory_order_relaxed);
    }
  }
  Slot* s = &g_ring.slots[pos & (kCapacity - 1)];
  uint64_t now = now_ns();
  s->kind = kind;
  s->outcome = outcome;
  s->t_ns = (int64_t)now;
  s->dur_ns = (int64_t)(now - t0_ns);
  s->span8 = span8;
  s->rem8 = rem8;
  s->seq.store(pos + 1, std::memory_order_release);
}

}  // namespace blackbox

namespace {

struct Shape {
  std::vector<int64_t> d;
  int64_t mx() const { return *std::max_element(d.begin(), d.end()); }
  int64_t mn() const { return *std::min_element(d.begin(), d.end()); }
};

// Order: (max edge, max-min spread, lexicographic) — most ICI-compact first.
bool shape_less(const Shape& a, const Shape& b) {
  if (a.mx() != b.mx()) return a.mx() < b.mx();
  int64_t sa = a.mx() - a.mn(), sb = b.mx() - b.mn();
  if (sa != sb) return sa < sb;
  return a.d < b.d;
}

void enum_shapes(const int64_t* mesh, int rank, int axis, int64_t remaining,
                 std::vector<int64_t>& prefix, std::vector<Shape>& out) {
  if (axis == rank - 1) {
    if (remaining <= mesh[axis]) {
      Shape s; s.d = prefix; s.d.push_back(remaining);
      out.push_back(std::move(s));
    }
    return;
  }
  for (int64_t d = 1; d <= remaining; ++d) {
    if (remaining % d == 0 && d <= mesh[axis]) {
      prefix.push_back(d);
      enum_shapes(mesh, rank, axis + 1, remaining / d, prefix, out);
      prefix.pop_back();
    }
  }
}

int64_t chip_index(const int64_t* mesh, int rank, const int64_t* coords) {
  int64_t idx = 0;
  for (int i = 0; i < rank; ++i) idx = idx * mesh[i] + coords[i];
  return idx;
}

void chip_coords(const int64_t* mesh, int rank, int64_t idx, int64_t* out) {
  for (int i = rank - 1; i >= 0; --i) { out[i] = idx % mesh[i]; idx /= mesh[i]; }
}

// -- adjacency quality (ABI v7; tpushare/core/topology.py is the spec) -------
// All-integer fixed point: quality = links * kAdjScale / max_links so the
// Python and native scores are bit-identical, never float-rounded.

constexpr int64_t kAdjScale = 1000000;

int64_t box_links_of(const std::vector<int64_t>& d) {
  int64_t n = 1;
  for (auto x : d) n *= x;
  int64_t total = 0;
  for (auto x : d) total += (x - 1) * (n / x);
  return total;
}

void max_links_rec(int64_t remaining, int64_t start,
                   std::vector<int64_t>& dims, int64_t* best) {
  for (int64_t f = start; f * f <= remaining; ++f) {
    if (remaining % f == 0) {
      dims.push_back(f);
      max_links_rec(remaining / f, f, dims, best);
      dims.pop_back();
    }
  }
  dims.push_back(remaining);
  int64_t l = box_links_of(dims);
  if (l > *best) *best = l;
  dims.pop_back();
}

// Max links over ALL factorizations of count (mesh-independent normalizer;
// mirrors topology.max_box_links including its factor enumeration order).
int64_t max_box_links_of(int64_t count) {
  if (count <= 1) return 0;
  int64_t best = 0;
  std::vector<int64_t> dims;
  max_links_rec(count, 2, dims, &best);
  return best;
}

// adjacency_quality(count, box): kAdjScale for one chip, 0 for scatter
// (box == nullptr), -1 for no placement, else scaled links.
int64_t adjacency_of(int req_count, const int64_t* box, int rank) {
  if (req_count <= 0) return -1;
  if (req_count == 1) return kAdjScale;
  if (box == nullptr) return 0;
  std::vector<int64_t> d(box, box + rank);
  return box_links_of(d) * kAdjScale / max_box_links_of(req_count);
}

// congruent(box, pref): multisets of the >1 dims match — the geometry,
// not the axis order or 1-padding, is the contract (topology.congruent).
std::vector<int64_t> nontrivial_sorted(const int64_t* d, int n) {
  std::vector<int64_t> out;
  for (int i = 0; i < n; ++i)
    if (d[i] > 1) out.push_back(d[i]);
  std::sort(out.begin(), out.end());
  return out;
}

bool shape_congruent(const Shape& s, const std::vector<int64_t>& pref_nt) {
  return nontrivial_sorted(s.d.data(), (int)s.d.size()) == pref_nt;
}

}  // namespace

namespace {

// Existence-only fit check for one node (early exit; no scoring).
// Mirrors tpushare.core.placement.fits semantics.
bool fits_one(int n_chips, const int64_t* free_hbm, const int64_t* total_hbm,
              int rank, const int64_t* mesh,
              int64_t req_hbm, int req_count,
              int topo_rank, const int64_t* topo_dims, int allow_scatter) {
  auto demand = [&](int i) -> int64_t {
    return req_hbm == 0 ? total_hbm[i] : req_hbm;
  };
  auto eligible = [&](int i) -> bool {
    return free_hbm[i] >= 0 && free_hbm[i] >= demand(i);
  };
  if (req_count > n_chips) return false;

  if (req_count == 1 || allow_scatter) {
    int n = 0;
    for (int i = 0; i < n_chips; ++i)
      if (eligible(i) && ++n >= req_count) return true;
    return false;
  }

  int64_t mesh_n = 1;
  for (int i = 0; i < rank; ++i) mesh_n *= mesh[i];
  if (mesh_n != n_chips) return false;  // caller uses Python repair path

  std::vector<Shape> shapes;
  if (topo_rank > 0) {
    if (topo_rank != rank) return false;  // rank-mismatched pin, no scatter
    Shape s; s.d.assign(topo_dims, topo_dims + topo_rank);
    int64_t prod = 1;
    for (auto d : s.d) prod *= d;
    if (prod != req_count) return false;
    shapes.push_back(std::move(s));
  } else {
    std::vector<int64_t> prefix;
    enum_shapes(mesh, rank, 0, req_count, prefix, shapes);
  }

  std::vector<int64_t> origin(rank), c(rank);
  for (const auto& shape : shapes) {
    bool fits_mesh = true;
    for (int i = 0; i < rank; ++i)
      if (shape.d[i] > mesh[i]) { fits_mesh = false; break; }
    if (!fits_mesh) continue;
    std::fill(origin.begin(), origin.end(), 0);
    while (true) {
      bool ok = true;
      std::fill(c.begin(), c.end(), 0);
      while (true) {
        int64_t idx = 0;
        for (int i = 0; i < rank; ++i) idx = idx * mesh[i] + origin[i] + c[i];
        if (!eligible((int)idx)) { ok = false; break; }
        int ax = rank - 1;
        while (ax >= 0 && ++c[ax] == shape.d[ax]) c[ax--] = 0;
        if (ax < 0) break;
      }
      if (ok) return true;  // existence is enough for Filter
      int ax = rank - 1;
      while (ax >= 0 && ++origin[ax] > mesh[ax] - shape.d[ax]) origin[ax--] = 0;
      if (ax < 0) break;
    }
  }
  return false;
}

}  // namespace

// ABI stamp for the loaded .so: engine.py surfaces it via /inspect so a
// stale prebuilt library (missing newer symbols, pre-sharding layout) is
// identifiable in production. Bump on any exported-signature or
// fleet-contract change.
//
// ABI v4 COMPATIBILITY NOTE: v4 adds tpushare_cycle_fleet (end-to-end
// Filter+Prioritize+chip-selection in one pass) and tpushare_solve_batch
// (multi-pod disjoint placement solve). Every v3 entry point keeps its
// exact signature and semantics — a v3 caller against a v4 .so is fully
// compatible; a v4 caller against a v3 .so detects the missing symbols
// (AttributeError at bind time) and runs the v3 score-then-reselect
// path. v4 out-array layout: cycle_fleet writes winning chip ids into a
// concatenated array indexed by the SAME absolute node_chip_offsets as
// the inputs (node n's chips at [offsets[n], offsets[n]+req_count)),
// and box/origin at the mesh_rank_offsets — so the sharding and
// resident-arena contracts below carry over to the outputs verbatim.
//
// ABI v5 COMPATIBILITY NOTE: v5 adds tpushare_solve_gang (one-shot
// multi-node gang solve: the tpushare_select_gang box search PLUS the
// per-member host decomposition that used to run in Python, against a
// resident slice arena). Every v4 entry point keeps its exact signature
// and semantics -- a v4 caller against a v5 .so is fully compatible; a
// v5 caller against a v4 .so detects the missing symbol (AttributeError
// at bind time, engine.py _gang_fn) and runs the sequential
// select_gang + Python-decomposition path, which is byte-identical by
// the parity contract (tests/test_native_parity.py). v5 member-array
// layout: member m's local chip ids sit at out_m_ids[m * req_count ..),
// geometry at out_m_box/out_m_origin[m * rank ..) -- member windows are
// per-member strided and independent, so the resident-arena reuse
// contract (caller keeps ONE marshalled slice and re-solves against
// delta-updated free values, engine.py SliceArena) carries over.
//
// ABI v6 COMPATIBILITY NOTE: v6 adds the wire-plane fast path — a
// resident digest→pre-encoded-response table plus tpushare_wire_probe,
// which takes raw HTTP request bytes, locates the NodeNames span with
// the same no-parse scanner as extender/wirecache.py, digests span and
// body remainder (BLAKE2b-128, bit-identical to hashlib.blake2b with
// digest_size=16), and copies the matching pre-encoded response back —
// all without touching the interpreter. Every v5 entry point keeps its
// exact signature and semantics — a v5 caller against a v6 .so is
// fully compatible; a v6 caller against a v5 .so detects the missing
// symbols (AttributeError at bind time, engine.py _wire_lib) and
// serves every request through the Python selector + wirecache path,
// which is byte-identical by construction: the native table is only
// ever delta-synced FROM that path's responses. The table is
// handle-based (create/destroy, one per server), guarded by its own
// internal mutex, and a probe serves an entry only when the caller's
// CURRENT mutation stamp equals the stamp the entry was installed
// under — a moved stamp is a miss (Python fallback), never a stale
// serve.
//
// ABI v7 COMPATIBILITY NOTE: v7 adds tpushare_cycle_fleet_topo — the v4
// cycle entry extended with a mesh-shape soft preference (pref_rank /
// pref_dims reorder the shape walk congruent-first, stable within each
// group) and a per-node adjacency-quality output (out_adj, fixed-point
// [0, 1000000], -1 = no placement), computed in the same GIL-released
// pass. pref_rank == 0 makes the walk byte-identical to
// tpushare_cycle_fleet (same impl, same ordering) — the off/absent
// path never diverges. Every v6 entry point keeps its exact signature
// and semantics — a v6 caller against a v7 .so is fully compatible; a
// v7 caller against a v6 .so detects the missing symbol
// (AttributeError at bind time, engine.py _topo_cycle_fn) and scores
// adjacency in Python from the returned geometry, which is
// bit-identical by the fixed-point parity contract
// (tests/test_topo_properties.py). Offsets stay ABSOLUTE and per-node
// evaluation independent, so the thread-sharding and resident-arena
// contracts hold for out_adj too.
//
// ABI v8 COMPATIBILITY NOTE: v8 adds the black-box event ring —
// tpushare_blackbox_enable / _disable / _drain / _stats over a
// process-global lock-free bounded ring that the GIL-released entry
// points (wire_probe, cycle_fleet_topo, solve_gang) write
// {kind, outcome, t_ns, dur_ns, span8, rem8} events into when enabled.
// Every v7 entry point keeps its exact signature and semantics — a v7
// caller against a v8 .so is fully compatible; a v8 caller against a
// v7 .so detects the missing symbols (AttributeError at bind time,
// engine.py _blackbox_fns) and runs with the ring absent: native
// serves still happen, the Python pump (tpushare/obs/blackbox.py)
// simply reports blackbox_supported=False and the Python-side latency
// fallback stays active. Disabled (the default at load) the ring costs
// one relaxed atomic load per instrumented call; producers NEVER block
// or spin unboundedly — a full ring drops the event and counts it in
// _stats, it never corrupts a record a drain is reading.
extern "C" int64_t tpushare_abi_version() { return 8; }

// -- ABI v8: black-box ring entry points -------------------------------------

// Reset the ring to empty and start recording. Idempotent; safe to call
// while producers are live (enable/disable serialize on a mutex that no
// hot path ever takes). Returns ring capacity in events.
extern "C" int64_t tpushare_blackbox_enable() {
  std::lock_guard<std::mutex> g(blackbox::g_enable_mu);
  blackbox::g_ring.enabled.store(0, std::memory_order_release);
  // Producers that already passed the enabled check may still be
  // completing an emit; the slot-sequence protocol makes that benign —
  // reinitializing seq below simply reclaims every slot.
  blackbox::g_ring.head.store(0, std::memory_order_relaxed);
  blackbox::g_ring.tail.store(0, std::memory_order_relaxed);
  for (uint64_t i = 0; i < blackbox::kCapacity; ++i)
    blackbox::g_ring.slots[i].seq.store(i, std::memory_order_relaxed);
  blackbox::g_ring.enabled.store(1, std::memory_order_release);
  return (int64_t)blackbox::kCapacity;
}

extern "C" void tpushare_blackbox_disable() {
  std::lock_guard<std::mutex> g(blackbox::g_enable_mu);
  blackbox::g_ring.enabled.store(0, std::memory_order_release);
}

// Drain up to max_events records into out (6 int64 per row:
// kind, outcome, t_ns, dur_ns, span8, rem8). Returns rows written.
// Safe against concurrent producers and concurrent drains.
extern "C" int64_t tpushare_blackbox_drain(int64_t max_events,
                                           int64_t* out) {
  if (max_events <= 0 || out == nullptr) return 0;
  int64_t n = 0;
  while (n < max_events) {
    uint64_t pos = blackbox::g_ring.tail.load(std::memory_order_relaxed);
    blackbox::Slot* s = nullptr;
    for (;;) {
      s = &blackbox::g_ring.slots[pos & (blackbox::kCapacity - 1)];
      uint64_t seq = s->seq.load(std::memory_order_acquire);
      int64_t dif = (int64_t)seq - (int64_t)(pos + 1);
      if (dif == 0) {
        if (blackbox::g_ring.tail.compare_exchange_weak(
                pos, pos + 1, std::memory_order_relaxed))
          break;
      } else if (dif < 0) {
        return n;  // ring empty
      } else {
        pos = blackbox::g_ring.tail.load(std::memory_order_relaxed);
      }
    }
    int64_t* row = out + n * 6;
    row[0] = s->kind;
    row[1] = s->outcome;
    row[2] = s->t_ns;
    row[3] = s->dur_ns;
    row[4] = s->span8;
    row[5] = s->rem8;
    s->seq.store(pos + blackbox::kCapacity, std::memory_order_release);
    ++n;
  }
  return n;
}

// out4 = {enabled, capacity, dropped_total, pending}.
extern "C" void tpushare_blackbox_stats(int64_t* out4) {
  if (out4 == nullptr) return;
  out4[0] = (int64_t)blackbox::g_ring.enabled.load(std::memory_order_acquire);
  out4[1] = (int64_t)blackbox::kCapacity;
  out4[2] = (int64_t)blackbox::g_ring.dropped.load(std::memory_order_relaxed);
  uint64_t h = blackbox::g_ring.head.load(std::memory_order_acquire);
  uint64_t t = blackbox::g_ring.tail.load(std::memory_order_acquire);
  out4[3] = (int64_t)(h > t ? h - t : 0);
}

// Fleet-wide Filter: one call evaluates every candidate node, avoiding
// per-node FFI marshalling (the reference's hot loop #1 x #2,
// SURVEY §3.2, fused into native code). Chip arrays are concatenated;
// node_chip_offsets/mesh_rank_offsets are prefix offsets (n_nodes+1).
//
// SHARDING CONTRACT (parallel fleet scan, engine.py _fleet_call): the
// offsets are ABSOLUTE indexes into the concatenated free/total/mesh
// arrays, and each node's evaluation is independent. A caller may
// therefore split one marshalled fleet into disjoint node ranges
// [a, b) and invoke this function concurrently from multiple threads,
// passing offsets+a / out+a with the SAME full chip arrays — each call
// reads shared immutable input and writes only its own out window.
// Both fleet entry points keep this property; do not introduce shared
// mutable state here.
//
// RESIDENT-ARENA NOTE (engine.py FleetArena): the same two properties —
// absolute offsets and per-node independence — are what let a caller
// keep ONE long-lived packed fleet and scan arbitrary subsets of it:
// a run of consecutive slots is passed as views into the resident
// arrays with rebased offsets, with no per-call marshalling. The v4
// additions preserve both properties (cycle_fleet's out arrays use the
// same absolute offsets; solve_batch mutates only caller-owned scratch);
// any future change that makes node evaluation order- or
// neighbor-dependent, or makes offsets relative, breaks BOTH the
// thread-sharding and the arena subset-scan callers and must bump the
// version.
extern "C" int tpushare_fits_fleet(
    int n_nodes,
    const int64_t* node_chip_offsets,
    const int64_t* free_hbm,
    const int64_t* total_hbm,
    const int64_t* mesh_rank_offsets,
    const int64_t* mesh_dims,
    int64_t req_hbm,
    int req_count,
    int topo_rank,
    const int64_t* topo_dims,
    int allow_scatter,
    uint8_t* out_fits) {
  if (n_nodes < 0) return -1;
  for (int n = 0; n < n_nodes; ++n) {
    int64_t c0 = node_chip_offsets[n], c1 = node_chip_offsets[n + 1];
    int64_t m0 = mesh_rank_offsets[n], m1 = mesh_rank_offsets[n + 1];
    out_fits[n] = fits_one(
        (int)(c1 - c0), free_hbm + c0, total_hbm + c0,
        (int)(m1 - m0), mesh_dims + m0,
        req_hbm, req_count, topo_rank, topo_dims, allow_scatter) ? 1 : 0;
  }
  return 0;
}

extern "C" int tpushare_select_chips(
    int n_chips, const int64_t* free_hbm, const int64_t* total_hbm,
    int rank, const int64_t* mesh, int64_t req_hbm, int req_count,
    int topo_rank, const int64_t* topo_dims, int allow_scatter,
    int64_t* out_ids, int64_t* out_box, int64_t* out_origin,
    int64_t* out_score);

// Fleet-wide Prioritize: best placement score per node in one call (the
// ranking analogue of tpushare_fits_fleet; same packed-array layout).
// out_scores[n]: >=0 best binpack score (lower = tighter), -1 = no
// placement, -2 = node not expressible in this ABI (caller falls back to
// the Python selector for it).
extern "C" int tpushare_score_fleet(
    int n_nodes,
    const int64_t* node_chip_offsets,
    const int64_t* free_hbm,
    const int64_t* total_hbm,
    const int64_t* mesh_rank_offsets,
    const int64_t* mesh_dims,
    int64_t req_hbm,
    int req_count,
    int topo_rank,
    const int64_t* topo_dims,
    int allow_scatter,
    int64_t* out_scores) {
  if (n_nodes < 0) return -1;
  std::vector<int64_t> ids, box, origin;
  for (int n = 0; n < n_nodes; ++n) {
    int64_t c0 = node_chip_offsets[n], c1 = node_chip_offsets[n + 1];
    int64_t m0 = mesh_rank_offsets[n], m1 = mesh_rank_offsets[n + 1];
    int n_chips = (int)(c1 - c0), rank = (int)(m1 - m0);
    ids.resize(n_chips > 0 ? n_chips : 1);
    box.resize(rank > 0 ? rank : 1);
    origin.resize(rank > 0 ? rank : 1);
    int64_t score = 0;
    int rc = tpushare_select_chips(
        n_chips, free_hbm + c0, total_hbm + c0, rank, mesh_dims + m0,
        req_hbm, req_count, topo_rank, topo_dims, allow_scatter,
        ids.data(), box.data(), origin.data(), &score);
    out_scores[n] = rc == 1 ? score : (rc == 0 ? -1 : -2);
  }
  return 0;
}

namespace {

// The shared selector body. pref_rank/pref_dims (ABI v7 mesh-shape soft
// preference) reorder the shape walk congruent-first; pref_rank == 0
// leaves the walk byte-identical to the v3 semantics. out_adj (nullable)
// receives the winner's adjacency quality.
int select_chips_impl(
    int n_chips,
    const int64_t* free_hbm,   // -1 => ineligible (unhealthy / exclusive-busy)
    const int64_t* total_hbm,
    int rank,
    const int64_t* mesh,
    int64_t req_hbm,           // 0 => exclusive (demand = chip total)
    int req_count,
    int topo_rank,             // 0 => any shape
    const int64_t* topo_dims,
    int allow_scatter,
    int pref_rank,             // 0 => shape-blind walk
    const int64_t* pref_dims,
    int64_t* out_ids,
    int64_t* out_box,          // out_box[0] == -1 => scattered
    int64_t* out_origin,
    int64_t* out_score,
    int64_t* out_adj) {
  if (n_chips <= 0 || rank <= 0 || req_count <= 0 || req_count > n_chips)
    return req_count > n_chips ? 0 : -1;
  int64_t mesh_n = 1;
  for (int i = 0; i < rank; ++i) mesh_n *= mesh[i];
  if (mesh_n != n_chips) return -1;  // caller falls back to Python topo repair

  auto demand = [&](int i) -> int64_t {
    return req_hbm == 0 ? total_hbm[i] : req_hbm;
  };
  auto eligible = [&](int i) -> bool {
    return free_hbm[i] >= 0 && free_hbm[i] >= demand(i);
  };

  // --- single chip: min-free-that-fits (nodeinfo.go:283-286 semantics) ---
  if (req_count == 1) {
    int best = -1;
    for (int i = 0; i < n_chips; ++i)
      if (eligible(i) && (best < 0 || free_hbm[i] < free_hbm[best])) best = i;
    if (best < 0) return 0;
    out_ids[0] = best;
    for (int i = 0; i < rank; ++i) out_box[i] = 1;
    chip_coords(mesh, rank, best, out_origin);
    *out_score = free_hbm[best] - demand(best);
    if (out_adj != nullptr) *out_adj = kAdjScale;
    return 1;
  }

  // --- multi chip: tightest contiguous sub-box, most-compact shape first ---
  std::vector<Shape> shapes;
  if (topo_rank > 0) {
    if (topo_rank != rank) goto scatter;  // rank-mismatched pin can't match
    Shape s; s.d.assign(topo_dims, topo_dims + topo_rank);
    int64_t prod = 1;
    for (auto d : s.d) prod *= d;
    if (prod == req_count) shapes.push_back(std::move(s));
  } else {
    std::vector<int64_t> prefix;
    enum_shapes(mesh, rank, 0, req_count, prefix, shapes);
    std::sort(shapes.begin(), shapes.end(), shape_less);
    if (pref_rank > 0) {
      // congruent-first STABLE partition: compactness order preserved
      // within each group (topology.congruent_first is the spec)
      std::vector<int64_t> pref_nt = nontrivial_sorted(pref_dims, pref_rank);
      std::stable_partition(
          shapes.begin(), shapes.end(),
          [&](const Shape& s) { return shape_congruent(s, pref_nt); });
    }
  }

  {
    std::vector<int64_t> origin(rank), best_origin(rank), best_box(rank);
    std::vector<int64_t> ids, best_ids;
    for (const auto& shape : shapes) {
      bool fits_mesh = true;
      for (int i = 0; i < rank; ++i)
        if (shape.d[i] > mesh[i]) { fits_mesh = false; break; }
      if (!fits_mesh) continue;

      bool found = false;
      int64_t best_score = 0;
      // iterate origins row-major, last axis fastest (itertools.product order)
      std::fill(origin.begin(), origin.end(), 0);
      while (true) {
        // evaluate box at `origin`
        ids.clear();
        int64_t score = 0;
        bool ok = true;
        std::vector<int64_t> c(rank);
        std::fill(c.begin(), c.end(), 0);
        while (true) {
          std::vector<int64_t> abs(rank);
          for (int i = 0; i < rank; ++i) abs[i] = origin[i] + c[i];
          int64_t idx = chip_index(mesh, rank, abs.data());
          if (!eligible((int)idx)) { ok = false; break; }
          ids.push_back(idx);
          score += free_hbm[idx] - demand((int)idx);
          int ax = rank - 1;
          while (ax >= 0 && ++c[ax] == shape.d[ax]) c[ax--] = 0;
          if (ax < 0) break;
        }
        if (ok && (!found || score < best_score)) {
          found = true;
          best_score = score;
          best_ids = ids;
          best_origin = origin;
          best_box = shape.d;
        }
        int ax = rank - 1;
        while (ax >= 0 && ++origin[ax] > mesh[ax] - shape.d[ax]) origin[ax--] = 0;
        if (ax < 0) break;
      }
      if (found) {
        for (size_t i = 0; i < best_ids.size(); ++i) out_ids[i] = best_ids[i];
        for (int i = 0; i < rank; ++i) {
          out_box[i] = best_box[i];
          out_origin[i] = best_origin[i];
        }
        *out_score = best_score;
        if (out_adj != nullptr)
          *out_adj = adjacency_of(req_count, best_box.data(), rank);
        return 1;
      }
    }
  }

scatter:
  if (!allow_scatter) return 0;
  {
    std::vector<int> elig;
    for (int i = 0; i < n_chips; ++i)
      if (eligible(i)) elig.push_back(i);
    if ((int)elig.size() < req_count) return 0;
    std::stable_sort(elig.begin(), elig.end(),
                     [&](int a, int b) { return free_hbm[a] < free_hbm[b]; });
    int64_t score = 0;
    for (int k = 0; k < req_count; ++k) {
      out_ids[k] = elig[k];
      score += free_hbm[elig[k]] - demand(elig[k]);
    }
    out_box[0] = -1;
    *out_score = score;
    if (out_adj != nullptr) *out_adj = adjacency_of(req_count, nullptr, rank);
    return 1;
  }
}

}  // namespace

extern "C" int tpushare_select_chips(
    int n_chips,
    const int64_t* free_hbm,
    const int64_t* total_hbm,
    int rank,
    const int64_t* mesh,
    int64_t req_hbm,
    int req_count,
    int topo_rank,
    const int64_t* topo_dims,
    int allow_scatter,
    int64_t* out_ids,
    int64_t* out_box,
    int64_t* out_origin,
    int64_t* out_score) {
  return select_chips_impl(
      n_chips, free_hbm, total_hbm, rank, mesh, req_hbm, req_count,
      topo_rank, topo_dims, allow_scatter, /*pref_rank=*/0,
      /*pref_dims=*/nullptr, out_ids, out_box, out_origin, out_score,
      /*out_adj=*/nullptr);
}

// Gang selector over a multi-host SLICE mesh (tpushare/core/slice.py
// select_gang is the behavioral spec; docs/designs/multihost-gang.md).
// Same sub-box search as tpushare_select_chips, but the comparison key
// is (hosts_spanned, score, origin-lex): inter-host links inside a
// slice are ICI, so host crossings cost COORDINATION (kubelets in the
// gang, blast radius), not bandwidth — fewest hosts leads, binpack
// breaks ties, ascending origin iteration resolves the rest. Shape
// classes run most-ICI-compact first with the same first-class-wins
// early break. No scatter mode: gangs are contiguous by definition.
//
// host_of maps global chip idx -> host ordinal in [0, n_hosts);
// free_hbm[i] < 0 marks an ineligible chip (unhealthy, missing host
// snapshot, exclusive-busy — the caller folds eligibility in).
extern "C" int tpushare_select_gang(
    int n_chips,
    const int64_t* free_hbm,
    const int64_t* total_hbm,
    const int64_t* host_of,
    int n_hosts,
    int rank,
    const int64_t* mesh,
    int64_t req_hbm,           // 0 => exclusive (demand = chip total)
    int req_count,
    int topo_rank,             // 0 => any shape
    const int64_t* topo_dims,
    int64_t* out_box,
    int64_t* out_origin,
    int64_t* out_score,
    int64_t* out_hosts) {
  if (n_chips <= 0 || rank <= 0 || req_count <= 0 || n_hosts <= 0)
    return -1;
  if (req_count > n_chips) return 0;
  int64_t mesh_n = 1;
  for (int i = 0; i < rank; ++i) mesh_n *= mesh[i];
  if (mesh_n != n_chips) return -1;

  auto demand = [&](int i) -> int64_t {
    return req_hbm == 0 ? total_hbm[i] : req_hbm;
  };
  auto eligible = [&](int i) -> bool {
    return free_hbm[i] >= 0 && free_hbm[i] >= demand(i);
  };

  std::vector<Shape> shapes;
  if (topo_rank > 0) {
    if (topo_rank != rank) return 0;  // rank-mismatched pin cannot match
    Shape s; s.d.assign(topo_dims, topo_dims + topo_rank);
    int64_t prod = 1;
    for (auto d : s.d) prod *= d;
    if (prod != req_count) return 0;
    shapes.push_back(std::move(s));
  } else {
    std::vector<int64_t> prefix;
    enum_shapes(mesh, rank, 0, req_count, prefix, shapes);
    std::sort(shapes.begin(), shapes.end(), shape_less);
  }

  std::vector<int64_t> origin(rank), c(rank), abs(rank);
  std::vector<int64_t> best_origin(rank), best_box(rank);
  std::vector<char> host_seen(n_hosts);
  for (const auto& shape : shapes) {
    bool fits_mesh = true;
    for (int i = 0; i < rank; ++i)
      if (shape.d[i] > mesh[i]) { fits_mesh = false; break; }
    if (!fits_mesh) continue;

    bool found = false;
    int64_t best_score = 0, best_hosts = 0;
    std::fill(origin.begin(), origin.end(), 0);
    while (true) {
      int64_t score = 0, hosts = 0;
      bool ok = true;
      std::fill(host_seen.begin(), host_seen.end(), 0);
      std::fill(c.begin(), c.end(), 0);
      while (true) {
        for (int i = 0; i < rank; ++i) abs[i] = origin[i] + c[i];
        int64_t idx = chip_index(mesh, rank, abs.data());
        if (!eligible((int)idx)) { ok = false; break; }
        score += free_hbm[idx] - demand((int)idx);
        int64_t h = host_of[idx];
        if (h < 0 || h >= n_hosts) { ok = false; break; }
        if (!host_seen[h]) { host_seen[h] = 1; ++hosts; }
        int ax = rank - 1;
        while (ax >= 0 && ++c[ax] == shape.d[ax]) c[ax--] = 0;
        if (ax < 0) break;
      }
      // ascending-origin iteration + strict less keeps the earliest
      // origin on (hosts, score) ties — matching the Python key's
      // trailing origin-lex component
      if (ok && (!found || hosts < best_hosts ||
                 (hosts == best_hosts && score < best_score))) {
        found = true;
        best_hosts = hosts;
        best_score = score;
        best_origin = origin;
        best_box = shape.d;
      }
      int ax = rank - 1;
      while (ax >= 0 && ++origin[ax] > mesh[ax] - shape.d[ax]) origin[ax--] = 0;
      if (ax < 0) break;
    }
    if (found) {
      for (int i = 0; i < rank; ++i) {
        out_box[i] = best_box[i];
        out_origin[i] = best_origin[i];
      }
      *out_score = best_score;
      *out_hosts = best_hosts;
      return 1;
    }
  }
  return 0;
}

// -- ABI v4: end-to-end cycles + batched solves ------------------------------

// Fleet-wide Filter+Prioritize+selection in ONE pass: like
// tpushare_score_fleet, but the winning chip set (the thing Bind's
// seed-placement lookup used to re-derive with a second call) is written
// out per node instead of discarded. out_scores[n] follows score_fleet
// (-1 no placement, -2 not expressible); when out_scores[n] >= 0 the
// chosen chip ids sit at out_ids[node_chip_offsets[n] ..
// node_chip_offsets[n] + req_count) (node-local ids, exactly what
// tpushare_select_chips emits) and the box/origin at
// out_box/out_origin[mesh_rank_offsets[n] .. +rank); out_box[m0] == -1
// marks a scattered placement. Offsets stay ABSOLUTE and every node's
// evaluation (and out window) is independent, so both the
// thread-sharding and resident-arena subset-scan contracts hold for the
// out arrays too.
extern "C" int tpushare_cycle_fleet(
    int n_nodes,
    const int64_t* node_chip_offsets,
    const int64_t* free_hbm,
    const int64_t* total_hbm,
    const int64_t* mesh_rank_offsets,
    const int64_t* mesh_dims,
    int64_t req_hbm,
    int req_count,
    int topo_rank,
    const int64_t* topo_dims,
    int allow_scatter,
    int64_t* out_scores,
    int64_t* out_ids,
    int64_t* out_box,
    int64_t* out_origin) {
  if (n_nodes < 0) return -1;
  for (int n = 0; n < n_nodes; ++n) {
    int64_t c0 = node_chip_offsets[n], c1 = node_chip_offsets[n + 1];
    int64_t m0 = mesh_rank_offsets[n], m1 = mesh_rank_offsets[n + 1];
    int64_t score = 0;
    int rc = tpushare_select_chips(
        (int)(c1 - c0), free_hbm + c0, total_hbm + c0,
        (int)(m1 - m0), mesh_dims + m0,
        req_hbm, req_count, topo_rank, topo_dims, allow_scatter,
        out_ids + c0, out_box + m0, out_origin + m0, &score);
    out_scores[n] = rc == 1 ? score : (rc == 0 ? -1 : -2);
  }
  return 0;
}

// -- ABI v7: topology-scored cycle -------------------------------------------

// tpushare_cycle_fleet with a mesh-shape soft preference and adjacency
// scoring fused into the same pass. pref_rank/pref_dims declare the
// pod's JAX mesh (e.g. {2, 4}); each node's shape walk runs
// mesh-congruent shape classes first (stable partition of the
// compactness order), so the returned box realizes the declared mesh
// whenever ANY congruent box fits, and out_adj[n] carries the winner's
// adjacency quality (fixed-point [0, kAdjScale]; kAdjScale for single
// chip, 0 for scatter, -1 for no placement / not expressible).
// pref_rank == 0 degrades to exactly tpushare_cycle_fleet's decisions
// with adjacency scored on the side — the byte-identity escape hatch
// TPUSHARE_NO_TOPO_SCORE relies on. Same absolute-offset layout and
// per-node independence as every other fleet entry: thread-sharding
// and resident-arena subset scans carry over, out_adj[n] is one slot
// per node.
extern "C" int tpushare_cycle_fleet_topo(
    int n_nodes,
    const int64_t* node_chip_offsets,
    const int64_t* free_hbm,
    const int64_t* total_hbm,
    const int64_t* mesh_rank_offsets,
    const int64_t* mesh_dims,
    int64_t req_hbm,
    int req_count,
    int topo_rank,
    const int64_t* topo_dims,
    int allow_scatter,
    int pref_rank,
    const int64_t* pref_dims,
    int64_t* out_scores,
    int64_t* out_ids,
    int64_t* out_box,
    int64_t* out_origin,
    int64_t* out_adj) {
  if (n_nodes < 0) return -1;
  const bool bb = blackbox::on();
  const uint64_t bb_t0 = bb ? blackbox::now_ns() : 0;
  int64_t feasible = 0;
  for (int n = 0; n < n_nodes; ++n) {
    int64_t c0 = node_chip_offsets[n], c1 = node_chip_offsets[n + 1];
    int64_t m0 = mesh_rank_offsets[n], m1 = mesh_rank_offsets[n + 1];
    int64_t score = 0, adj = -1;
    int rc = select_chips_impl(
        (int)(c1 - c0), free_hbm + c0, total_hbm + c0,
        (int)(m1 - m0), mesh_dims + m0,
        req_hbm, req_count, topo_rank, topo_dims, allow_scatter,
        pref_rank, pref_dims,
        out_ids + c0, out_box + m0, out_origin + m0, &score, &adj);
    out_scores[n] = rc == 1 ? score : (rc == 0 ? -1 : -2);
    out_adj[n] = rc == 1 ? adj : -1;
    if (rc == 1) ++feasible;
  }
  // Black-box event: outcome = feasible-node count for the whole pass.
  if (bb) blackbox::emit(blackbox::kCycleTopo, feasible, 0, 0, bb_t0);
  return 0;
}

// Multi-pod solve: place k IDENTICAL requests (one _req_sig equivalence
// class) onto the fleet in one call, returning k pairwise chip-DISJOINT
// speculative placements. k repetitions of the single-pod decision
// (argmin node score), with two batch-specific rules:
//
// 1. every chip a member takes is marked INELIGIBLE (free = -1) before
//    the next member solves — disjointness by construction. Sharing a
//    chip across members would be HBM-legal, but a speculative sibling
//    placement is worthless the moment the first member's bind moves
//    the node's stamp, and disjointness keeps apiserver truth
//    oversubscription-free even if every member's PATCH lands;
// 2. nodes no member has touched are preferred (argmin key is
//    (touched, score, node index)) — a placement on a sibling's node
//    is guaranteed to be stamp-demoted to the solo path once that
//    sibling binds, so spreading maximizes the placements that survive
//    revalidation; same-node disjoint placements are still produced
//    when untouched capacity runs out.
//
// free_hbm is MUTATED — callers pass a scratch copy, never
// resident-arena buffers.
//
// Outputs per member m: out_nodes[m] = node index into this call's
// fleet (-1 = no placement for this and all later members — capacity
// only shrinks), out_scores[m], node-local chip ids at
// out_ids[m * req_count ..), box/origin at out_box/out_origin
// [m * geo_stride ..) with geo_stride >= every node's rank
// (out_box[m * geo_stride] == -1 marks scatter). NOT shardable: members
// are sequentially dependent by design; one call per batch.
extern "C" int tpushare_solve_batch(
    int n_nodes,
    const int64_t* node_chip_offsets,
    int64_t* free_hbm,
    const int64_t* total_hbm,
    const int64_t* mesh_rank_offsets,
    const int64_t* mesh_dims,
    int64_t req_hbm,
    int req_count,
    int topo_rank,
    const int64_t* topo_dims,
    int allow_scatter,
    int k,
    int geo_stride,
    int64_t* out_nodes,
    int64_t* out_scores,
    int64_t* out_ids,
    int64_t* out_box,
    int64_t* out_origin) {
  if (n_nodes < 0 || k < 0 || req_count <= 0 || geo_stride <= 0)
    return -1;
  int64_t max_chips = 1, max_rank = 1;
  for (int n = 0; n < n_nodes; ++n) {
    max_chips = std::max(max_chips,
                         node_chip_offsets[n + 1] - node_chip_offsets[n]);
    max_rank = std::max(max_rank,
                        mesh_rank_offsets[n + 1] - mesh_rank_offsets[n]);
  }
  if (max_rank > geo_stride) return -1;
  std::vector<int64_t> ids(max_chips), box(max_rank), origin(max_rank);
  std::vector<int64_t> scores(n_nodes);
  std::vector<char> fit(n_nodes), touched(n_nodes);

  auto rescore = [&](int n) {
    int64_t c0 = node_chip_offsets[n], c1 = node_chip_offsets[n + 1];
    int64_t m0 = mesh_rank_offsets[n], m1 = mesh_rank_offsets[n + 1];
    int64_t s = 0;
    int rc = tpushare_select_chips(
        (int)(c1 - c0), free_hbm + c0, total_hbm + c0,
        (int)(m1 - m0), mesh_dims + m0,
        req_hbm, req_count, topo_rank, topo_dims, allow_scatter,
        ids.data(), box.data(), origin.data(), &s);
    fit[n] = rc == 1;
    scores[n] = s;
  };
  for (int n = 0; n < n_nodes; ++n) rescore(n);

  for (int m = 0; m < k; ++m) {
    int best = -1;
    for (int n = 0; n < n_nodes; ++n)
      if (fit[n] && (best < 0 ||
                     (touched[n] != touched[best]
                          ? touched[n] < touched[best]
                          : scores[n] < scores[best])))
        best = n;
    if (best < 0) {
      for (int r = m; r < k; ++r) out_nodes[r] = -1;
      return 0;
    }
    // re-run the selector on the winner to materialize the chip set
    // (the scan above kept only scores); the scratch holds node-local
    // ids and geometry for exactly this node
    rescore(best);
    if (!fit[best]) { --m; continue; }  // defensive; cannot recur
    int64_t c0 = node_chip_offsets[best];
    int64_t m0 = mesh_rank_offsets[best], m1 = mesh_rank_offsets[best + 1];
    int rank = (int)(m1 - m0);
    out_nodes[m] = best;
    out_scores[m] = scores[best];
    for (int j = 0; j < req_count; ++j)
      out_ids[(int64_t)m * req_count + j] = ids[j];
    for (int i = 0; i < geo_stride; ++i) {
      out_box[(int64_t)m * geo_stride + i] = i < rank ? box[i] : 0;
      out_origin[(int64_t)m * geo_stride + i] = i < rank ? origin[i] : 0;
    }
    // rule 1: the taken chips leave the pool entirely (disjointness);
    // rule 2: the node is now a demotion risk for siblings
    for (int j = 0; j < req_count; ++j)
      free_hbm[c0 + ids[j]] = -1;
    touched[best] = 1;
    rescore(best);
  }
  return 0;
}

// -- ABI v5: one-shot multi-node gang solve ----------------------------------

// tpushare_select_gang's box search PLUS the per-member host
// decomposition (tpushare/core/slice.py _build_gang is the behavioral
// spec), in one GIL-released call. The host partition is given as the
// uniform per-host box dims `hbox` (mesh must tile exactly: mesh[i] %
// hbox[i] == 0) — host ordinal = row-major index over the host grid
// mesh/hbox, matching HostMesh in core/topology.py. Compared to
// select_gang this removes the Python-side merge/decompose passes and
// lets the caller keep a RESIDENT marshalled slice (engine.py
// SliceArena) whose free values are delta-synced per host.
//
// Outputs on return 1: global best box/origin/score as select_gang,
// plus *out_n_members member records in FIRST-APPEARANCE order over the
// row-major box walk (the same order slice.py _build_gang discovers
// hosts): out_m_host[m] = host ordinal, out_m_nchips[m] chips with
// sorted LOCAL ids at out_m_ids[m * req_count ..), local geometry at
// out_m_box/out_m_origin[m * rank ..), binpack sub-score at
// out_m_score[m]. The member windows are strided by the caller-known
// req_count / rank, never by n_members — windows are independent.
// Return 0 = no placement, -1 = not expressible (caller falls back).
static int solve_gang_impl(
    int n_chips,
    const int64_t* free_hbm,   // -1 => ineligible (caller folds eligibility)
    const int64_t* total_hbm,
    int rank,
    const int64_t* mesh,
    const int64_t* hbox,       // uniform per-host box dims (rank)
    int64_t req_hbm,           // 0 => exclusive (demand = chip total)
    int req_count,
    int topo_rank,             // 0 => any shape
    const int64_t* topo_dims,
    int max_members,           // capacity of the member out arrays
    int64_t* out_box,
    int64_t* out_origin,
    int64_t* out_score,
    int64_t* out_n_members,
    int64_t* out_m_host,
    int64_t* out_m_nchips,
    int64_t* out_m_ids,
    int64_t* out_m_box,
    int64_t* out_m_origin,
    int64_t* out_m_score) {
  if (n_chips <= 0 || rank <= 0 || req_count <= 0 || max_members <= 0)
    return -1;
  if (req_count > n_chips) return 0;
  int64_t mesh_n = 1, n_hosts = 1;
  for (int i = 0; i < rank; ++i) {
    if (hbox[i] <= 0 || mesh[i] % hbox[i] != 0) return -1;
    mesh_n *= mesh[i];
    n_hosts *= mesh[i] / hbox[i];
  }
  if (mesh_n != n_chips) return -1;

  auto demand = [&](int i) -> int64_t {
    return req_hbm == 0 ? total_hbm[i] : req_hbm;
  };
  auto eligible = [&](int i) -> bool {
    return free_hbm[i] >= 0 && free_hbm[i] >= demand(i);
  };
  // host ordinal of a global coordinate: row-major over the host grid
  std::vector<int64_t> grid(rank);
  for (int i = 0; i < rank; ++i) grid[i] = mesh[i] / hbox[i];
  auto host_of = [&](const int64_t* coords) -> int64_t {
    int64_t h = 0;
    for (int i = 0; i < rank; ++i) h = h * grid[i] + coords[i] / hbox[i];
    return h;
  };

  std::vector<Shape> shapes;
  if (topo_rank > 0) {
    if (topo_rank != rank) return 0;  // rank-mismatched pin cannot match
    Shape s; s.d.assign(topo_dims, topo_dims + topo_rank);
    int64_t prod = 1;
    for (auto d : s.d) prod *= d;
    if (prod != req_count) return 0;
    shapes.push_back(std::move(s));
  } else {
    std::vector<int64_t> prefix;
    enum_shapes(mesh, rank, 0, req_count, prefix, shapes);
    std::sort(shapes.begin(), shapes.end(), shape_less);
  }

  std::vector<int64_t> origin(rank), c(rank), abs(rank);
  std::vector<int64_t> best_origin(rank), best_box(rank);
  std::vector<char> host_seen(n_hosts);
  bool found = false;
  for (const auto& shape : shapes) {
    bool fits_mesh = true;
    for (int i = 0; i < rank; ++i)
      if (shape.d[i] > mesh[i]) { fits_mesh = false; break; }
    if (!fits_mesh) continue;

    int64_t best_score = 0, best_hosts = 0;
    std::fill(origin.begin(), origin.end(), 0);
    while (true) {
      int64_t score = 0, hosts = 0;
      bool ok = true;
      std::fill(host_seen.begin(), host_seen.end(), 0);
      std::fill(c.begin(), c.end(), 0);
      while (true) {
        for (int i = 0; i < rank; ++i) abs[i] = origin[i] + c[i];
        int64_t idx = chip_index(mesh, rank, abs.data());
        if (!eligible((int)idx)) { ok = false; break; }
        score += free_hbm[idx] - demand((int)idx);
        int64_t h = host_of(abs.data());
        if (!host_seen[h]) { host_seen[h] = 1; ++hosts; }
        int ax = rank - 1;
        while (ax >= 0 && ++c[ax] == shape.d[ax]) c[ax--] = 0;
        if (ax < 0) break;
      }
      // ascending-origin iteration + strict less keeps the earliest
      // origin on (hosts, score) ties — same key as select_gang
      if (ok && (!found || hosts < best_hosts ||
                 (hosts == best_hosts && score < best_score))) {
        found = true;
        best_hosts = hosts;
        best_score = score;
        best_origin = origin;
        best_box = shape.d;
      }
      int ax = rank - 1;
      while (ax >= 0 && ++origin[ax] > mesh[ax] - shape.d[ax]) origin[ax--] = 0;
      if (ax < 0) break;
    }
    if (found) break;  // first shape class with a placement wins
  }
  if (!found) return 0;

  // -- decompose the winning box into per-host member records ----------------
  // member index per host ordinal, assigned in first-appearance order
  // over the SAME row-major box walk the search used (and slice.py
  // _build_gang uses), so member order matches the Python spec exactly
  std::vector<int> member_of(n_hosts, -1);
  int n_members = 0;
  int64_t total_score = 0;
  std::fill(c.begin(), c.end(), 0);
  while (true) {
    for (int i = 0; i < rank; ++i) abs[i] = best_origin[i] + c[i];
    int64_t idx = chip_index(mesh, rank, abs.data());
    int64_t h = host_of(abs.data());
    int m = member_of[h];
    if (m < 0) {
      if (n_members >= max_members) return -1;  // caller sized too small
      m = member_of[h] = n_members++;
      out_m_host[m] = h;
      out_m_nchips[m] = 0;
      out_m_score[m] = 0;
      for (int i = 0; i < rank; ++i) {
        // host-local box accumulators: origin tracks the min local
        // coord, box temporarily the max (turned into extent below)
        out_m_origin[(int64_t)m * rank + i] = hbox[i];
        out_m_box[(int64_t)m * rank + i] = -1;
      }
    }
    // local coordinate within the host's tile + row-major local id
    int64_t lid = 0;
    for (int i = 0; i < rank; ++i) {
      int64_t lc = abs[i] % hbox[i];
      lid = lid * hbox[i] + lc;
      int64_t* mo = out_m_origin + (int64_t)m * rank + i;
      int64_t* mb = out_m_box + (int64_t)m * rank + i;
      if (lc < *mo) *mo = lc;
      if (lc > *mb) *mb = lc;
    }
    // row-major walk visits each host's cells in ascending local id
    // order, so the per-member id list lands sorted without a sort
    out_m_ids[(int64_t)m * req_count + out_m_nchips[m]++] = lid;
    out_m_score[m] += free_hbm[idx] - demand((int)idx);
    int ax = rank - 1;
    while (ax >= 0 && ++c[ax] == best_box[ax]) c[ax--] = 0;
    if (ax < 0) break;
  }
  for (int m = 0; m < n_members; ++m) {
    total_score += out_m_score[m];
    for (int i = 0; i < rank; ++i) {
      int64_t o = out_m_origin[(int64_t)m * rank + i];
      out_m_box[(int64_t)m * rank + i] =
          out_m_box[(int64_t)m * rank + i] - o + 1;
    }
  }
  for (int i = 0; i < rank; ++i) {
    out_box[i] = best_box[i];
    out_origin[i] = best_origin[i];
  }
  *out_score = total_score;
  *out_n_members = n_members;
  return 1;
}

// Exported shim: unchanged v5 signature/semantics; adds only the v8
// black-box event (kind=kSolveGang, outcome = impl return code).
extern "C" int tpushare_solve_gang(
    int n_chips,
    const int64_t* free_hbm,
    const int64_t* total_hbm,
    int rank,
    const int64_t* mesh,
    const int64_t* hbox,
    int64_t req_hbm,
    int req_count,
    int topo_rank,
    const int64_t* topo_dims,
    int max_members,
    int64_t* out_box,
    int64_t* out_origin,
    int64_t* out_score,
    int64_t* out_n_members,
    int64_t* out_m_host,
    int64_t* out_m_nchips,
    int64_t* out_m_ids,
    int64_t* out_m_box,
    int64_t* out_m_origin,
    int64_t* out_m_score) {
  const bool bb = blackbox::on();
  const uint64_t bb_t0 = bb ? blackbox::now_ns() : 0;
  int rc = solve_gang_impl(
      n_chips, free_hbm, total_hbm, rank, mesh, hbox, req_hbm, req_count,
      topo_rank, topo_dims, max_members, out_box, out_origin, out_score,
      out_n_members, out_m_host, out_m_nchips, out_m_ids, out_m_box,
      out_m_origin, out_m_score);
  if (bb) blackbox::emit(blackbox::kSolveGang, rc, 0, 0, bb_t0);
  return rc;
}

// ---------------------------------------------------------------------------
// ABI v6: wire-plane fast path.
//
// The steady-state serve path (httpserver.py _native_serve) hands the raw
// bytes of a connection's input buffer to tpushare_wire_probe with the GIL
// released. The probe parses just enough HTTP to frame one request, ports
// wirecache.py's no-parse NodeNames scanner, digests the span and the body
// remainder with BLAKE2b-128 (bit-identical to hashlib.blake2b(...,
// digest_size=16) so the Python sync side can compute the same keys with
// the stdlib), and serves a pre-encoded response installed earlier by the
// Python wirecache under the mutation-stamp protocol. Anything the probe is
// not POSITIVE about — ambiguous framing, chunked bodies, close semantics,
// a moved stamp — is a miss or a bypass, never a guess: the Python path
// behind it is the specification and serves every non-hit byte-identically.

namespace wire {

// --- BLAKE2b (RFC 7693), keyless, sequential. Only the 16-byte-digest
// parameterization is exercised; the core is the full 12-round function.

constexpr size_t kBlockBytes = 128;

const uint64_t kIV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

const uint8_t kSigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);  // little-endian hosts only (x86-64 / aarch64)
  return v;
}

inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

struct B2 {
  uint64_t h[8];
  uint64_t t0, t1;
  uint8_t buf[kBlockBytes];
  size_t buflen;
};

void b2_compress(B2* s, const uint8_t* block, bool last) {
  uint64_t m[16], v[16];
  for (int i = 0; i < 16; ++i) m[i] = load64(block + 8 * i);
  for (int i = 0; i < 8; ++i) v[i] = s->h[i];
  for (int i = 0; i < 8; ++i) v[8 + i] = kIV[i];
  v[12] ^= s->t0;
  v[13] ^= s->t1;
  if (last) v[14] = ~v[14];
#define B2_G(a, b, c, d, x, y)       \
  do {                               \
    v[a] = v[a] + v[b] + (x);        \
    v[d] = rotr64(v[d] ^ v[a], 32);  \
    v[c] = v[c] + v[d];              \
    v[b] = rotr64(v[b] ^ v[c], 24);  \
    v[a] = v[a] + v[b] + (y);        \
    v[d] = rotr64(v[d] ^ v[a], 16);  \
    v[c] = v[c] + v[d];              \
    v[b] = rotr64(v[b] ^ v[c], 63);  \
  } while (0)
  for (int r = 0; r < 12; ++r) {
    const uint8_t* g = kSigma[r];
    B2_G(0, 4, 8, 12, m[g[0]], m[g[1]]);
    B2_G(1, 5, 9, 13, m[g[2]], m[g[3]]);
    B2_G(2, 6, 10, 14, m[g[4]], m[g[5]]);
    B2_G(3, 7, 11, 15, m[g[6]], m[g[7]]);
    B2_G(0, 5, 10, 15, m[g[8]], m[g[9]]);
    B2_G(1, 6, 11, 12, m[g[10]], m[g[11]]);
    B2_G(2, 7, 8, 13, m[g[12]], m[g[13]]);
    B2_G(3, 4, 9, 14, m[g[14]], m[g[15]]);
  }
#undef B2_G
  for (int i = 0; i < 8; ++i) s->h[i] ^= v[i] ^ v[8 + i];
}

void b2_init(B2* s, size_t outlen) {
  std::memset(s, 0, sizeof(*s));
  for (int i = 0; i < 8; ++i) s->h[i] = kIV[i];
  // parameter block word 0: digest_length | key_length<<8 | fanout<<16
  // | depth<<24 (sequential mode: fanout = depth = 1)
  s->h[0] ^= 0x01010000ULL ^ (uint64_t)outlen;
}

void b2_update(B2* s, const uint8_t* in, size_t len) {
  if (len == 0) return;
  size_t left = s->buflen;
  size_t fill = kBlockBytes - left;
  if (len > fill) {
    s->buflen = 0;
    std::memcpy(s->buf + left, in, fill);
    s->t0 += kBlockBytes;
    if (s->t0 < kBlockBytes) s->t1++;
    b2_compress(s, s->buf, false);
    in += fill;
    len -= fill;
    while (len > kBlockBytes) {  // strictly >: keep >=1 byte for final
      s->t0 += kBlockBytes;
      if (s->t0 < kBlockBytes) s->t1++;
      b2_compress(s, in, false);
      in += kBlockBytes;
      len -= kBlockBytes;
    }
  }
  std::memcpy(s->buf + s->buflen, in, len);
  s->buflen += len;
}

void b2_final(B2* s, uint8_t* out, size_t outlen) {
  s->t0 += s->buflen;
  if (s->t0 < s->buflen) s->t1++;
  std::memset(s->buf + s->buflen, 0, kBlockBytes - s->buflen);
  b2_compress(s, s->buf, true);
  uint8_t full[64];
  std::memcpy(full, s->h, 64);  // little-endian host: h[] is the digest
  std::memcpy(out, full, outlen);
}

// --- resident digest→response table.

constexpr size_t kDigest = 16;
constexpr size_t kCapacity = 128;

struct Entry {
  uint8_t span[kDigest];
  uint8_t rem[kDigest];
  int32_t verb;
  int64_t stamp;
  std::vector<uint8_t> resp;
  uint64_t used;
};

struct Table {
  std::mutex mu;
  std::vector<Entry> entries;
  uint64_t tick = 0;
  int64_t probes = 0, hits = 0, misses = 0, stamp_misses = 0;
  int64_t installs = 0, evictions = 0;
};

// --- HTTP framing + NodeNames scanner (ports wirecache._find_span).

constexpr int kHit = 1;         // response written, *consumed set
constexpr int kMiss = 0;        // eligible request, no current entry
constexpr int kIncomplete = -2; // need more bytes before judging
constexpr int kGrow = -3;       // out buffer too small, *out_len = need
constexpr int kBypass = -4;     // not a fast-path request: Python serves
constexpr int kError = -1;

constexpr int64_t kMaxHeaderBytes = 64 * 1024;       // httpserver 431 cap
constexpr int64_t kMaxBodyBytes = 64 * 1024 * 1024;  // httpserver 413 cap

inline bool ieq(uint8_t a, uint8_t b) {
  return (a | 0x20) == (b | 0x20);  // ASCII case-insensitive
}

bool header_is(const uint8_t* name, size_t n, const char* want) {
  size_t w = std::strlen(want);
  if (n != w) return false;
  for (size_t i = 0; i < n; ++i)
    if (!ieq(name[i], (uint8_t)want[i])) return false;
  return true;
}

// Finds `"NodeNames": [...]` from the END of the body (the key appears
// once, near the end of ExtenderArgs) — identical semantics to
// wirecache._find_span: rfind key, skip WS, ':', skip WS, '[', forward
// find ']'. Returns false when the shape is not there.
bool find_span(const uint8_t* body, int64_t n, int64_t* s, int64_t* e) {
  static const char kKey[] = "\"NodeNames\"";
  constexpr int64_t kKeyLen = 11;
  int64_t i = -1;
  for (int64_t p = n - kKeyLen; p >= 0; --p) {
    if (std::memcmp(body + p, kKey, kKeyLen) == 0) {
      i = p;
      break;
    }
  }
  if (i < 0) return false;
  int64_t j = i + kKeyLen;
  while (j < n && (body[j] == ' ' || body[j] == '\t' || body[j] == '\r' ||
                   body[j] == '\n'))
    j++;
  if (j >= n || body[j] != ':') return false;
  j++;
  while (j < n && (body[j] == ' ' || body[j] == '\t' || body[j] == '\r' ||
                   body[j] == '\n'))
    j++;
  if (j >= n || body[j] != '[') return false;
  int64_t k = -1;
  for (int64_t p = j; p < n; ++p) {
    if (body[p] == ']') {
      k = p;
      break;
    }
  }
  if (k < 0) return false;
  *s = j;
  *e = k + 1;
  return true;
}

}  // namespace wire

extern "C" void* tpushare_wire_table_create(void) {
  return new (std::nothrow) wire::Table();
}

extern "C" void tpushare_wire_table_destroy(void* t) {
  delete static_cast<wire::Table*>(t);
}

// Installs (or refreshes) one pre-encoded response under its span digest,
// remainder digest, verb and the mutation stamp it was computed under.
// Matching is by (span, rem, verb): a re-install after a fleet mutation
// self-heals the entry in place with the new stamp+bytes. Returns 0, or
// -1 on bad arguments.
extern "C" int tpushare_wire_install(void* tp, const uint8_t* span,
                                     const uint8_t* rem, int32_t verb,
                                     int64_t stamp, const uint8_t* resp,
                                     int64_t resp_len) {
  if (tp == nullptr || span == nullptr || rem == nullptr ||
      resp == nullptr || resp_len <= 0)
    return -1;
  auto* t = static_cast<wire::Table*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  t->installs++;
  t->tick++;
  for (auto& ent : t->entries) {
    if (ent.verb == verb &&
        std::memcmp(ent.span, span, wire::kDigest) == 0 &&
        std::memcmp(ent.rem, rem, wire::kDigest) == 0) {
      ent.stamp = stamp;
      ent.resp.assign(resp, resp + resp_len);
      ent.used = t->tick;
      return 0;
    }
  }
  wire::Entry* slot;
  if (t->entries.size() >= wire::kCapacity) {
    slot = &t->entries[0];
    for (auto& ent : t->entries)
      if (ent.used < slot->used) slot = &ent;
    t->evictions++;
  } else {
    t->entries.emplace_back();
    slot = &t->entries.back();
  }
  std::memcpy(slot->span, span, wire::kDigest);
  std::memcpy(slot->rem, rem, wire::kDigest);
  slot->verb = verb;
  slot->stamp = stamp;
  slot->resp.assign(resp, resp + resp_len);
  slot->used = t->tick;
  return 0;
}

extern "C" void tpushare_wire_clear(void* tp) {
  if (tp == nullptr) return;
  auto* t = static_cast<wire::Table*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  t->entries.clear();
}

// out[8] = {entries, capacity, probes, hits, misses, stamp_misses,
//           installs, evictions}
extern "C" void tpushare_wire_stats(void* tp, int64_t* out) {
  if (tp == nullptr || out == nullptr) return;
  auto* t = static_cast<wire::Table*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  out[0] = (int64_t)t->entries.size();
  out[1] = (int64_t)wire::kCapacity;
  out[2] = t->probes;
  out[3] = t->hits;
  out[4] = t->misses;
  out[5] = t->stamp_misses;
  out[6] = t->installs;
  out[7] = t->evictions;
}

// Digest helper exported for parity testing and for the Python sync side's
// self-checks: BLAKE2b-128 over [pre | post] (either part may be empty),
// written to out16. Mirrors hashlib.blake2b(digest_size=16) streamed over
// two chunks.
extern "C" void tpushare_wire_digest2(const uint8_t* pre, int64_t pre_len,
                                      const uint8_t* post, int64_t post_len,
                                      uint8_t* out16) {
  wire::B2 st;
  wire::b2_init(&st, wire::kDigest);
  if (pre != nullptr && pre_len > 0) wire::b2_update(&st, pre, (size_t)pre_len);
  if (post != nullptr && post_len > 0)
    wire::b2_update(&st, post, (size_t)post_len);
  wire::b2_final(&st, out16, wire::kDigest);
}

// The probe. req/req_len is the connection's raw input buffer (possibly
// several pipelined requests; only the FIRST is examined). stamp is the
// caller's CURRENT mutation stamp, read immediately before the call.
// Returns:
//    1  hit — response bytes copied to out (*out_len), *consumed = bytes
//       of the request to pop from the input buffer
//    0  eligible digest-shaped request, but no current entry (cold or
//       stamp moved): caller serves through the Python path
//   -2  incomplete — more bytes must arrive before the request is framed
//   -3  out buffer too small — *out_len holds the needed size, retry
//   -4  bypass — not a fast-path request (wrong verb/route/version,
//       chunked, close semantics, no NodeNames span, oversized)
//   -1  error (bad arguments)
static int wire_probe_impl(void* tp, const uint8_t* req,
                           int64_t req_len, int64_t stamp,
                           uint8_t* out, int64_t out_cap,
                           int64_t* out_len, int64_t* consumed,
                           int64_t* bb_span8, int64_t* bb_rem8,
                           int64_t* bb_verb) {
  if (tp == nullptr || req == nullptr || out_len == nullptr ||
      consumed == nullptr)
    return wire::kError;
  if (req_len <= 0) return wire::kIncomplete;

  // frame the head
  int64_t head_end = -1;
  for (int64_t p = 0; p + 3 < req_len; ++p) {
    if (req[p] == '\r' && req[p + 1] == '\n' && req[p + 2] == '\r' &&
        req[p + 3] == '\n') {
      head_end = p;
      break;
    }
  }
  if (head_end < 0)
    return req_len > wire::kMaxHeaderBytes ? wire::kBypass : wire::kIncomplete;
  if (head_end > wire::kMaxHeaderBytes) return wire::kBypass;

  // request line: POST /tpushare-scheduler/{filter|prioritize} HTTP/1.1
  int64_t line_end = -1;
  for (int64_t p = 0; p + 1 <= head_end; ++p) {
    if (req[p] == '\r' && req[p + 1] == '\n') {
      line_end = p;
      break;
    }
  }
  if (line_end < 0) line_end = head_end;
  static const char kF[] = "POST /tpushare-scheduler/filter HTTP/1.1";
  static const char kP[] = "POST /tpushare-scheduler/prioritize HTTP/1.1";
  int32_t verb;
  if (line_end == (int64_t)sizeof(kF) - 1 &&
      std::memcmp(req, kF, sizeof(kF) - 1) == 0) {
    verb = 0;
  } else if (line_end == (int64_t)sizeof(kP) - 1 &&
             std::memcmp(req, kP, sizeof(kP) - 1) == 0) {
    verb = 1;
  } else {
    return wire::kBypass;
  }
  *bb_verb = verb;

  // headers: Content-Length required; Transfer-Encoding or an explicit
  // Connection: close demotes to the Python path (it owns close/chunked
  // semantics). Last duplicate wins, matching the dict the Python parser
  // builds.
  int64_t content_length = -1;
  int64_t p = line_end + 2;
  while (p < head_end) {
    int64_t eol = -1;
    for (int64_t q = p; q + 1 <= head_end; ++q) {
      if (req[q] == '\r' && req[q + 1] == '\n') {
        eol = q;
        break;
      }
    }
    if (eol < 0) eol = head_end;
    int64_t colon = -1;
    for (int64_t q = p; q < eol; ++q) {
      if (req[q] == ':') {
        colon = q;
        break;
      }
    }
    if (colon > p) {
      const uint8_t* name = req + p;
      size_t name_len = (size_t)(colon - p);
      int64_t v0 = colon + 1, v1 = eol;
      while (v0 < v1 && (req[v0] == ' ' || req[v0] == '\t')) v0++;
      while (v1 > v0 && (req[v1 - 1] == ' ' || req[v1 - 1] == '\t')) v1--;
      if (wire::header_is(name, name_len, "transfer-encoding")) {
        return wire::kBypass;
      } else if (wire::header_is(name, name_len, "connection")) {
        if (v1 - v0 == 5 && wire::ieq(req[v0], 'c') &&
            wire::ieq(req[v0 + 1], 'l') && wire::ieq(req[v0 + 2], 'o') &&
            wire::ieq(req[v0 + 3], 's') && wire::ieq(req[v0 + 4], 'e'))
          return wire::kBypass;
      } else if (wire::header_is(name, name_len, "content-length")) {
        if (v0 >= v1) return wire::kBypass;
        int64_t cl = 0;
        for (int64_t q = v0; q < v1; ++q) {
          if (req[q] < '0' || req[q] > '9') return wire::kBypass;
          cl = cl * 10 + (req[q] - '0');
          if (cl > wire::kMaxBodyBytes) return wire::kBypass;
        }
        content_length = cl;
      }
    }
    p = eol + 2;
  }
  if (content_length < 0) return wire::kBypass;

  const int64_t body_off = head_end + 4;
  const int64_t total = body_off + content_length;
  if (req_len < total) return wire::kIncomplete;
  const uint8_t* body = req + body_off;

  // NodeNames span + the two digests
  int64_t s, e;
  if (!wire::find_span(body, content_length, &s, &e)) return wire::kBypass;
  uint8_t span_d[wire::kDigest], rem_d[wire::kDigest];
  tpushare_wire_digest2(body + s, e - s, nullptr, 0, span_d);
  tpushare_wire_digest2(body, s, body + e, content_length - e, rem_d);
  *bb_span8 = blackbox::prefix8(span_d);
  *bb_rem8 = blackbox::prefix8(rem_d);

  auto* t = static_cast<wire::Table*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  t->probes++;
  for (auto& ent : t->entries) {
    if (ent.verb != verb) continue;
    if (std::memcmp(ent.span, span_d, wire::kDigest) != 0) continue;
    if (std::memcmp(ent.rem, rem_d, wire::kDigest) != 0) continue;
    if (ent.stamp != stamp) {
      // the fleet mutated since this entry was synced: NEVER serve it
      t->stamp_misses++;
      t->misses++;
      return wire::kMiss;
    }
    if ((int64_t)ent.resp.size() > out_cap || out == nullptr) {
      *out_len = (int64_t)ent.resp.size();
      return wire::kGrow;
    }
    std::memcpy(out, ent.resp.data(), ent.resp.size());
    *out_len = (int64_t)ent.resp.size();
    *consumed = total;
    t->hits++;
    t->tick++;
    ent.used = t->tick;
    return wire::kHit;
  }
  t->misses++;
  return wire::kMiss;
}

// Exported shim: unchanged v6 signature/semantics; adds only the v8
// black-box event. kIncomplete/kGrow are retry artifacts (the caller
// re-probes the same request) and are NOT emitted — one serve, one
// event. Event outcome packs {probe rc, verb}: rc * 256 + verb, verb
// 0=filter 1=prioritize 255=undetermined (bypass before route match).
extern "C" int tpushare_wire_probe(void* tp, const uint8_t* req,
                                   int64_t req_len, int64_t stamp,
                                   uint8_t* out, int64_t out_cap,
                                   int64_t* out_len, int64_t* consumed) {
  int64_t span8 = 0, rem8 = 0, verb = 255;
  if (!blackbox::on())
    return wire_probe_impl(tp, req, req_len, stamp, out, out_cap, out_len,
                           consumed, &span8, &rem8, &verb);
  const uint64_t t0 = blackbox::now_ns();
  int rc = wire_probe_impl(tp, req, req_len, stamp, out, out_cap, out_len,
                           consumed, &span8, &rem8, &verb);
  if (rc != wire::kIncomplete && rc != wire::kGrow)
    blackbox::emit(blackbox::kWireProbe, (int64_t)rc * 256 + verb, span8,
                   rem8, t0);
  return rc;
}
