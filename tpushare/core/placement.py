"""Fit checking and chip selection — the scheduler's decision kernel.

Reference behavior being matched (and then extended):

- Fit check (``Assume``, /root/reference/pkg/cache/nodeinfo.go:147-181):
  a pod requesting ``mem`` on ``count`` devices fits a node iff there exist
  ``count`` devices each with ``free >= mem``; ``mem>0 && count==0`` implies
  ``count=1`` (nodeinfo.go:157-159).
- Single-device binpack (``allocateGPUID``, nodeinfo.go:265-308): among
  devices with ``free >= mem`` pick the one with the *least* free memory
  ("min free that fits") so big holes survive for big pods.
- Multi-device allocation (fork's ``allocateGPUIDs``, nodeinfo.go:312-363):
  first-fit N devices each with ``free >= mem``.

TPU-native extension: multi-chip requests are placed on a *contiguous
axis-aligned sub-box* of the host's ICI mesh (2x2 on v5e for count=4) chosen
by a binpack score, rather than any N chips. Scatter placement is kept as an
explicit opt-in fallback (`allow_scatter`) for workloads that do no
inter-chip communication — that mode reproduces the reference fork's
semantics exactly.

The same algorithms exist in C++ (tpushare/core/native/placement.cpp) for
large fleets; `select_chips` transparently uses the native engine when its
shared object is available. Both implementations are covered by the parity
tests in tests/test_native_parity.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from tpushare.core.chips import ChipView
from tpushare.core.topology import MeshTopology


@dataclass(frozen=True)
class PlacementRequest:
    """What one pod asks of one node.

    ``hbm_mib`` is the per-chip HBM request (the reference's per-device
    semantics: each of the N devices must offer the full amount,
    nodeinfo.go:345-350). ``chip_count == 0`` with ``hbm_mib > 0`` is
    normalized to 1 chip. ``hbm_mib == 0`` with ``chip_count > 0`` means
    *exclusive* chips (the whole-device case: only completely-free chips
    qualify). ``topology`` optionally pins the sub-slice shape (e.g. (2, 2));
    ``allow_scatter`` permits non-contiguous fallback. ``mesh_shape`` is
    the SOFT analogue of ``topology``: a declared JAX mesh (e.g. (2, 4))
    that reorders shape enumeration congruent-first without constraining
    what is admissible — ``None`` leaves every decision byte-identical
    to the shape-blind path.
    """

    hbm_mib: int
    chip_count: int = 1
    topology: tuple[int, ...] | None = None
    allow_scatter: bool = False
    mesh_shape: tuple[int, ...] | None = None

    def __post_init__(self) -> None:
        if self.hbm_mib < 0 or self.chip_count < 0:
            raise ValueError("negative request")
        if self.hbm_mib == 0 and self.chip_count == 0:
            raise ValueError("empty request")
        if self.chip_count == 0:
            object.__setattr__(self, "chip_count", 1)
        if self.topology is not None:
            n = 1
            for d in self.topology:
                n *= d
            if n != self.chip_count:
                raise ValueError(
                    f"topology {self.topology} has {n} chips, "
                    f"request asks for {self.chip_count}")
        if self.mesh_shape is not None:
            n = 1
            for d in self.mesh_shape:
                n *= d
            if n != self.chip_count or any(d <= 0 for d in self.mesh_shape):
                raise ValueError(
                    f"mesh_shape {self.mesh_shape} does not cover "
                    f"exactly {self.chip_count} chips")

    @property
    def exclusive(self) -> bool:
        return self.hbm_mib == 0

    def chip_demand_mib(self, chip_total: int) -> int:
        """HBM this request consumes on each selected chip."""
        return chip_total if self.exclusive else self.hbm_mib


@dataclass(frozen=True)
class Placement:
    """A concrete device decision: which chips, what shape, how tight."""

    chip_ids: tuple[int, ...]
    box: tuple[int, ...] | None  # None => scattered (non-contiguous)
    origin: tuple[int, ...] | None = None
    score: int = 0  # lower is better (leftover free HBM on chosen chips)

    @property
    def contiguous(self) -> bool:
        return self.box is not None

    @property
    def adjacency(self) -> int:
        """Fixed-point adjacency quality of this placement (see
        :func:`tpushare.core.topology.adjacency_quality`). A derived
        property, not a field: every placement anywhere in the system —
        memo, arena, gang member — scores identically with no new state
        to keep coherent, and the ABI v7 native scores parity-check
        against this exact computation."""
        from tpushare.core.topology import adjacency_quality
        return adjacency_quality(len(self.chip_ids), self.box)


def _eligible(chip: ChipView, req: PlacementRequest) -> bool:
    if not chip.healthy:
        return False
    if req.exclusive:
        return chip.used_hbm_mib == 0
    return chip.free_hbm_mib >= req.hbm_mib


def fits(chips: Sequence[ChipView], topo: MeshTopology,
         req: PlacementRequest) -> bool:
    """Filter-path predicate: can this node host the request at all?

    Mirrors ``Assume`` (nodeinfo.go:147-181): count chips with enough free
    HBM. For contiguity-required multi-chip requests the existence check
    consults the mesh but stops at the FIRST eligible box — the same
    early-exit bound as the C++ fleet scan (placement.cpp fits_one:
    "existence is enough for Filter"); only the bind path pays the full
    scoring pass.
    """
    if req.chip_count == 1 or req.allow_scatter:
        n = sum(1 for c in chips if _eligible(c, req))
        return n >= req.chip_count

    if len(chips) != topo.num_chips:
        topo = MeshTopology((len(chips),))  # partial host: 1-D fallback
    by_idx = {c.idx: c for c in chips}
    shapes = [req.topology] if req.topology is not None \
        else topo.box_shapes(req.chip_count)
    for box in shapes:
        if len(box) != len(topo.shape):
            continue
        for origin in topo.box_positions(box):
            ids = topo.box_chips(origin, box)
            members = [by_idx[i] for i in ids if i in by_idx]
            if len(members) == len(ids) and \
                    all(_eligible(c, req) for c in members):
                return True
    return False


def select_chips(chips: Sequence[ChipView], topo: MeshTopology,
                 req: PlacementRequest) -> Placement | None:
    """Bind-path selector. Returns the chosen placement or None.

    Single chip: min-free-that-fits binpack (nodeinfo.go:283-286).
    Multi chip: tightest contiguous sub-box; optional scatter fallback
    reproducing the fork's first-fit (nodeinfo.go:312-363) — except ordered
    by the same binpack score instead of device index, which is what drives
    the anti-fragmentation numbers in bench.py.
    """
    from tpushare.core import native  # late import: optional C++ engine
    # native.select_chips itself degrades to select_chips_py when the
    # engine is unavailable or the node isn't ABI-expressible — and
    # COUNTS the fallback (tpushare_native_fallback_total), which a
    # pre-check here would silently bypass
    return native.select_chips(chips, topo, req)


def select_chips_py(chips: Sequence[ChipView], topo: MeshTopology,
                    req: PlacementRequest) -> Placement | None:
    """Pure-Python selection (the behavioral specification)."""
    if len(chips) != topo.num_chips:
        # Node reported fewer chips than its mesh label claims (partial
        # breakage): fall back to a 1-D mesh over what exists.
        topo = MeshTopology((len(chips),))

    if req.chip_count == 1:
        # tie-break on idx so the decision is identical regardless of input
        # order and of which engine (Python/C++) evaluates it
        best: ChipView | None = None
        for c in chips:
            if _eligible(c, req) and (
                    best is None
                    or (c.free_hbm_mib, c.idx) < (best.free_hbm_mib, best.idx)):
                best = c
        if best is None:
            return None
        return Placement((best.idx,), box=(1,) * len(topo.shape),
                         origin=best.coords,
                         score=best.free_hbm_mib - req.chip_demand_mib(best.total_hbm_mib))

    by_idx = {c.idx: c for c in chips}
    if req.topology is not None:
        shapes = [req.topology]
    else:
        shapes = topo.box_shapes(req.chip_count)
        if req.mesh_shape is not None:
            # soft preference: mesh-congruent shape classes first, the
            # compactness order untouched within each group — absent a
            # congruent fit the walk degrades to the shape-blind order
            from tpushare.core.topology import congruent_first
            shapes = congruent_first(shapes, req.mesh_shape)

    best_p: Placement | None = None
    for box in shapes:
        if len(box) != len(topo.shape):
            continue
        for origin in topo.box_positions(box):
            ids = topo.box_chips(origin, box)
            members = [by_idx[i] for i in ids if i in by_idx]
            if len(members) != len(ids):
                continue
            if not all(_eligible(c, req) for c in members):
                continue
            score = sum(
                c.free_hbm_mib - req.chip_demand_mib(c.total_hbm_mib)
                for c in members)
            if best_p is None or score < best_p.score:
                best_p = Placement(tuple(ids), box=box, origin=origin,
                                   score=score)
        if best_p is not None:
            # shapes are ordered most-ICI-compact first; once any position
            # works for the best shape class, don't degrade to stringier
            # boxes just to chase a tighter HBM pack.
            break

    if best_p is not None:
        return best_p

    if req.allow_scatter:
        elig = sorted((c for c in chips if _eligible(c, req)),
                      key=lambda c: (c.free_hbm_mib, c.idx))
        if len(elig) >= req.chip_count:
            chosen = elig[:req.chip_count]
            return Placement(tuple(c.idx for c in chosen), box=None,
                             score=sum(
                                 c.free_hbm_mib - req.chip_demand_mib(c.total_hbm_mib)
                                 for c in chosen))
    return None


# -- fleet metrics (inspect API + bench) ------------------------------------

def utilization_pct(chips: Sequence[ChipView]) -> float:
    """Aggregate allocated-HBM / total-HBM, the BASELINE headline metric."""
    total = sum(c.total_hbm_mib for c in chips)
    if total == 0:
        return 0.0
    return 100.0 * sum(c.used_hbm_mib for c in chips) / total


def fragmentation(chips: Sequence[ChipView]) -> float:
    """1 - (largest single-chip free block / total free HBM).

    0.0 = all free HBM is on one chip (a whole-chip pod could still land);
    approaching 1.0 = free HBM is dust spread across chips that no large
    request can use. This is the quantity the min-free-that-fits binpack
    minimizes, reported via /metrics (SURVEY §6 "chip fragmentation").
    """
    free = [c.free_hbm_mib for c in chips if c.healthy]
    total_free = sum(free)
    if total_free == 0:
        return 0.0
    return 1.0 - max(free) / total_free
