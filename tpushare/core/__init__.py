"""Pure placement domain: chips, mesh topology, fit/binpack/sub-slice selection.

No Kubernetes types anywhere in this package — it is the hermetically testable
core that SURVEY.md §7 stage 1 calls for. The extender's Filter path reduces to
:func:`tpushare.core.placement.fits` and the Bind path to
:func:`tpushare.core.placement.select_chips`.
"""

from tpushare.core.chips import ChipView, node_chips
from tpushare.core.topology import MeshTopology
from tpushare.core.placement import (
    PlacementRequest,
    Placement,
    fits,
    select_chips,
    utilization_pct,
    fragmentation,
)

__all__ = [
    "ChipView",
    "node_chips",
    "MeshTopology",
    "PlacementRequest",
    "Placement",
    "fits",
    "select_chips",
    "utilization_pct",
    "fragmentation",
]
