"""Chip-level state snapshots used by the placement engine.

The reference models one GPU as ``DeviceInfo{idx, totalGPUMem, podMap}``
(/root/reference/pkg/cache/deviceinfo.go:12-22) and computes used memory as the
sum of the pod annotations on that device (deviceinfo.go:41-54). Here the
mutable pod-tracking lives in :mod:`tpushare.cache`; the placement engine only
ever sees immutable :class:`ChipView` snapshots, so the hot fit/select path is
a pure function — trivially testable and portable to the native C++ engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence


@dataclass(frozen=True, slots=True)
class ChipView:
    """Immutable snapshot of one TPU chip at placement time.

    ``coords`` are the chip's ICI-mesh coordinates within its host's topology
    (e.g. ``(1, 2)`` on a v5e 4x4 host) — the TPU-native datum the reference
    has no analogue for; its devices are an unordered 1-D array
    (nodeinfo.go:38-40).
    """

    idx: int
    coords: tuple[int, ...]
    total_hbm_mib: int
    used_hbm_mib: int = 0
    healthy: bool = True
    # HBM held by best-effort-tier pods — evictable under pressure, so
    # guaranteed/burstable admission may count it as headroom when the
    # QoS overcommit knob is active (tpushare/qos/tiers.py). Zero on a
    # fleet that never sets the tier annotation.
    reclaimable_hbm_mib: int = 0

    @property
    def free_hbm_mib(self) -> int:
        return self.total_hbm_mib - self.used_hbm_mib

    def with_used(self, used_hbm_mib: int) -> "ChipView":
        return ChipView(self.idx, self.coords, self.total_hbm_mib,
                        used_hbm_mib, self.healthy,
                        self.reclaimable_hbm_mib)

    def with_healthy(self, healthy: bool) -> "ChipView":
        return ChipView(self.idx, self.coords, self.total_hbm_mib,
                        self.used_hbm_mib, healthy,
                        self.reclaimable_hbm_mib)


class ChipSnapshot(list):
    """A list of :class:`ChipView` that supports weak references and
    identity hashing, so engines can cache marshalled derivatives (e.g.
    the native engine's packed arrays) keyed by the snapshot object
    itself. NodeInfo hands the SAME snapshot out until its state changes,
    making identity a valid cache key. (``list.__hash__`` is None — an
    unhashable key would silently disable WeakKeyDictionary caching.)"""

    __slots__ = ("__weakref__",)

    # identity hash + inherited elementwise __eq__: a hash-bucket
    # collision only "hits" on an equal-content snapshot, whose pack is
    # identical anyway (ChipView coords encode the mesh shape)
    __hash__ = object.__hash__

    # Snapshots are SHARED between callers and cached by identity —
    # in-place mutation would corrupt every holder and the engine pack
    # cache, so the list mutators are disabled.
    def _immutable(self, *args, **kwargs):
        raise TypeError("ChipSnapshot is immutable (shared between "
                        "callers; see NodeInfo.snapshot)")

    append = extend = insert = remove = _immutable
    pop = clear = sort = reverse = _immutable
    __setitem__ = __delitem__ = __iadd__ = __imul__ = _immutable


def node_chips(
    count: int,
    total_hbm_mib_per_chip: int,
    mesh_shape: tuple[int, ...] | None = None,
    used: Sequence[int] | None = None,
    unhealthy: Sequence[int] = (),
) -> list[ChipView]:
    """Build a chip array for one node.

    The reference derives per-device memory as ``node total / device count``
    (nodeinfo.go:38-40) because the device plugin only reports the aggregate;
    our device plugin reports per-chip HBM and topology explicitly, but this
    constructor keeps the same uniform-chip convenience for tests and for
    nodes whose plugin predates topology labels.
    """
    from tpushare.core.topology import MeshTopology

    topo = MeshTopology.for_chip_count(count) if mesh_shape is None \
        else MeshTopology(mesh_shape)
    bad = set(unhealthy)
    return [
        ChipView(
            idx=i,
            coords=topo.coords(i),
            total_hbm_mib=total_hbm_mib_per_chip,
            used_hbm_mib=0 if used is None else used[i],
            healthy=i not in bad,
        )
        for i in range(count)
    ]
