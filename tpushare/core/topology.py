"""ICI mesh topology model.

TPU hosts expose their chips as an ICI mesh (v5e: 2-D, up to 4x4 per host /
16x16 per slice; v5p: 3-D torus). Multi-chip workloads only get full ICI
bandwidth when their chips form a *contiguous axis-aligned sub-box* of the
mesh — four arbitrary chips cannot run an efficient ``psum`` ring. The
reference has no topology concept at all: its multi-GPU allocator picks the
first N devices that fit (nodeinfo.go:312-363). This module supplies the
geometry that upgrades that scalar loop into sub-slice placement.

Everything here is pure data + enumeration; selection policy lives in
:mod:`tpushare.core.placement`.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import lru_cache


@dataclass(frozen=True)
class MeshTopology:
    """An axis-aligned chip mesh of arbitrary rank (1-D, 2-D v5e, 3-D v5p).

    Chip index <-> coordinate mapping is row-major: the last axis varies
    fastest. This matches how libtpu enumerates chips on a host and how
    ``TPU_VISIBLE_CHIPS`` indexes them.
    """

    shape: tuple[int, ...]

    def __post_init__(self) -> None:
        if not self.shape or any(d <= 0 for d in self.shape):
            raise ValueError(f"invalid mesh shape {self.shape!r}")

    # -- index <-> coords ---------------------------------------------------

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def coords(self, idx: int) -> tuple[int, ...]:
        if not 0 <= idx < self.num_chips:
            raise IndexError(f"chip {idx} outside mesh {self.shape}")
        out = []
        for d in reversed(self.shape):
            out.append(idx % d)
            idx //= d
        return tuple(reversed(out))

    def index(self, coords: tuple[int, ...]) -> int:
        if len(coords) != len(self.shape):
            raise ValueError(f"coords {coords} rank != mesh rank {self.shape}")
        idx = 0
        for c, d in zip(coords, self.shape):
            if not 0 <= c < d:
                raise IndexError(f"coords {coords} outside mesh {self.shape}")
            idx = idx * d + c
        return idx

    # -- sub-box enumeration ------------------------------------------------

    def box_shapes(self, count: int) -> list[tuple[int, ...]]:
        """All axis-aligned box shapes with ``count`` chips that fit the mesh,

        most ICI-compact first. Compactness = smaller maximum edge, then
        smaller edge-length spread — a 2x2 beats a 1x4 (shorter all-reduce
        rings, more bisection bandwidth), a 2x2x2 beats a 1x2x4.
        """
        return _box_shapes(self.shape, count)

    def box_positions(self, box: tuple[int, ...]) -> list[tuple[int, ...]]:
        """All origins where ``box`` fits inside the mesh."""
        ranges = [range(d - b + 1) for d, b in zip(self.shape, box)]
        return [tuple(p) for p in itertools.product(*ranges)]

    def box_chips(self, origin: tuple[int, ...], box: tuple[int, ...]) -> list[int]:
        """Chip indices inside the box at ``origin`` (row-major order)."""
        ranges = [range(o, o + b) for o, b in zip(origin, box)]
        return [self.index(c) for c in itertools.product(*ranges)]

    def neighbors(self, idx: int) -> list[int]:
        """ICI-adjacent chip indices (mesh, not torus, within one host)."""
        c = self.coords(idx)
        out = []
        for ax in range(len(self.shape)):
            for delta in (-1, 1):
                n = list(c)
                n[ax] += delta
                if 0 <= n[ax] < self.shape[ax]:
                    out.append(self.index(tuple(n)))
        return out

    # -- parsing ------------------------------------------------------------

    @classmethod
    def from_label(cls, label: str) -> "MeshTopology":
        """Parse a node topology label like ``"4x4"`` or ``"2x2x4"``.

        This is the string the device plugin publishes as the node label
        ``tpushare.aliyun.com/mesh`` (the analogue of the reference reporting
        gpu-count via node capacity, node.go:24-30 — but as *geometry*, not a
        scalar).
        """
        try:
            dims = tuple(int(p) for p in label.lower().split("x"))
        except ValueError:
            raise ValueError(f"bad mesh label {label!r}") from None
        return cls(dims)

    @classmethod
    def for_chip_count(cls, count: int) -> "MeshTopology":
        """Default topology for a host with ``count`` chips and no mesh label.

        Picks the most-square 2-D factorization (v5e-style); 1-D for primes.
        A 4-chip host becomes 2x2, 8 becomes 2x4, 16 becomes 4x4 — matching
        real v5e host shapes.
        """
        if count <= 0:
            raise ValueError("count must be positive")
        best = (1, count)
        for a in range(2, int(count ** 0.5) + 1):
            if count % a == 0:
                best = (a, count // a)
        return cls(best if best[0] > 1 else (count,))

    def label(self) -> str:
        return "x".join(str(d) for d in self.shape)


@dataclass(frozen=True)
class HostMesh:
    """Inter-node adjacency model: hosts as points in a *host grid*.

    A multi-host slice is a chip mesh tiled by identical per-host boxes; the
    device plugin publishes each host's tile origin as the node label
    ``tpushare.aliyun.com/slice-origin`` (``"0x2"`` form). Dividing those
    origins by the uniform host-box dims places every host at an integer
    point of a coarse grid — the geometry a cross-host gang must satisfy:
    its member hosts form a contiguous axis-aligned sub-box of this grid,
    exactly as a single-host placement forms a sub-box of the chip mesh.

    ``grid`` is the host-grid dims, ``hbox`` the uniform per-host chip box,
    ``hosts`` the host names row-major over the grid (last axis fastest),
    matching :class:`MeshTopology` index order so chip-level and host-level
    coordinates compose without translation tables.
    """

    grid: tuple[int, ...]
    hbox: tuple[int, ...]
    hosts: tuple[str, ...]

    def __post_init__(self) -> None:
        n = 1
        for d in self.grid:
            n *= d
        if len(self.hosts) != n:
            raise ValueError(
                f"{len(self.hosts)} hosts cannot tile host grid {self.grid}")

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def mesh(self) -> MeshTopology:
        """The host grid viewed as a (tiny) mesh of host-points."""
        return MeshTopology(self.grid)

    def host_coord(self, name: str) -> tuple[int, ...]:
        return self.mesh.coords(self.hosts.index(name))

    def chip_origin(self, name: str) -> tuple[int, ...]:
        """The host's tile origin in global chip coordinates."""
        return tuple(c * b for c, b in zip(self.host_coord(name), self.hbox))

    def best_eligible_box(self, weight_of) -> int:
        """Max total weight over contiguous host sub-boxes of all-eligible
        hosts (``weight_of(name) > 0``). Powers the adjacency-tier prune in
        :mod:`tpushare.cache.index`: any gang placement's member hosts form
        such a sub-box with >=1 eligible chip each, so a gang whose chip
        demand exceeds this bound cannot fit — regardless of chip geometry.

        2-d grids (every v5e/v5p pod slice) run in O(hosts) via the
        maximal-rectangle histogram scan: weights are positive inside an
        eligible box, so the best box is a MAXIMAL eligible rectangle,
        every one of which surfaces as a stack pop at its bottom row;
        a 2-d prefix sum prices each candidate O(1). This sits on the
        Filter hot path (recomputed per mutated host group), where the
        shapes x positions x cells enumeration was O(hosts^3) — seconds
        per solve at 512 hosts. Other ranks keep the enumeration.
        """
        w = [weight_of(h) for h in self.hosts]
        if len(self.grid) == 2:
            return _best_box_2d(self.grid[0], self.grid[1], w)
        gm = self.mesh
        best = 0
        for shape in itertools.product(*[range(1, d + 1) for d in self.grid]):
            for origin in gm.box_positions(shape):
                total = 0
                for c in itertools.product(
                        *[range(o, o + s) for o, s in zip(origin, shape)]):
                    wt = w[gm.index(c)]
                    if wt <= 0:
                        total = -1
                        break
                    total += wt
                if total > best:
                    best = total
        return best

    @classmethod
    def from_layout(
        cls, layout: dict[str, tuple[tuple[int, ...], tuple[int, ...]]],
    ) -> "HostMesh":
        """Build from ``{host: (chip_origin, chip_shape)}`` as read off the
        slice-origin / mesh node labels. Raises ``ValueError`` when the
        labels do not describe a uniform, aligned, fully-tiled host grid —
        callers treat that as "this slice has no gang geometry" and skip it.
        """
        if not layout:
            raise ValueError("empty slice layout")
        shapes = {shape for _, shape in layout.values()}
        if len(shapes) != 1:
            raise ValueError(f"non-uniform host boxes {sorted(shapes)}")
        hbox = next(iter(shapes))
        rank = len(hbox)
        grid = [0] * rank
        for name, (origin, _) in layout.items():
            if len(origin) != rank:
                raise ValueError(f"host {name}: origin rank != box rank")
            for ax, (o, b) in enumerate(zip(origin, hbox)):
                if o % b:
                    raise ValueError(
                        f"host {name}: origin {origin} not aligned to {hbox}")
                grid[ax] = max(grid[ax], o // b + 1)
        gm = MeshTopology(tuple(grid))
        cells: list[str | None] = [None] * gm.num_chips
        for name, (origin, _) in layout.items():
            idx = gm.index(tuple(o // b for o, b in zip(origin, hbox)))
            if cells[idx] is not None:
                raise ValueError(
                    f"hosts {cells[idx]} and {name} share origin {origin}")
            cells[idx] = name
        if any(c is None for c in cells):
            raise ValueError(f"host grid {tuple(grid)} not fully tiled")
        return cls(tuple(grid), hbox, tuple(cells))  # type: ignore[arg-type]


def _best_box_2d(rows: int, cols: int, w: list[int]) -> int:
    """Max-weight all-positive sub-rectangle of a row-major ``rows x
    cols`` weight grid. Any all-positive rectangle extends to a MAXIMAL
    one with no smaller sum (extensions only add positive weight), and
    every maximal rectangle is popped off the histogram stack at its
    true bottom row with its exact extent — priced O(1) off the prefix
    sum, O(rows * cols) overall."""
    # P[r+1][c+1] = sum of w over rows 0..r, cols 0..c
    pref = [[0] * (cols + 1) for _ in range(rows + 1)]
    for r in range(rows):
        pr, pq = pref[r + 1], pref[r]
        base = r * cols
        for c in range(cols):
            pr[c + 1] = pq[c + 1] + pr[c] - pq[c] + w[base + c]
    best = 0
    heights = [0] * cols  # consecutive all-positive rows ending at row r
    for r in range(rows):
        base = r * cols
        for c in range(cols):
            heights[c] = heights[c] + 1 if w[base + c] > 0 else 0
        stack: list[tuple[int, int]] = []  # (leftmost col, height)
        for c in range(cols + 1):
            h = heights[c] if c < cols else 0
            start = c
            while stack and stack[-1][1] >= h:
                start, sh = stack.pop()
                # maximal candidate: rows [r-sh+1, r] x cols [start, c-1]
                s = pref[r + 1][c] - pref[r - sh + 1][c] \
                    - pref[r + 1][start] + pref[r - sh + 1][start]
                if s > best:
                    best = s
            if h and (not stack or stack[-1][1] < h):
                stack.append((start, h))
    return best


@lru_cache(maxsize=4096)
def _box_shapes(mesh: tuple[int, ...], count: int) -> list[tuple[int, ...]]:
    rank = len(mesh)
    shapes: set[tuple[int, ...]] = set()

    def rec(prefix: list[int], remaining: int, axis: int) -> None:
        if axis == rank - 1:
            if remaining <= mesh[axis]:
                shapes.add(tuple(prefix + [remaining]))
            return
        for d in _divisors(remaining):
            if d <= mesh[axis]:
                rec(prefix + [d], remaining // d, axis + 1)

    if count >= 1:
        rec([], count, 0)

    def compactness(s: tuple[int, ...]) -> tuple[int, int, tuple[int, ...]]:
        # final lexicographic component makes the order fully deterministic
        # (ties must break identically in the native C++ engine)
        return (max(s), max(s) - min(s), s)

    return sorted(shapes, key=compactness)


def _divisors(n: int) -> list[int]:
    out = []
    for d in range(1, n + 1):
        if n % d == 0:
            out.append(d)
    return out


# -- adjacency quality (mesh-aware placement) --------------------------------

# Fixed-point scale of adjacency_quality: scores are integers in
# [0, ADJ_SCALE] so the native engine (int64 arithmetic, no doubles)
# reproduces the Python spec bit-for-bit. -1 is the no-placement
# sentinel, distinct from a legal 0 (fully scattered chips).
ADJ_SCALE = 1_000_000


def box_links(shape: tuple[int, ...]) -> int:
    """Internal ICI links of an axis-aligned chip box: sum over axes of
    ``(d_i - 1) * prod_{j != i} d_j`` — the complement of the discrete
    surface/perimeter. More internal links means shorter collective
    rings and more bisection bandwidth for a JAX Mesh laid out over the
    box; 1-dims contribute zero, so padding a shape with 1s never
    changes its score."""
    n = 1
    for d in shape:
        n *= d
    return sum((d - 1) * (n // d) for d in shape)


@lru_cache(maxsize=4096)
def max_box_links(count: int) -> int:
    """Max of :func:`box_links` over ALL factorizations of ``count``
    (any rank, mesh-independent) — the normalizer that makes adjacency
    quality comparable across nodes with different mesh shapes. The
    native engine mirrors this enumeration exactly."""
    if count <= 1:
        return 0
    best = 0

    def rec(remaining: int, start: int, dims: list[int]) -> None:
        nonlocal best
        d = start
        while d * d <= remaining:
            if remaining % d == 0:
                rec(remaining // d, d, dims + [d])
            d += 1
        best = max(best, box_links(tuple(dims + [remaining])))

    rec(count, 2, [])
    return best


def adjacency_quality(count: int, box: tuple[int, ...] | None) -> int:
    """Fixed-point adjacency score of one placement: ``ADJ_SCALE`` for
    a single chip (nothing to be adjacent to — perfect by definition),
    0 for scatter (``box=None``), else ``box_links`` scaled against the
    best achievable for this chip count. Returns -1 for ``count <= 0``
    (the native engine's no-placement sentinel)."""
    if count <= 0:
        return -1
    if count == 1:
        return ADJ_SCALE
    if box is None:
        return 0
    return box_links(box) * ADJ_SCALE // max_box_links(count)


def congruent(box: tuple[int, ...], mesh_shape: tuple[int, ...]) -> bool:
    """Does the box realize the declared mesh shape (up to axis order
    and 1-dims)? A (4, 2) box serves a ``"2x4"`` Mesh by transposing
    the device array — the geometry, not the orientation, is the
    performance contract."""
    return sorted(d for d in box if d > 1) \
        == sorted(d for d in mesh_shape if d > 1)


def congruent_first(shapes: list[tuple[int, ...]],
                    mesh_shape: tuple[int, ...]) -> list[tuple[int, ...]]:
    """Stable partition of a compactness-ordered shape list: congruent
    shapes first, original order preserved within each group — the
    ordering both :func:`tpushare.core.placement.select_chips_py` and
    the ABI v7 native cycle apply when a pod declares a mesh shape.
    Stability is load-bearing: within each group the first-working-
    shape-class semantics of the shape-blind path are unchanged."""
    hit = [s for s in shapes if congruent(s, mesh_shape)]
    miss = [s for s in shapes if not congruent(s, mesh_shape)]
    return hit + miss


def occupancy_adjacency(coords: list[tuple[int, ...]]) -> int:
    """Adjacency quality of an ALREADY-BOUND allocation, from the chip
    coordinates its annotations pin. Box allocations (the bounding box
    is exactly full) score :func:`adjacency_quality` of that box;
    scattered allocations (holes in the bounding box) score 0, same as
    ``allow_scatter`` placements at selection time. -1 for an empty
    coordinate list. Powers the fleet adjacency scorecard — an
    after-the-fact audit of what Prioritize's blend actually won."""
    if not coords:
        return -1
    rank = len(coords[0])
    box = tuple(max(c[ax] for c in coords) - min(c[ax] for c in coords) + 1
                for ax in range(rank))
    vol = 1
    for d in box:
        vol *= d
    if vol != len(coords):
        return 0  # holes: not a contiguous axis-aligned box
    return adjacency_quality(len(coords), box)


def gang_hop_span(hmesh: HostMesh, names) -> int:
    """Worst-case inter-host ICI hop distance across a gang's member
    hosts: sum over host-grid axes of (coordinate extent - 1). 0 means
    the gang sits on one host; a 2x1 host pair scores 1. The gang
    planner prefers member decompositions minimizing this span when the
    gang declares a mesh shape."""
    coords = [hmesh.host_coord(n) for n in names]
    if not coords:
        return 0
    return sum(max(c[ax] for c in coords) - min(c[ax] for c in coords)
               for ax in range(len(hmesh.grid)))
