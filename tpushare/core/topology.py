"""ICI mesh topology model.

TPU hosts expose their chips as an ICI mesh (v5e: 2-D, up to 4x4 per host /
16x16 per slice; v5p: 3-D torus). Multi-chip workloads only get full ICI
bandwidth when their chips form a *contiguous axis-aligned sub-box* of the
mesh — four arbitrary chips cannot run an efficient ``psum`` ring. The
reference has no topology concept at all: its multi-GPU allocator picks the
first N devices that fit (nodeinfo.go:312-363). This module supplies the
geometry that upgrades that scalar loop into sub-slice placement.

Everything here is pure data + enumeration; selection policy lives in
:mod:`tpushare.core.placement`.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import lru_cache


@dataclass(frozen=True)
class MeshTopology:
    """An axis-aligned chip mesh of arbitrary rank (1-D, 2-D v5e, 3-D v5p).

    Chip index <-> coordinate mapping is row-major: the last axis varies
    fastest. This matches how libtpu enumerates chips on a host and how
    ``TPU_VISIBLE_CHIPS`` indexes them.
    """

    shape: tuple[int, ...]

    def __post_init__(self) -> None:
        if not self.shape or any(d <= 0 for d in self.shape):
            raise ValueError(f"invalid mesh shape {self.shape!r}")

    # -- index <-> coords ---------------------------------------------------

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def coords(self, idx: int) -> tuple[int, ...]:
        if not 0 <= idx < self.num_chips:
            raise IndexError(f"chip {idx} outside mesh {self.shape}")
        out = []
        for d in reversed(self.shape):
            out.append(idx % d)
            idx //= d
        return tuple(reversed(out))

    def index(self, coords: tuple[int, ...]) -> int:
        if len(coords) != len(self.shape):
            raise ValueError(f"coords {coords} rank != mesh rank {self.shape}")
        idx = 0
        for c, d in zip(coords, self.shape):
            if not 0 <= c < d:
                raise IndexError(f"coords {coords} outside mesh {self.shape}")
            idx = idx * d + c
        return idx

    # -- sub-box enumeration ------------------------------------------------

    def box_shapes(self, count: int) -> list[tuple[int, ...]]:
        """All axis-aligned box shapes with ``count`` chips that fit the mesh,

        most ICI-compact first. Compactness = smaller maximum edge, then
        smaller edge-length spread — a 2x2 beats a 1x4 (shorter all-reduce
        rings, more bisection bandwidth), a 2x2x2 beats a 1x2x4.
        """
        return _box_shapes(self.shape, count)

    def box_positions(self, box: tuple[int, ...]) -> list[tuple[int, ...]]:
        """All origins where ``box`` fits inside the mesh."""
        ranges = [range(d - b + 1) for d, b in zip(self.shape, box)]
        return [tuple(p) for p in itertools.product(*ranges)]

    def box_chips(self, origin: tuple[int, ...], box: tuple[int, ...]) -> list[int]:
        """Chip indices inside the box at ``origin`` (row-major order)."""
        ranges = [range(o, o + b) for o, b in zip(origin, box)]
        return [self.index(c) for c in itertools.product(*ranges)]

    def neighbors(self, idx: int) -> list[int]:
        """ICI-adjacent chip indices (mesh, not torus, within one host)."""
        c = self.coords(idx)
        out = []
        for ax in range(len(self.shape)):
            for delta in (-1, 1):
                n = list(c)
                n[ax] += delta
                if 0 <= n[ax] < self.shape[ax]:
                    out.append(self.index(tuple(n)))
        return out

    # -- parsing ------------------------------------------------------------

    @classmethod
    def from_label(cls, label: str) -> "MeshTopology":
        """Parse a node topology label like ``"4x4"`` or ``"2x2x4"``.

        This is the string the device plugin publishes as the node label
        ``tpushare.aliyun.com/mesh`` (the analogue of the reference reporting
        gpu-count via node capacity, node.go:24-30 — but as *geometry*, not a
        scalar).
        """
        try:
            dims = tuple(int(p) for p in label.lower().split("x"))
        except ValueError:
            raise ValueError(f"bad mesh label {label!r}") from None
        return cls(dims)

    @classmethod
    def for_chip_count(cls, count: int) -> "MeshTopology":
        """Default topology for a host with ``count`` chips and no mesh label.

        Picks the most-square 2-D factorization (v5e-style); 1-D for primes.
        A 4-chip host becomes 2x2, 8 becomes 2x4, 16 becomes 4x4 — matching
        real v5e host shapes.
        """
        if count <= 0:
            raise ValueError("count must be positive")
        best = (1, count)
        for a in range(2, int(count ** 0.5) + 1):
            if count % a == 0:
                best = (a, count // a)
        return cls(best if best[0] > 1 else (count,))

    def label(self) -> str:
        return "x".join(str(d) for d in self.shape)


@dataclass(frozen=True)
class HostMesh:
    """Inter-node adjacency model: hosts as points in a *host grid*.

    A multi-host slice is a chip mesh tiled by identical per-host boxes; the
    device plugin publishes each host's tile origin as the node label
    ``tpushare.aliyun.com/slice-origin`` (``"0x2"`` form). Dividing those
    origins by the uniform host-box dims places every host at an integer
    point of a coarse grid — the geometry a cross-host gang must satisfy:
    its member hosts form a contiguous axis-aligned sub-box of this grid,
    exactly as a single-host placement forms a sub-box of the chip mesh.

    ``grid`` is the host-grid dims, ``hbox`` the uniform per-host chip box,
    ``hosts`` the host names row-major over the grid (last axis fastest),
    matching :class:`MeshTopology` index order so chip-level and host-level
    coordinates compose without translation tables.
    """

    grid: tuple[int, ...]
    hbox: tuple[int, ...]
    hosts: tuple[str, ...]

    def __post_init__(self) -> None:
        n = 1
        for d in self.grid:
            n *= d
        if len(self.hosts) != n:
            raise ValueError(
                f"{len(self.hosts)} hosts cannot tile host grid {self.grid}")

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def mesh(self) -> MeshTopology:
        """The host grid viewed as a (tiny) mesh of host-points."""
        return MeshTopology(self.grid)

    def host_coord(self, name: str) -> tuple[int, ...]:
        return self.mesh.coords(self.hosts.index(name))

    def chip_origin(self, name: str) -> tuple[int, ...]:
        """The host's tile origin in global chip coordinates."""
        return tuple(c * b for c, b in zip(self.host_coord(name), self.hbox))

    def best_eligible_box(self, weight_of) -> int:
        """Max total weight over contiguous host sub-boxes of all-eligible
        hosts (``weight_of(name) > 0``). Powers the adjacency-tier prune in
        :mod:`tpushare.cache.index`: any gang placement's member hosts form
        such a sub-box with >=1 eligible chip each, so a gang whose chip
        demand exceeds this bound cannot fit — regardless of chip geometry.

        2-d grids (every v5e/v5p pod slice) run in O(hosts) via the
        maximal-rectangle histogram scan: weights are positive inside an
        eligible box, so the best box is a MAXIMAL eligible rectangle,
        every one of which surfaces as a stack pop at its bottom row;
        a 2-d prefix sum prices each candidate O(1). This sits on the
        Filter hot path (recomputed per mutated host group), where the
        shapes x positions x cells enumeration was O(hosts^3) — seconds
        per solve at 512 hosts. Other ranks keep the enumeration.
        """
        w = [weight_of(h) for h in self.hosts]
        if len(self.grid) == 2:
            return _best_box_2d(self.grid[0], self.grid[1], w)
        gm = self.mesh
        best = 0
        for shape in itertools.product(*[range(1, d + 1) for d in self.grid]):
            for origin in gm.box_positions(shape):
                total = 0
                for c in itertools.product(
                        *[range(o, o + s) for o, s in zip(origin, shape)]):
                    wt = w[gm.index(c)]
                    if wt <= 0:
                        total = -1
                        break
                    total += wt
                if total > best:
                    best = total
        return best

    @classmethod
    def from_layout(
        cls, layout: dict[str, tuple[tuple[int, ...], tuple[int, ...]]],
    ) -> "HostMesh":
        """Build from ``{host: (chip_origin, chip_shape)}`` as read off the
        slice-origin / mesh node labels. Raises ``ValueError`` when the
        labels do not describe a uniform, aligned, fully-tiled host grid —
        callers treat that as "this slice has no gang geometry" and skip it.
        """
        if not layout:
            raise ValueError("empty slice layout")
        shapes = {shape for _, shape in layout.values()}
        if len(shapes) != 1:
            raise ValueError(f"non-uniform host boxes {sorted(shapes)}")
        hbox = next(iter(shapes))
        rank = len(hbox)
        grid = [0] * rank
        for name, (origin, _) in layout.items():
            if len(origin) != rank:
                raise ValueError(f"host {name}: origin rank != box rank")
            for ax, (o, b) in enumerate(zip(origin, hbox)):
                if o % b:
                    raise ValueError(
                        f"host {name}: origin {origin} not aligned to {hbox}")
                grid[ax] = max(grid[ax], o // b + 1)
        gm = MeshTopology(tuple(grid))
        cells: list[str | None] = [None] * gm.num_chips
        for name, (origin, _) in layout.items():
            idx = gm.index(tuple(o // b for o, b in zip(origin, hbox)))
            if cells[idx] is not None:
                raise ValueError(
                    f"hosts {cells[idx]} and {name} share origin {origin}")
            cells[idx] = name
        if any(c is None for c in cells):
            raise ValueError(f"host grid {tuple(grid)} not fully tiled")
        return cls(tuple(grid), hbox, tuple(cells))  # type: ignore[arg-type]


def _best_box_2d(rows: int, cols: int, w: list[int]) -> int:
    """Max-weight all-positive sub-rectangle of a row-major ``rows x
    cols`` weight grid. Any all-positive rectangle extends to a MAXIMAL
    one with no smaller sum (extensions only add positive weight), and
    every maximal rectangle is popped off the histogram stack at its
    true bottom row with its exact extent — priced O(1) off the prefix
    sum, O(rows * cols) overall."""
    # P[r+1][c+1] = sum of w over rows 0..r, cols 0..c
    pref = [[0] * (cols + 1) for _ in range(rows + 1)]
    for r in range(rows):
        pr, pq = pref[r + 1], pref[r]
        base = r * cols
        for c in range(cols):
            pr[c + 1] = pq[c + 1] + pr[c] - pq[c] + w[base + c]
    best = 0
    heights = [0] * cols  # consecutive all-positive rows ending at row r
    for r in range(rows):
        base = r * cols
        for c in range(cols):
            heights[c] = heights[c] + 1 if w[base + c] > 0 else 0
        stack: list[tuple[int, int]] = []  # (leftmost col, height)
        for c in range(cols + 1):
            h = heights[c] if c < cols else 0
            start = c
            while stack and stack[-1][1] >= h:
                start, sh = stack.pop()
                # maximal candidate: rows [r-sh+1, r] x cols [start, c-1]
                s = pref[r + 1][c] - pref[r - sh + 1][c] \
                    - pref[r + 1][start] + pref[r - sh + 1][start]
                if s > best:
                    best = s
            if h and (not stack or stack[-1][1] < h):
                stack.append((start, h))
    return best


@lru_cache(maxsize=4096)
def _box_shapes(mesh: tuple[int, ...], count: int) -> list[tuple[int, ...]]:
    rank = len(mesh)
    shapes: set[tuple[int, ...]] = set()

    def rec(prefix: list[int], remaining: int, axis: int) -> None:
        if axis == rank - 1:
            if remaining <= mesh[axis]:
                shapes.add(tuple(prefix + [remaining]))
            return
        for d in _divisors(remaining):
            if d <= mesh[axis]:
                rec(prefix + [d], remaining // d, axis + 1)

    if count >= 1:
        rec([], count, 0)

    def compactness(s: tuple[int, ...]) -> tuple[int, int, tuple[int, ...]]:
        # final lexicographic component makes the order fully deterministic
        # (ties must break identically in the native C++ engine)
        return (max(s), max(s) - min(s), s)

    return sorted(shapes, key=compactness)


def _divisors(n: int) -> list[int]:
    out = []
    for d in range(1, n + 1):
        if n % d == 0:
            out.append(d)
    return out
