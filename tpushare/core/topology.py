"""ICI mesh topology model.

TPU hosts expose their chips as an ICI mesh (v5e: 2-D, up to 4x4 per host /
16x16 per slice; v5p: 3-D torus). Multi-chip workloads only get full ICI
bandwidth when their chips form a *contiguous axis-aligned sub-box* of the
mesh — four arbitrary chips cannot run an efficient ``psum`` ring. The
reference has no topology concept at all: its multi-GPU allocator picks the
first N devices that fit (nodeinfo.go:312-363). This module supplies the
geometry that upgrades that scalar loop into sub-slice placement.

Everything here is pure data + enumeration; selection policy lives in
:mod:`tpushare.core.placement`.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from functools import lru_cache


@dataclass(frozen=True)
class MeshTopology:
    """An axis-aligned chip mesh of arbitrary rank (1-D, 2-D v5e, 3-D v5p).

    Chip index <-> coordinate mapping is row-major: the last axis varies
    fastest. This matches how libtpu enumerates chips on a host and how
    ``TPU_VISIBLE_CHIPS`` indexes them.
    """

    shape: tuple[int, ...]

    def __post_init__(self) -> None:
        if not self.shape or any(d <= 0 for d in self.shape):
            raise ValueError(f"invalid mesh shape {self.shape!r}")

    # -- index <-> coords ---------------------------------------------------

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    def coords(self, idx: int) -> tuple[int, ...]:
        if not 0 <= idx < self.num_chips:
            raise IndexError(f"chip {idx} outside mesh {self.shape}")
        out = []
        for d in reversed(self.shape):
            out.append(idx % d)
            idx //= d
        return tuple(reversed(out))

    def index(self, coords: tuple[int, ...]) -> int:
        if len(coords) != len(self.shape):
            raise ValueError(f"coords {coords} rank != mesh rank {self.shape}")
        idx = 0
        for c, d in zip(coords, self.shape):
            if not 0 <= c < d:
                raise IndexError(f"coords {coords} outside mesh {self.shape}")
            idx = idx * d + c
        return idx

    # -- sub-box enumeration ------------------------------------------------

    def box_shapes(self, count: int) -> list[tuple[int, ...]]:
        """All axis-aligned box shapes with ``count`` chips that fit the mesh,

        most ICI-compact first. Compactness = smaller maximum edge, then
        smaller edge-length spread — a 2x2 beats a 1x4 (shorter all-reduce
        rings, more bisection bandwidth), a 2x2x2 beats a 1x2x4.
        """
        return _box_shapes(self.shape, count)

    def box_positions(self, box: tuple[int, ...]) -> list[tuple[int, ...]]:
        """All origins where ``box`` fits inside the mesh."""
        ranges = [range(d - b + 1) for d, b in zip(self.shape, box)]
        return [tuple(p) for p in itertools.product(*ranges)]

    def box_chips(self, origin: tuple[int, ...], box: tuple[int, ...]) -> list[int]:
        """Chip indices inside the box at ``origin`` (row-major order)."""
        ranges = [range(o, o + b) for o, b in zip(origin, box)]
        return [self.index(c) for c in itertools.product(*ranges)]

    def neighbors(self, idx: int) -> list[int]:
        """ICI-adjacent chip indices (mesh, not torus, within one host)."""
        c = self.coords(idx)
        out = []
        for ax in range(len(self.shape)):
            for delta in (-1, 1):
                n = list(c)
                n[ax] += delta
                if 0 <= n[ax] < self.shape[ax]:
                    out.append(self.index(tuple(n)))
        return out

    # -- parsing ------------------------------------------------------------

    @classmethod
    def from_label(cls, label: str) -> "MeshTopology":
        """Parse a node topology label like ``"4x4"`` or ``"2x2x4"``.

        This is the string the device plugin publishes as the node label
        ``tpushare.aliyun.com/mesh`` (the analogue of the reference reporting
        gpu-count via node capacity, node.go:24-30 — but as *geometry*, not a
        scalar).
        """
        try:
            dims = tuple(int(p) for p in label.lower().split("x"))
        except ValueError:
            raise ValueError(f"bad mesh label {label!r}") from None
        return cls(dims)

    @classmethod
    def for_chip_count(cls, count: int) -> "MeshTopology":
        """Default topology for a host with ``count`` chips and no mesh label.

        Picks the most-square 2-D factorization (v5e-style); 1-D for primes.
        A 4-chip host becomes 2x2, 8 becomes 2x4, 16 becomes 4x4 — matching
        real v5e host shapes.
        """
        if count <= 0:
            raise ValueError("count must be positive")
        best = (1, count)
        for a in range(2, int(count ** 0.5) + 1):
            if count % a == 0:
                best = (a, count // a)
        return cls(best if best[0] > 1 else (count,))

    def label(self) -> str:
        return "x".join(str(d) for d in self.shape)


@lru_cache(maxsize=4096)
def _box_shapes(mesh: tuple[int, ...], count: int) -> list[tuple[int, ...]]:
    rank = len(mesh)
    shapes: set[tuple[int, ...]] = set()

    def rec(prefix: list[int], remaining: int, axis: int) -> None:
        if axis == rank - 1:
            if remaining <= mesh[axis]:
                shapes.add(tuple(prefix + [remaining]))
            return
        for d in _divisors(remaining):
            if d <= mesh[axis]:
                rec(prefix + [d], remaining // d, axis + 1)

    if count >= 1:
        rec([], count, 0)

    def compactness(s: tuple[int, ...]) -> tuple[int, int, tuple[int, ...]]:
        # final lexicographic component makes the order fully deterministic
        # (ties must break identically in the native C++ engine)
        return (max(s), max(s) - min(s), s)

    return sorted(shapes, key=compactness)


def _divisors(n: int) -> list[int]:
    out = []
    for d in range(1, n + 1):
        if n % d == 0:
            out.append(d)
    return out
