"""Multi-host slice (gang) placement kernel.

A physical v5e-16 is 4 hosts x (2x2) chips forming one 4x4 ICI mesh;
v5p slices tile 3-D meshes the same way. The reference has no multi-host
concept at all (its allocator stops at one node's device array,
nodeinfo.go:312-363); this module places one workload's chips across
host boundaries as an axis-aligned sub-box of the SLICE mesh, expressed
back in each host's local chip numbering so the existing per-node
reserve/bind machinery can execute it. Design: docs/designs/
multihost-gang.md. This kernel is pure and hermetic; the extender wiring
(GangCoordinator, filter/bind verbs, annotation contract, device-plugin
labels) lives in tpushare/cache/gang.py + tpushare/extender/handlers.py.

Scoring note: inter-host links inside a slice are ICI (full bandwidth),
so host crossings cost COORDINATION (kubelets in the gang, failure
blast radius), not bandwidth — hence `hosts_spanned` leads the score
tuple rather than feeding a fake link-cost model. Gangs never span
slices: that would put DCN inside a psum ring.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

from tpushare.core.chips import ChipView
from tpushare.core.placement import Placement, PlacementRequest, _eligible
from tpushare.core.topology import MeshTopology, congruent_first


@dataclass(frozen=True)
class HostBox:
    """One host's axis-aligned share of the slice mesh."""

    origin: tuple[int, ...]
    shape: tuple[int, ...]

    def contains(self, coords: tuple[int, ...]) -> bool:
        return all(o <= c < o + s
                   for c, o, s in zip(coords, self.origin, self.shape))


@dataclass(frozen=True)
class GangPlacement:
    """A cross-host decision: the global box + each host's local share.

    ``per_host`` values are :class:`Placement` objects in the HOST's
    local chip ids/coords — directly consumable by per-node reserve.
    """

    box: tuple[int, ...]
    origin: tuple[int, ...]
    per_host: dict[str, Placement]
    score: int  # leftover free HBM over chosen chips (lower = tighter)

    @property
    def hosts_spanned(self) -> int:
        return len(self.per_host)


class SliceTopology:
    """Global slice mesh + the host boxes that tile it.

    Hosts must exactly tile the mesh with non-overlapping axis-aligned
    boxes (that is how real slices are built: v5e-16 = 2x2 hosts of
    2x2 chips). Chip ids are LOCAL per host (row-major within the host
    box, the device plugin's numbering); this class owns the
    local<->global mapping.
    """

    def __init__(self, mesh: MeshTopology,
                 hosts: Mapping[str, HostBox]) -> None:
        self.mesh = mesh
        self.hosts = dict(hosts)
        covered: dict[tuple[int, ...], str] = {}
        for name, hb in self.hosts.items():
            if len(hb.origin) != len(mesh.shape) \
                    or len(hb.shape) != len(mesh.shape):
                raise ValueError(
                    f"host {name} box rank != mesh rank {mesh.shape}")
            for coords in self._box_coords(hb.origin, hb.shape):
                if any(not 0 <= c < d
                       for c, d in zip(coords, mesh.shape)):
                    raise ValueError(
                        f"host {name} box {hb} exceeds mesh {mesh.shape}")
                if coords in covered:
                    raise ValueError(
                        f"hosts {covered[coords]} and {name} overlap "
                        f"at {coords}")
                covered[coords] = name
        if len(covered) != mesh.num_chips:
            raise ValueError(
                f"host boxes cover {len(covered)} of "
                f"{mesh.num_chips} slice chips — hosts must tile the "
                "mesh exactly")
        self._host_of = covered  # global coords -> host name

    @staticmethod
    def _box_coords(origin, shape):
        import itertools
        return itertools.product(*[range(o, o + s)
                                   for o, s in zip(origin, shape)])

    @classmethod
    def from_host_grid(cls, host_grid: tuple[int, ...],
                       host_box: tuple[int, ...],
                       host_names: Sequence[str]) -> "SliceTopology":
        """The common real-world construction: hosts arranged in a grid,
        each owning an identical box. v5e-16:
        ``from_host_grid((2, 2), (2, 2), ["h0", "h1", "h2", "h3"])``
        -> 4x4 mesh. Host order is row-major over the host grid."""
        if len(host_grid) != len(host_box):
            raise ValueError("host_grid and host_box rank differ")
        n_hosts = 1
        for d in host_grid:
            n_hosts *= d
        if n_hosts != len(host_names):
            raise ValueError(
                f"host grid {host_grid} needs {n_hosts} names, "
                f"got {len(host_names)}")
        mesh = MeshTopology(tuple(g * b for g, b in
                                  zip(host_grid, host_box)))
        grid = MeshTopology(host_grid)
        hosts = {}
        for i, name in enumerate(host_names):
            gcoords = grid.coords(i)
            origin = tuple(g * b for g, b in zip(gcoords, host_box))
            hosts[name] = HostBox(origin, tuple(host_box))
        return cls(mesh, hosts)

    # -- local <-> global ---------------------------------------------------

    def host_of(self, global_coords: tuple[int, ...]) -> str:
        return self._host_of[global_coords]

    def local_topology(self, host: str) -> MeshTopology:
        return MeshTopology(self.hosts[host].shape)

    def to_local(self, host: str,
                 global_coords: tuple[int, ...]) -> tuple[int, ...]:
        hb = self.hosts[host]
        if not hb.contains(global_coords):
            raise ValueError(f"{global_coords} not on host {host}")
        return tuple(c - o for c, o in zip(global_coords, hb.origin))

    def global_view(self, views: Mapping[str, Sequence[ChipView]]
                    ) -> dict[tuple[int, ...], ChipView]:
        """Merge per-host LOCAL snapshots into global-coords -> view.

        A host missing from ``views`` (down, unreported) simply
        contributes no chips — boxes touching it are ineligible, which
        is the correct degraded behavior for gang placement.
        """
        merged: dict[tuple[int, ...], ChipView] = {}
        for host, chips in views.items():
            hb = self.hosts.get(host)
            if hb is None:
                raise ValueError(f"unknown host {host}")
            local = self.local_topology(host)
            for c in chips:
                # trust idx (the device plugin's local numbering); derive
                # global coords from it so a partial snapshot cannot
                # shift later chips
                gcoords = tuple(o + lc for o, lc in
                                zip(hb.origin, local.coords(c.idx)))
                merged[gcoords] = c
        return merged


def fits_gang(slice_topo: SliceTopology,
              views: Mapping[str, Sequence[ChipView]],
              req: PlacementRequest) -> bool:
    """Existence check (Filter path): first eligible box, early exit."""
    return _search_gang(slice_topo, views, req, first_only=True) is not None


def select_gang(slice_topo: SliceTopology,
                views: Mapping[str, Sequence[ChipView]],
                req: PlacementRequest) -> GangPlacement | None:
    """Bind-path gang selector (see module docstring for policy).

    The box SEARCH — the O(shapes x positions x chips) part — runs in
    the native engine when available (placement.cpp
    tpushare_select_gang, same relationship as select_chips /
    select_chips_py); the per-host GangPlacement decomposition always
    runs here. Parity: tests/test_native_parity.py.
    """
    if req.allow_scatter:
        raise ValueError("gangs are contiguous by definition; "
                         "scatter placement is a single-host mode")
    from tpushare.core import native  # late import: optional C++ engine
    merged = slice_topo.global_view(views)
    r = native.select_gang_box(slice_topo, views, req, merged=merged)
    if r != "fallback":
        if r is None:
            return None
        box, origin = r
        coords_list = [
            tuple(o + d for o, d in zip(origin, delta))
            for delta in SliceTopology._box_coords((0,) * len(box), box)]
        return _build_gang(slice_topo, box, origin, coords_list, merged,
                           req)
    return _search_gang(slice_topo, views, req, first_only=False)


def _py_solve_gang(slice_topo: SliceTopology,
                   views: Mapping[str, Sequence[ChipView]],
                   req: PlacementRequest) -> GangPlacement | None:
    """Behavioral spec for the ABI v5 one-shot native gang solve
    (placement.cpp tpushare_solve_gang): the full Python search +
    decomposition, bypassing every native entry point. Parity between
    this and engine.solve_gang over randomized fleets/meshes/gang
    shapes is enforced by tests/test_native_parity.py; byte-identity
    is what lets TPUSHARE_NO_GANG_SOLVE be a pure perf knob."""
    return _search_gang(slice_topo, views, req, first_only=False)


def _search_gang(slice_topo: SliceTopology,
                 views: Mapping[str, Sequence[ChipView]],
                 req: PlacementRequest,
                 first_only: bool) -> GangPlacement | None:
    if req.allow_scatter:
        raise ValueError("gangs are contiguous by definition; "
                         "scatter placement is a single-host mode")
    mesh = slice_topo.mesh
    merged = slice_topo.global_view(views)
    shapes = [req.topology] if req.topology is not None \
        else mesh.box_shapes(req.chip_count)
    if req.mesh_shape is not None and req.topology is None:
        # mesh-declared gangs: congruent global boxes outrank compactness
        # (the same stable partition select_chips_py applies per host) —
        # the member decomposition then hands each host a share of a box
        # the replica's dp x tp Mesh can be laid over without relabeling.
        # Soft preference only: admissibility and the per-shape-class
        # first-fit policy below are unchanged.
        shapes = congruent_first(shapes, req.mesh_shape)

    best: tuple[tuple[int, int, tuple[int, ...]], GangPlacement] | None \
        = None
    for box in shapes:
        if len(box) != len(mesh.shape):
            continue
        for origin in mesh.box_positions(box):
            coords_list = [
                tuple(o + d for o, d in zip(origin, delta))
                for delta in SliceTopology._box_coords(
                    (0,) * len(box), box)]
            members = [merged.get(c) for c in coords_list]
            if any(m is None or not _eligible(m, req) for m in members):
                continue
            placement = _build_gang(slice_topo, box, origin,
                                    coords_list, merged, req)
            if first_only:
                return placement
            key = (placement.hosts_spanned, placement.score,
                   placement.origin)
            if best is None or key < best[0]:
                best = (key, placement)
        if best is not None:
            # shapes come most-ICI-compact first: stop at the first
            # shape class with a placement (same policy as
            # select_chips_py)
            break
    return best[1] if best else None


def _build_gang(slice_topo: SliceTopology, box, origin, coords_list,
                merged, req: PlacementRequest) -> GangPlacement:
    by_host: dict[str, list[tuple[int, ...]]] = {}
    for c in coords_list:
        by_host.setdefault(slice_topo.host_of(c), []).append(c)
    per_host: dict[str, Placement] = {}
    for host, gcoords in by_host.items():
        local = slice_topo.local_topology(host)
        lcoords = [slice_topo.to_local(host, c) for c in gcoords]
        # the host's share of an axis-aligned global box is itself an
        # axis-aligned local box
        lorigin = tuple(min(c[ax] for c in lcoords)
                        for ax in range(len(local.shape)))
        lshape = tuple(max(c[ax] for c in lcoords) - lorigin[ax] + 1
                       for ax in range(len(local.shape)))
        ids = tuple(sorted(local.index(c) for c in lcoords))
        sub_score = sum(
            merged[g].free_hbm_mib - req.chip_demand_mib(
                merged[g].total_hbm_mib)
            for g in gcoords)
        per_host[host] = Placement(ids, box=lshape, origin=lorigin,
                                   score=sub_score)
    # the gang score IS the sum of its per-host shares — one formula,
    # computed once
    return GangPlacement(box=tuple(box), origin=tuple(origin),
                         per_host=per_host,
                         score=sum(p.score for p in per_host.values()))
