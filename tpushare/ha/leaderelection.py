"""Lease-based leader election (client-go leaderelection semantics).

Acquire/renew loop over a single ``coordination.k8s.io/v1`` Lease object
with optimistic concurrency: every transition is an update preconditioned
on the lease's resourceVersion, so two candidates can't both win a term.

A candidate acquires when the lease is absent, expired (renewTime +
duration < now), or already its own; it renews every ``renew_period``
while leading and abdicates (best-effort holder clear) on stop. Followers
poll at ``retry_period``.
"""

from __future__ import annotations

import datetime
import logging
import threading
from typing import Callable

from tpushare.k8s.client import ApiError

log = logging.getLogger("tpushare.ha")

LEASE_NAMESPACE = "kube-system"
LEASE_NAME = "tpushare-schd-extender"


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _fmt(t: datetime.datetime) -> str:
    return t.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


def _parse(raw: str | None) -> datetime.datetime | None:
    if not raw:
        return None
    try:
        return datetime.datetime.strptime(
            raw.rstrip("Z"), "%Y-%m-%dT%H:%M:%S.%f").replace(
            tzinfo=datetime.timezone.utc)
    except ValueError:
        return None


class LeaderElector:
    def __init__(
        self,
        cluster,
        identity: str,
        lease_name: str = LEASE_NAME,
        namespace: str = LEASE_NAMESPACE,
        lease_duration: float = 15.0,
        renew_period: float = 5.0,
        retry_period: float = 2.0,
        on_started_leading: Callable[[], None] | None = None,
        on_stopped_leading: Callable[[], None] | None = None,
    ) -> None:
        self._cluster = cluster
        self.identity = identity
        self.lease_name = lease_name
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        self._on_start = on_started_leading
        self._on_stop = on_stopped_leading
        self._leader = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_renew = 0.0  # monotonic time of last successful write

    # -- public ---------------------------------------------------------------

    def is_leader(self) -> bool:
        return self._leader.is_set()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"tpushare-ha-{self.identity}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # loop is stuck in a slow apiserver call; its in-flight
                # write is suppressed by the _stop checks, but skip the
                # abdication rather than race it
                log.warning("ha: %s election loop did not stop in time",
                            self.identity)
                self._set_leader(False)
                return
        if self._leader.is_set():
            self._set_leader(False)
            self._release()

    # -- loop -----------------------------------------------------------------

    # outcomes of one acquire/renew attempt
    _RENEWED, _LOST, _ERROR = "renewed", "lost", "error"

    def _run(self) -> None:
        import time as _time
        while not self._stop.is_set():
            outcome = self._try_acquire_or_renew()
            if outcome == self._RENEWED:
                self._last_renew = _time.monotonic()
                self._set_leader(True)
                wait = self.renew_period
            elif outcome == self._LOST:
                # someone else holds a live lease: demote immediately
                self._set_leader(False)
                wait = self.retry_period
            else:  # transient apiserver error
                # renew-deadline rule (client-go semantics): a leader that
                # cannot renew within lease_duration MUST step down — a
                # partitioned replica that kept is_leader() true would
                # serve Bind alongside the newly elected leader
                if self.is_leader() and (
                        _time.monotonic() - self._last_renew
                        > self.lease_duration):
                    log.warning("ha: %s renew deadline exceeded; stepping "
                                "down", self.identity)
                    self._set_leader(False)
                wait = self.retry_period
            if self._stop.wait(wait):
                break

    def _set_leader(self, leading: bool) -> None:
        was = self._leader.is_set()
        if leading and not was:
            self._leader.set()
            log.info("ha: %s became leader", self.identity)
            self._fire(self._on_start, "on_started_leading")
        elif not leading and was:
            self._leader.clear()
            log.warning("ha: %s lost leadership", self.identity)
            self._fire(self._on_stop, "on_stopped_leading")

    def _fire(self, cb: Callable[[], None] | None, what: str) -> None:
        """Run a transition callback on its own thread, exception-guarded:
        a slow or failing callback must neither stall lease renewal nor
        kill the election loop (client-go runs OnStartedLeading in its own
        goroutine for the same reason)."""
        if cb is None:
            return

        def safe() -> None:
            try:
                cb()
            except Exception as e:  # noqa: BLE001
                log.error("ha: %s callback failed: %s", what, e)

        threading.Thread(target=safe, name=f"tpushare-ha-cb-{what}",
                         daemon=True).start()

    # -- lease mechanics -------------------------------------------------------

    def _spec(self, acquire_time: str | None = None) -> dict:
        now = _fmt(_now())
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration) or 1,
            "acquireTime": acquire_time or now,
            "renewTime": now,
        }

    def _try_acquire_or_renew(self) -> str:
        try:
            lease = self._cluster.get_lease(self.namespace, self.lease_name)
        except ApiError as e:
            if not e.is_not_found:
                return self._ERROR  # transient; _run applies renew deadline
            if self._stop.is_set():
                return self._LOST
            try:
                self._cluster.create_lease(
                    self.namespace, self.lease_name, self._spec())
                return self._RENEWED
            except ApiError:
                return self._LOST  # lost the creation race

        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        renew = _parse(spec.get("renewTime"))
        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration)
        expired = renew is None or \
            (_now() - renew).total_seconds() > duration
        if holder not in (None, "", self.identity) and not expired:
            return self._LOST  # someone else holds a live lease

        acquire = spec.get("acquireTime") if holder == self.identity else None
        new_spec = self._spec(acquire_time=acquire)
        if self._stop.is_set():
            # stopping: don't renew — a write here could overwrite the
            # abdication stop() is about to perform
            return self._LOST
        try:
            self._cluster.update_lease(
                self.namespace, self.lease_name, new_spec,
                resource_version=(lease.get("metadata") or {})
                .get("resourceVersion"))
            return self._RENEWED
        except ApiError:
            return self._LOST  # optimistic-lock loser

    def _release(self) -> None:
        """Best-effort abdication so the next candidate wins immediately."""
        try:
            lease = self._cluster.get_lease(self.namespace, self.lease_name)
            if (lease.get("spec") or {}).get("holderIdentity") != self.identity:
                return
            spec = dict(lease["spec"])
            spec["holderIdentity"] = ""
            self._cluster.update_lease(
                self.namespace, self.lease_name, spec,
                resource_version=lease["metadata"].get("resourceVersion"))
        except ApiError:
            pass
