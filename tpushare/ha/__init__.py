"""High availability: Lease-based leader election for the extender.

The reference lists scheduler-extender HA as an unimplemented roadmap item
(/root/reference/README.md:80) and deploys a single replica with
``ignorable: false`` — extender downtime blocks all gpu-mem scheduling
(SURVEY §5.3d). tpushare closes that gap: multiple extender replicas run
behind the Service; all of them serve Filter/Inspect from their own
watch-warmed caches, while the Bind verb — the only writer — is gated on
holding a ``coordination.k8s.io/v1`` Lease, the same mechanism
kube-scheduler itself uses for leader election. A non-leader replica
answers binds with a retryable error; the default scheduler retries and
the Service (or the scheduler's own retry) reaches the leader.
"""

from tpushare.ha.leaderelection import LeaderElector

__all__ = ["LeaderElector"]
