"""High availability: leader election and active-active sharding.

The reference lists scheduler-extender HA as an unimplemented roadmap item
(/root/reference/README.md:80) and deploys a single replica with
``ignorable: false`` — extender downtime blocks all gpu-mem scheduling
(SURVEY §5.3d). tpushare closes that gap in two modes:

- **Active-passive** (`leaderelection.py`): multiple replicas behind the
  Service; all serve Filter/Inspect from their own watch-warmed caches,
  while Bind — the only writer — is gated on holding one
  ``coordination.k8s.io/v1`` Lease. Every bind pays a per-node claim CAS.
- **Active-active** (`sharding.py` + `ring.py`): every replica renews its
  own membership lease; a consistent-hash ring deterministically shards
  the fleet over the live members, and each replica binds **lock-free**
  (no claim CAS) within its shard, falling back to the claim-CAS path
  only for cross-shard spillover. This is the ROADMAP item-1 structural
  unlock — aggregate bind throughput scales with replicas.
"""

from tpushare.ha.leaderelection import LeaderElector
from tpushare.ha.ring import HashRing
from tpushare.ha.sharding import ShardMembership

__all__ = ["LeaderElector", "HashRing", "ShardMembership"]
