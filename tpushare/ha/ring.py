"""Consistent-hash ring over node names (the sharding tentpole's map).

An immutable value object: membership changes build a NEW ring rather
than mutating one in place, so readers on the bind path need no lock —
`ShardMembership` swaps the attribute and Python's reference assignment
does the rest. Virtual nodes smooth the shard sizes (with V vnodes per
member the expected imbalance is O(1/sqrt(V)); 64 keeps the worst shard
within a few percent of fair on a 50k-node fleet) and, being a
*consistent* hash, a membership change moves only ~1/N of the fleet —
exactly the nodes whose handover the stamp-revalidation protocol then
guards.

The hash is blake2b-64, not `hash()`: ring ownership must agree across
replicas and restarts, and PYTHONHASHSEED randomizes `hash()` per
process.
"""

from __future__ import annotations

import bisect
import hashlib


def stable_hash(key: str) -> int:
    """64-bit position on the ring; deterministic across processes."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big")


DEFAULT_VNODES = 64


class HashRing:
    """Sorted (point, member) circle; ``owner(name)`` walks clockwise to
    the first vnode at-or-after the name's hash."""

    __slots__ = ("members", "vnodes", "_points", "_owners")

    def __init__(self, members, vnodes: int = DEFAULT_VNODES) -> None:
        self.members: tuple[str, ...] = tuple(sorted(set(members)))
        self.vnodes = max(1, int(vnodes))
        points: list[tuple[int, str]] = []
        for m in self.members:
            for i in range(self.vnodes):
                points.append((stable_hash(f"{m}#{i}"), m))
        points.sort()
        self._points = [p for p, _m in points]
        self._owners = [m for _p, m in points]

    def owner(self, name: str) -> str | None:
        """The member owning ``name`` (None on an empty ring)."""
        if not self._owners:
            return None
        i = bisect.bisect_right(self._points, stable_hash(name))
        if i == len(self._owners):
            i = 0  # wrap past the last vnode to the ring's start
        return self._owners[i]

    def leader(self) -> str | None:
        """Deterministic ring-wide singleton seat (lowest identity):
        every replica computes the same answer from the same membership,
        no extra election round. Gates the defrag controller."""
        return self.members[0] if self.members else None

    def shard_sizes(self, names) -> dict[str, int]:
        """Owned-node count per member over ``names`` (inspect surface)."""
        sizes = {m: 0 for m in self.members}
        for n in names:
            o = self.owner(n)
            if o is not None:
                sizes[o] += 1
        return sizes

    def describe(self) -> dict:
        return {"members": list(self.members), "vnodes": self.vnodes,
                "points": len(self._points)}
