"""Owner forwarding: route a request to the replica that owns it.

Active-active sharding (ha/sharding.py) made binds lock-free *on the
owning replica*, but the kube-scheduler webhook sprays requests across
replicas blindly — in an N-replica fleet (N-1)/N of binds land off-shard
and pay the claim-CAS spillover path (+2 apiserver round-trips) as a
steady-state cost. This module turns that steady state into a rare-race
fallback: a request landing on a non-owner hops ONCE, replica-to-replica,
to the shard owner (peer addresses discovered from the shard leases) and
the owner's verdict is relayed verbatim.

Loop guard: the hop carries ``X-Tpushare-Forwarded: <origin identity>``.
A request that already hopped is NEVER forwarded again — during a
rebalance two replicas may briefly disagree about ownership, and without
the guard they would ping-pong the request until the webhook timeout.
Instead the receiver serves locally: if its ring agrees it owns the
target that is the normal ``served`` outcome; if it disagrees
(``loop_fallback``) the bind simply degrades to the claim-CAS spillover
path, which is mutual-exclusion-safe against any concurrent writer — the
exact fallback PR 10 proved. Forwarding is therefore an optimization
layered ON TOP of the safety protocol, never a replacement for it.

Transport failures (dead peer, open per-peer breaker) are counted
``peer_failed`` and also degrade to the local CAS — a forward must never
make a bind less available than not forwarding.

What forwards: Bind, keyed on the ring owner of the target node, on by
default when sharding is live and the owner advertised an address
(``TPUSHARE_FORWARD=0`` disables). Filter/Prioritize forwarding — keyed
on the pod, so a pod's whole cycle runs on one replica and its Filter
verdict warms the owner's caches — is opt-in via
``TPUSHARE_FORWARD_CYCLE=1``: a Filter verdict is a cache read every
replica can serve, so the extra hop only pays off when cycle affinity
matters more than a round-trip.
"""

from __future__ import annotations

import logging
import os

from tpushare.ha.sharding import SHARD_FORWARDS
from tpushare.k8s.client import ApiError
from tpushare.k8s.peer import PeerPool

log = logging.getLogger("tpushare.ha")

FORWARD_HEADER = "X-Tpushare-Forwarded"


class ForwardRouter:
    """Per-replica forwarding decision + transport.

    ``maybe_forward`` returns the peer's ``(status, body_bytes)`` when
    the request was handed to the shard owner, or ``None`` when it must
    be served locally (we own it, forwarding is off, no peer address,
    the loop guard is set, or the peer hop failed).
    """

    def __init__(self, sharding, pool: PeerPool | None = None,
                 enabled: bool | None = None,
                 cycle: bool | None = None) -> None:
        self._sharding = sharding
        self._pool = pool or PeerPool()
        if enabled is None:
            enabled = os.environ.get("TPUSHARE_FORWARD", "1") != "0"
        if cycle is None:
            cycle = os.environ.get("TPUSHARE_FORWARD_CYCLE", "0") == "1"
        self.enabled = enabled
        self.cycle = cycle

    # -- routing keys ---------------------------------------------------------

    @staticmethod
    def _route_key(route: str, args: dict) -> str | None:
        """The string whose ring owner should serve this request."""
        if route == "bind":
            return args.get("Node") or None
        # filter/prioritize: key the pod so its whole cycle has one home
        meta = (args.get("Pod") or {}).get("metadata") or {}
        name = meta.get("name")
        if not name:
            return None
        return f"{meta.get('namespace', 'default')}/{name}"

    # -- the decision ---------------------------------------------------------

    def maybe_forward(self, route: str, path: str, body: bytes,
                      args: dict, forwarded_from: str | None
                      ) -> tuple[int, bytes] | None:
        sm = self._sharding
        if sm is None or not sm.is_live():
            return None
        if route == "bind":
            if not self.enabled:
                return None
        elif not (self.enabled and self.cycle):
            return None
        key = self._route_key(route, args)
        if key is None:
            return None
        owner = sm.owner_of(key)
        if forwarded_from is not None:
            # already hopped once: serve locally no matter what. Ring
            # agreement is the normal case (served); disagreement is the
            # mid-rebalance window (loop_fallback) and the claim CAS
            # underneath keeps it safe.
            SHARD_FORWARDS.inc("served" if owner == sm.identity
                               else "loop_fallback")
            return None
        if owner is None or owner == sm.identity:
            return None
        url = sm.peer_url(owner)
        if url is None:
            return None  # owner never advertised (mixed-version fleet)
        try:
            status, data = self._pool.forward(
                url, path, body, {FORWARD_HEADER: sm.identity})
        except ApiError as e:
            SHARD_FORWARDS.inc("peer_failed")
            log.warning("forward %s %s -> %s failed (%s); serving "
                        "locally via claim CAS", route, key, owner, e)
            return None
        SHARD_FORWARDS.inc("forwarded")
        return status, data
