"""Active-active shard membership: N replicas, one lease each.

The leader-election machinery (`leaderelection.py`) generalized from one
contested lease to N uncontested ones: every extender replica renews its
OWN Lease (``tpushare-schd-shard-<identity>``) and lists the others, so
the live membership is simply "every shard lease whose holder is set and
whose renewTime has not expired". Membership feeds an immutable
:class:`~tpushare.ha.ring.HashRing`; each replica deterministically owns
the shard of node names the ring hashes to it and schedules those
**lock-free** — no per-node claim CAS — while cross-shard spillover
falls back to the claim-CAS path the active-passive design already
proved safe.

Safety protocol (the part that makes lock-free correct):

- **Self step-down.** A replica that cannot renew its own lease within
  ``lease_duration`` stops claiming ownership entirely (``live`` drops,
  ``is_owned`` answers False for everything): by then the others have
  expired it from membership and re-own its shard, and a partitioned
  stale owner binding lock-free alongside the new owner is exactly the
  split-brain the lease TTL exists to prevent. Its binds degrade to the
  claim-CAS spillover path, which is mutual-exclusion-safe against any
  other writer.
- **Handover revalidation.** A rebalance hands this replica nodes whose
  recent history it did not schedule (the previous owner may still have
  a bind in flight). Each newly owned node enters a pending set with its
  current generation stamp; ``owns_for_bind`` promotes it to lock-free
  only once a later check sees the stamp UNCHANGED — i.e. the node
  provably quiesced across the observation gap. Until then binds keep
  the claim CAS (counted ``spillover``), so a straggler write from the
  old owner can race nothing.

Lock discipline: ``self._lock`` is LEFTMOST in the documented order (see
tests/test_lock_order_lint.py) — it guards only the membership/ring/
pending bookkeeping and is never held across lease I/O, a solve, or a
bind. The ring itself is immutable and swapped by reference, so the
bind-path reads (`is_owned`, `owner_of`) are plain attribute loads.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable

from tpushare.ha.leaderelection import (
    LEASE_NAMESPACE, _fmt, _now, _parse)
from tpushare.ha.ring import DEFAULT_VNODES, HashRing
from tpushare.k8s.client import ApiError
from tpushare.metrics import Counter, LabeledCounter

log = logging.getLogger("tpushare.ha")

SHARD_LEASE_PREFIX = "tpushare-schd-shard-"

# Per-bind ownership outcomes: `owned` binds skipped the claim CAS
# entirely (the restored plain path), `spillover` kept it (foreign or
# not-yet-revalidated node), `cas_lost` is the subset of spillover binds
# that actually lost the CAS to another writer. Sustained cas_lost
# growth = replicas fighting over the same nodes (ring churn, or a
# workload whose only fits are off-shard) — see docs/ops.md.
SHARD_CONFLICTS = LabeledCounter(
    "tpushare_shard_conflicts_total",
    "Bind-path shard ownership outcomes "
    "(owned = lock-free, spillover = claim-CAS fallback, "
    "cas_lost = spillover bind that lost the CAS)",
    ("outcome",))

RING_REBALANCES = Counter(
    "tpushare_ring_rebalances_total",
    "Consistent-hash ring rebuilds on membership change (join, leave, "
    "lease expiry). Each rebalance re-routes ~1/N of the fleet and "
    "re-arms stamp revalidation for the handed-over nodes")

# Owner-forwarding attribution (ha/forward.py): `forwarded` = this
# replica handed the request to the shard owner over the peer hop,
# `served` = this replica answered a request a peer forwarded to it,
# `loop_fallback` = a forwarded request arrived at a replica that does
# NOT think it owns the target (mid-rebalance ring disagreement) — the
# loop guard stops a second hop and the bind degrades to the claim-CAS
# spillover path, `peer_failed` = the forward transport failed (dead
# peer, open peer breaker) and the bind fell back to the local CAS.
SHARD_FORWARDS = LabeledCounter(
    "tpushare_shard_forwards_total",
    "Owner-forwarded requests by outcome (forwarded = sent to the "
    "shard owner, served = answered a peer's forward, loop_fallback = "
    "forward arrived off-owner and degraded to the claim CAS, "
    "peer_failed = transport failed and the bind ran locally)",
    ("outcome",))


class ShardMembership:
    """One replica's view of the active-active membership.

    ``cluster`` needs get/create/update/list_leases; ``cache`` (optional
    but wired in production) provides node names + stamps for handover
    revalidation and receives ownership refreshes for its owned-subset
    views (index / eqclass / arena).
    """

    def __init__(
        self,
        cluster,
        identity: str,
        cache=None,
        namespace: str = LEASE_NAMESPACE,
        lease_duration: float = 15.0,
        renew_period: float = 5.0,
        retry_period: float = 2.0,
        vnodes: int | None = None,
        on_rebalance: Callable[[], None] | None = None,
        advertise_url: str | None = None,
    ) -> None:
        self._cluster = cluster
        self.identity = identity
        # Peer address book: when set, the advertise URL rides INSIDE
        # holderIdentity ("<identity> <url>") so discovery needs nothing
        # beyond the lease listing every replica already does. Settable
        # after construction (the server's bound port is only known once
        # it starts) but before start().
        self.advertise_url = advertise_url
        self._peers: dict[str, str] = {}  # swapped whole, read lock-free
        self.lease_name = SHARD_LEASE_PREFIX + identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        self._cache = cache
        self._on_rebalance = on_rebalance
        if vnodes is None:
            vnodes = int(os.environ.get("TPUSHARE_SHARD_VNODES",
                                        DEFAULT_VNODES))
        self.vnodes = max(1, vnodes)
        # _ring/_live are swapped whole (reference assignment) so the
        # bind path reads them without the membership lock
        self._ring: HashRing | None = None
        self._live = False
        self._lock = threading.Lock()  # LEFTMOST: bookkeeping only
        self._members: tuple[str, ...] = ()
        self._pending: dict[str, tuple[int, int] | None] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_renew = 0.0

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"tpushare-shard-{self.identity}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._live = False
        if self._thread:
            self._thread.join(timeout=5)
        self._release()

    def _release(self) -> None:
        """Best-effort holder clear so peers expire us immediately
        instead of after a full TTL."""
        try:
            lease = self._cluster.get_lease(self.namespace, self.lease_name)
            spec = dict(lease.get("spec") or {})
            holder = (spec.get("holderIdentity") or "").split()
            if not holder or holder[0] != self.identity:
                return
            spec["holderIdentity"] = ""
            self._cluster.update_lease(
                self.namespace, self.lease_name, spec,
                resource_version=(lease.get("metadata") or {})
                .get("resourceVersion"))
        except ApiError:
            pass

    # -- bind-path reads (lock-free) ------------------------------------------

    def members(self) -> tuple[str, ...]:
        return self._members

    def is_live(self) -> bool:
        return self._live

    def is_owned(self, node_name: str) -> bool:
        """Ring says this replica owns the node (ignores the pending
        handover state — use :meth:`owns_for_bind` on the bind path)."""
        ring = self._ring
        return self._live and ring is not None \
            and ring.owner(node_name) == self.identity

    def owner_of(self, node_name: str) -> str | None:
        ring = self._ring
        return None if ring is None else ring.owner(node_name)

    def peer_url(self, identity: str) -> str | None:
        """Advertised base URL of a live member, or None when the
        member never advertised one (or has expired)."""
        return self._peers.get(identity)

    def peers(self) -> dict[str, str]:
        return dict(self._peers)

    def is_ring_leader(self) -> bool:
        """Deterministic fleet-wide singleton seat (lowest live member):
        gates the defrag controller so exactly one planner runs."""
        ring = self._ring
        return self._live and ring is not None \
            and ring.leader() == self.identity

    def owns_for_bind(self, node_name: str) -> bool:
        """True iff a bind on ``node_name`` may skip the claim CAS:
        owned by the ring AND past handover revalidation.

        A pending node is promoted when its generation stamp is
        UNCHANGED since the last observation — the node quiesced across
        the gap, so no straggler write from the previous owner is in
        flight. A moved stamp re-arms the check with the new stamp and
        keeps this bind on the CAS path (safe, merely slower).
        """
        if not self.is_owned(node_name):
            return False
        with self._lock:
            if node_name not in self._pending:
                return True
            recorded = self._pending[node_name]
        current = self._stamp(node_name)
        with self._lock:
            if node_name not in self._pending:
                return True  # a concurrent check already promoted it
            if recorded is not None and current == recorded:
                del self._pending[node_name]
                return True
            self._pending[node_name] = current
        return False

    def note_bound(self, node_name: str) -> None:
        """A bind by THIS replica just mutated ``node_name``. Our own
        write is not a straggler from the previous owner, yet it moves
        the generation stamp exactly like one — without this hook a
        node under sustained bind traffic re-arms on every check and
        never leaves the CAS path. Re-recording the post-bind stamp
        keeps the quiesce window honest (any foreign write landing
        after it still moves the stamp and re-arms) while letting the
        next check promote."""
        with self._lock:
            if node_name not in self._pending:
                return
        current = self._stamp(node_name)
        with self._lock:
            if node_name in self._pending:
                self._pending[node_name] = current

    def _stamp(self, node_name: str) -> tuple[int, int] | None:
        if self._cache is None:
            return None
        info = self._cache.peek_node(node_name)
        return None if info is None else info.version

    # -- membership loop ------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            ok = self._renew_own_lease()
            if ok:
                self._last_renew = time.monotonic()
            elif self._live and (time.monotonic() - self._last_renew
                                 > self.lease_duration):
                # self step-down: peers have expired us by now and
                # re-own our shard; claiming ownership any longer would
                # be the lock-free split-brain the TTL exists to prevent
                log.warning("shard: %s renew deadline exceeded; dropping "
                            "ownership", self.identity)
                self._live = False
            try:
                members = self._list_members()
            except ApiError:
                members = None  # keep the last view; expiry is peer-side
            if members is not None:
                self._apply_membership(members)
            if self._stop.wait(self.renew_period if ok
                               else self.retry_period):
                break

    def _renew_own_lease(self) -> bool:
        now = _fmt(_now())
        holder = self.identity
        if self.advertise_url:
            holder = f"{self.identity} {self.advertise_url}"
        spec = {
            "holderIdentity": holder,
            "leaseDurationSeconds": int(self.lease_duration) or 1,
            "acquireTime": now,
            "renewTime": now,
        }
        try:
            lease = self._cluster.get_lease(self.namespace, self.lease_name)
        except ApiError as e:
            if not e.is_not_found:
                return False
            try:
                self._cluster.create_lease(
                    self.namespace, self.lease_name, spec)
                return True
            except ApiError:
                return False  # creation raced (stale previous self)
        old = lease.get("spec") or {}
        old_holder = (old.get("holderIdentity") or "").split()
        if old.get("acquireTime") and old_holder \
                and old_holder[0] == self.identity:
            spec["acquireTime"] = old["acquireTime"]
        try:
            self._cluster.update_lease(
                self.namespace, self.lease_name, spec,
                resource_version=(lease.get("metadata") or {})
                .get("resourceVersion"))
            return True
        except ApiError:
            return False

    def _list_members(self) -> list[str]:
        """Live shard members: every ``tpushare-schd-shard-*`` lease
        with a holder and an unexpired renewTime. A holder of the form
        ``"<identity> <url>"`` also advertises the replica's peer
        address; the URLs land in the peer address book
        (:meth:`peer_url`), the returned membership stays plain
        identities."""
        members = []
        peers: dict[str, str] = {}
        for lease in self._cluster.list_leases(self.namespace):
            name = (lease.get("metadata") or {}).get("name") or ""
            if not name.startswith(SHARD_LEASE_PREFIX):
                continue
            spec = lease.get("spec") or {}
            tokens = (spec.get("holderIdentity") or "").split()
            if not tokens:
                continue  # released / abdicated
            renew = _parse(spec.get("renewTime"))
            duration = float(spec.get("leaseDurationSeconds")
                             or self.lease_duration)
            if renew is None or \
                    (_now() - renew).total_seconds() > duration:
                continue  # expired: the replica died or partitioned
            members.append(tokens[0])
            if len(tokens) > 1:
                peers[tokens[0]] = tokens[1]
        self._peers = peers
        return sorted(set(members))

    def _apply_membership(self, members: list[str]) -> None:
        in_ring = self.identity in members
        prev_ring = self._ring
        with self._lock:
            changed = tuple(members) != self._members
        if not changed:
            self._live = in_ring
            return
        new_ring = HashRing(members, vnodes=self.vnodes)
        # arm handover revalidation BEFORE publishing the new ring:
        # a bind must never see a newly-owned node as plain-owned
        # without passing through the pending set
        pending: dict[str, tuple[int, int] | None] = {}
        if self._cache is not None and in_ring:
            for name in self._cache.node_names():
                if new_ring.owner(name) != self.identity:
                    continue
                if prev_ring is not None and self._live and \
                        prev_ring.owner(name) == self.identity:
                    continue  # continuously owned: no handover happened
                pending[name] = self._stamp(name)
        with self._lock:
            self._members = tuple(members)
            # carry over still-unrevalidated nodes we still own
            for name, st in self._pending.items():
                if new_ring.owner(name) == self.identity \
                        and name not in pending:
                    pending[name] = st
            self._pending = pending
        self._ring = new_ring
        self._live = in_ring
        RING_REBALANCES.inc()
        log.info("shard: %s ring rebalanced to %d member(s) %s "
                 "(%d node(s) pending revalidation)", self.identity,
                 len(members), members, len(pending))
        if self._cache is not None and \
                hasattr(self._cache, "set_ownership"):
            # refresh the owned-subset views (index summaries, arena
            # residency); runs outside self._lock — it takes cache locks
            self._cache.set_ownership(self.is_owned if in_ring else None)
        if self._on_rebalance is not None:
            try:
                self._on_rebalance()
            except Exception as e:  # noqa: BLE001
                log.error("shard: on_rebalance callback failed: %s", e)

    # -- observability --------------------------------------------------------

    def owned_count(self) -> int:
        if self._cache is None or not self._live:
            return 0
        ring = self._ring
        if ring is None:
            return 0
        return sum(1 for n in self._cache.node_names()
                   if ring.owner(n) == self.identity)

    def snapshot(self) -> dict:
        """The /inspect/ring payload."""
        ring = self._ring
        with self._lock:
            members = list(self._members)
            pending = len(self._pending)
        names = self._cache.node_names() if self._cache is not None else []
        sizes = ring.shard_sizes(names) if ring is not None else {}
        return {
            "identity": self.identity,
            "live": self._live,
            "ring_leader": ring.leader() if ring is not None else None,
            "members": members,
            "vnodes": self.vnodes,
            "lease_duration_s": self.lease_duration,
            "shard_sizes": sizes,
            "owned_nodes": sizes.get(self.identity, 0),
            "pending_revalidation": pending,
            "rebalances_total": RING_REBALANCES.value,
            "conflicts": {
                "owned": SHARD_CONFLICTS.get("owned"),
                "spillover": SHARD_CONFLICTS.get("spillover"),
                "cas_lost": SHARD_CONFLICTS.get("cas_lost"),
            },
            "advertise_url": self.advertise_url,
            "peers": dict(self._peers),
            "forwards": {
                "forwarded": SHARD_FORWARDS.get("forwarded"),
                "served": SHARD_FORWARDS.get("served"),
                "loop_fallback": SHARD_FORWARDS.get("loop_fallback"),
                "peer_failed": SHARD_FORWARDS.get("peer_failed"),
            },
        }

    def attach(self, registry) -> None:
        registry.register(SHARD_CONFLICTS)
        registry.register(RING_REBALANCES)
        registry.register(SHARD_FORWARDS)
        registry.gauge_func(
            "tpushare_shard_owned_nodes",
            "Nodes this replica's ring shard currently owns (0 while "
            "not live in the membership)",
            lambda: [("", float(self.owned_count()))])
