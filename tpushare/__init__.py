"""tpushare — TPU-native fine-grained accelerator-sharing scheduler.

A from-scratch Kubernetes scheduler-extender framework with the capabilities of
the GPU Share Scheduler Extender (mengwanguc/gpushare-scheduler-extender),
re-designed for TPU hosts:

- Pods request HBM megabytes (``aliyun.com/tpu-hbm``) and/or chip counts
  (``aliyun.com/tpu-count``) instead of whole devices.
- The extender performs per-chip fit checking and binpack placement
  (reference: pkg/cache/nodeinfo.go), with ICI-mesh-topology awareness so
  multi-chip requests land on *contiguous* sub-slices — the TPU-native
  generalization of the reference fork's multi-GPU allocator
  (nodeinfo.go:312-363).
- A device plugin enumerates chips (libtpu / /dev/accel scan; reference uses
  NVML, designs.md:59) and injects ``TPU_VISIBLE_CHIPS`` + HBM-limit env vars
  at container start (reference injects NVIDIA_VISIBLE_DEVICES,
  designs.md:95-101).
- Pod annotations carry the placement decision between extender and device
  plugin; all state is crash-rebuildable from the apiserver
  (reference: pkg/cache/cache.go:49-74).

Layer map (mirrors SURVEY.md §1):

====================  =========================================================
``tpushare.extender`` HTTP wire protocol + routes (reference pkg/routes,
                      pkg/scheduler)
``tpushare.cache``    SchedulerCache / NodeInfo / ChipInfo state layer
                      (reference pkg/cache)
``tpushare.controller`` informer-style sync loop (reference pkg/gpushare)
``tpushare.core``     pure placement domain: mesh topology, fit, binpack,
                      contiguous sub-slice selection (+ native C++ engine)
``tpushare.contract`` extended-resource names + annotation codec
                      (reference pkg/utils)
``tpushare.k8s``      minimal cluster client (fake + in-cluster stdlib HTTP)
``tpushare.deviceplugin`` node agent: chip enumeration, kubelet Allocate
                      rendezvous (reference sibling repo, designs.md:53-101)
``tpushare.workloads`` JAX serving workloads that run under the HBM limits the
                      plugin injects (samples/ analogue)
====================  =========================================================
"""

__version__ = "0.1.0"
