"""Capacity-planning simulator for TPU-share fleets.

The reference verifies its binpack behavior with two demo videos
(README.md:64-70) and nothing else; operators get no way to answer "what
utilization will MY workload mix reach on N hosts?" before buying them.
This package answers that offline: a discrete-event simulator drives the
real placement kernel (:mod:`tpushare.core.placement` — the same code the
extender serves) over a synthetic or recorded workload trace and reports
time-weighted utilization, fragmentation, and rejection rates per policy.

CLI: ``python -m tpushare.sim --help``.
"""

from tpushare.sim.engine_loop import LoopKnobs, run_sim_native
from tpushare.sim.simulator import (
    POLICIES, Fleet, SimReport, TraceSpec, run_sim, synth_trace)
from tpushare.sim.traces import (
    DEFAULT_TIERS, DiurnalSpec, FaultEvent, FaultSpec, PodTier,
    SpikeWindow, synth_diurnal, synth_faults, synth_fleet)

__all__ = ["DEFAULT_TIERS", "DiurnalSpec", "FaultEvent", "FaultSpec",
           "Fleet", "LoopKnobs", "POLICIES", "PodTier", "SimReport",
           "SpikeWindow", "TraceSpec", "run_sim", "run_sim_native",
           "synth_diurnal", "synth_faults", "synth_fleet",
           "synth_trace"]
