"""Tiered QoS wind tunnel: HBM oversubscription under eviction pressure.

The classic wind tunnel treats every pod as one class; this module
replays the same discrete-event loop with the QoS subsystem's admission
arithmetic (``NodeInfo._qos_views``) and eviction policy
(``NodeInfo.pressure_victim`` + the pressure monitor's budget governor)
so the oversubscription design can be measured before it touches a
fleet:

- **best-effort** pods borrow idle HBM up to ``int(hbm * overcommit)``
  per chip — they may push a chip's grant sum past physical.
- **guaranteed / burstable** pods admit against physical HBM but count
  best-effort bytes as *reclaimable* (the pressure monitor will evict
  the borrowers), still bounded by the overcommit ceiling.
- **pressure** — a chip whose grant sum exceeds physical HBM while
  non-best-effort usage is present — triggers eviction of the smallest
  best-effort entry clearing the whole overage (else the largest),
  governed by a sliding-window budget exactly like the live monitor.
  Evicted pods restart: full duration, wait keyed to original arrival,
  so eviction cost lands in the best-effort wait tail.

Both invariants the chaos drill asserts hold *by admission*, so the sim
samples them at every event and reports violation counts that must be
zero: non-best-effort bytes never exceed physical HBM on any chip
(guaranteed isolation), and no chip's grant sum ever exceeds the
declared overcommit bound.

At ``overcommit <= 1.0`` the loop degrades to single-class physical
admission with zero evictions — the baseline the pinned gate compares
against: the tiered run must buy utilization *without* degrading the
guaranteed tier's wait tail (tests/test_wind_tunnel_gate.py).

Everything is a pure function of (fleet, trace, knobs) — no wall
clock, no ambient randomness — so the golden is byte-reproducible.
Re-pinning is deliberate: ``python -m tpushare.sim --qos --pin``.
"""

from __future__ import annotations

import heapq
import json
import os
from dataclasses import dataclass, field

from tpushare.sim.simulator import Fleet, SimPod, _p99
from tpushare.sim.traces import DiurnalSpec, PodTier, synth_diurnal

BEST_EFFORT = "best-effort"
GUARANTEED = "guaranteed"

# The gate mix: guaranteed inference replicas with fast churn, a
# burstable middle, and long-squatting best-effort batch scavengers —
# the workload shape oversubscription exists for. Weights keep the
# fleet saturated at the diurnal peak so the overcommit headroom is
# actually contended (an idle fleet proves nothing).
QOS_GATE_TIERS: tuple[PodTier, ...] = (
    PodTier("g-serve-6g", 0.20, 6144, mean_duration=0.2,
            qos_tier=GUARANTEED),
    PodTier("g-serve-4g", 0.15, 4096, mean_duration=0.4,
            qos_tier=GUARANTEED),
    PodTier("b-dev-4g", 0.25, 4096, mean_duration=0.4),
    PodTier("b-dev-2g", 0.15, 2048, mean_duration=0.2),
    PodTier("be-batch-8g", 0.15, 8192, mean_duration=1.0,
            qos_tier=BEST_EFFORT),
    PodTier("be-batch-4g", 0.10, 4096, mean_duration=0.7,
            qos_tier=BEST_EFFORT),
)

QOS_GATE_SPEC = DiurnalSpec(hours=2.0, period=2.0, base_rate=150.0,
                            peak_rate=450.0, tiers=QOS_GATE_TIERS,
                            seed=17)
QOS_GATE_FLEET = {"nodes": 8, "chips": 4, "hbm": 16384, "mesh": (2, 2)}
GATE_OVERCOMMIT = 1.25
GATE_EVICT_BUDGET = 4      # evictions per sliding window (live default)
GATE_EVICT_WINDOW = 0.25   # window length in trace-time units

# The premise oversubscription monetizes: guaranteed/burstable requests
# are sized for peak (OOM kills are unacceptable), so their RESERVED
# bytes overstate ACTUAL residency — best-effort scavengers harvest the
# slack. Utilization integrates actual bytes (reserved x this fraction
# for non-best-effort, full demand for best-effort, clamped at physical
# HBM); admission, pressure, and both invariants stay on reservations,
# exactly like the live fleet where apiserver grants are the truth.
NONBE_USE_FRAC = 0.6

QOS_GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "data",
    "qos_wind_tunnel_golden.json")

# same semantics as autotune.DEFAULT_BANDS: deterministic replays, so
# bands absorb intended small shifts while a policy regression cannot
# hide inside them
QOS_DEFAULT_BANDS = {
    "time_weighted_util_pct": 1.0,
    "rejection_rate": 0.03,
    "p99_pending_age_s": 3.0,
}


@dataclass
class QosSimReport:
    overcommit: float
    pods: int
    placed: int
    never_placed: int
    mean_wait: float
    p99_wait: float
    util_pct: float            # ACTUAL bytes (NONBE_USE_FRAC model),
                               # clamped per chip at physical HBM
    makespan: float
    evictions: int
    max_window_evictions: int  # proof the governor held: <= budget
    budget_deferred: int       # pressured scans the governor postponed
    reclaimed_mib: int         # best-effort bytes evicted back
    oversub_time_weighted_mib: float
    guaranteed_violations: int # sampled instants; MUST be zero
    overcommit_violations: int # sampled instants; MUST be zero
    by_tier: dict = field(default_factory=dict)
    waits: list[float] = field(default_factory=list, repr=False)

    def scorecard(self) -> dict:
        """Same currency as SimReport.scorecard / the live fleetwatch
        scorecard, so one band checker serves both gates."""
        return {
            "time_weighted_util_pct": round(self.util_pct, 4),
            "rejection_rate": round(self.never_placed / self.pods, 4)
            if self.pods else None,
            "p99_pending_age_s": round(self.p99_wait, 4),
        }

    def to_json(self) -> dict:
        out = {k: (round(v, 4) if isinstance(v, float) else v)
               for k, v in self.__dict__.items() if k != "waits"}
        out["scorecard"] = self.scorecard()
        return {k: out[k] for k in sorted(out)}


def run_qos_sim(fleet: Fleet, trace: list[SimPod],
                overcommit: float = 1.0,
                evict_budget: int = GATE_EVICT_BUDGET,
                evict_window: float = GATE_EVICT_WINDOW) -> QosSimReport:
    """Replay ``trace`` under tiered admission. Deterministic.

    Admission per chip mirrors ``NodeInfo._qos_views``: best-effort
    headroom is ``int(hbm * oc) - used``; non-best-effort headroom is
    ``max(0, min(hbm - used + reclaimable, int(hbm * oc) - used))`` —
    both constraints hold AT admission, so the sampled invariants need
    no grace window. ``overcommit <= 1.0`` is plain physical admission
    for every tier (the live master gate collapses identically).
    """
    oc = max(1.0, overcommit)
    nchips = [len(n.used) for n in fleet.nodes]
    # best-effort grant sum per (node, chip) — the reclaimable pool
    be = [[0] * c for c in nchips]
    heap: list[tuple] = []
    for seq, pod in enumerate(sorted(trace, key=lambda p: p.arrival)):
        heapq.heappush(heap, (pod.arrival, 1, seq, pod))
    pending: list[SimPod] = []
    waits: list[float] = []
    tier_waits: dict[str, list[float]] = {}
    tier_counts: dict[str, list[int]] = {}  # tier -> [pods, placed]
    for pod in trace:
        tier_counts.setdefault(pod.qos_tier, [0, 0])[0] += 1
    placed = 0
    evictions = 0
    budget_deferred = 0
    reclaimed = 0
    g_viol = 0
    oc_viol = 0
    evict_times: list[float] = []
    max_window = 0
    # seq2 -> (pod, node_index, chip_ids, per-chip demand)
    active: dict[int, tuple] = {}
    cancelled: set[int] = set()
    now = 0.0
    last_t = 0.0
    util_integral = 0.0
    oversub_integral = 0.0
    busy_start: float | None = None
    seq2 = len(trace)

    def advance(to: float) -> None:
        nonlocal util_integral, oversub_integral, last_t, g_viol, oc_viol
        dt = to - last_t
        if dt > 0:
            for ni, node in enumerate(fleet.nodes):
                cap = int(node.hbm * oc)
                for i, u in enumerate(node.used):
                    actual = (u - be[ni][i]) * NONBE_USE_FRAC + be[ni][i]
                    util_integral += min(actual, node.hbm) * dt
                    oversub_integral += max(0, u - node.hbm) * dt
                    # sampled invariants (chaos drill currency): the
                    # guaranteed reservation is physically backed and
                    # the declared bound holds at every instant
                    if u - be[ni][i] > node.hbm:
                        g_viol += 1
                    if u > cap:
                        oc_viol += 1
        last_t = to

    def adj_free(node, ni: int, i: int, tier: str) -> int:
        u = node.used[i]
        if oc <= 1.0:
            return node.hbm - u
        cap = int(node.hbm * oc)
        if tier == BEST_EFFORT:
            return cap - u
        return max(0, min(node.hbm - u + be[ni][i], cap - u))

    def try_place(pod: SimPod) -> bool:
        nonlocal placed, seq2
        demand = pod.hbm_mib
        tier = pod.qos_tier
        best = None  # (press_sum, free_sum, ni, chip_ids)
        for ni, node in enumerate(fleet.nodes):
            if node.down:
                continue
            cands = []
            for i in range(len(node.used)):
                if not node.chip_healthy(i):
                    continue
                free = adj_free(node, ni, i, tier)
                if free < demand:
                    continue
                u = node.used[i]
                nonbe_after = u - be[ni][i] + (0 if tier == BEST_EFFORT
                                              else demand)
                press = 1 if (u + demand > node.hbm
                              and nonbe_after > 0) else 0
                cands.append((press, free, i))
            if len(cands) < pod.chip_count:
                continue
            cands.sort()
            pick = cands[:pod.chip_count]
            key = (sum(c[0] for c in pick), sum(c[1] for c in pick), ni)
            if best is None or key < best[:3]:
                best = (*key, tuple(c[2] for c in pick))
        if best is None:
            return False
        _press, _free, ni, chip_ids = best
        node = fleet.nodes[ni]
        for cid in chip_ids:
            node.used[cid] += demand
            if tier == BEST_EFFORT:
                be[ni][cid] += demand
        heapq.heappush(heap, (now + pod.duration, 0, seq2,
                              (ni, chip_ids, demand)))
        active[seq2] = (pod, ni, chip_ids, demand)
        seq2 += 1
        placed += 1
        tier_counts.setdefault(tier, [0, 0])[1] += 1
        waits.append(now - pod.arrival)
        tier_waits.setdefault(tier, []).append(now - pod.arrival)
        return True

    def _release(vid: int) -> SimPod:
        pod, ni, chip_ids, demand = active.pop(vid)
        node = fleet.nodes[ni]
        for cid in chip_ids:
            node.used[cid] -= demand
            if pod.qos_tier == BEST_EFFORT:
                be[ni][cid] -= demand
        cancelled.add(vid)
        return pod

    def pressure_scan() -> None:
        """Evict best-effort borrowers off pressured chips, one victim
        per pass (pressure_victim's loop), under the budget governor."""
        nonlocal evictions, budget_deferred, reclaimed, max_window
        while True:
            worst = None  # (overage, ni, chip)
            for ni, node in enumerate(fleet.nodes):
                for i, u in enumerate(node.used):
                    over = u - node.hbm
                    # pressure: over physical AND non-best-effort usage
                    # present AND something evictable on the chip — a
                    # purely best-effort chip within the bound is the
                    # intended borrow state, not pressure
                    if over > 0 and u - be[ni][i] > 0 and be[ni][i] > 0:
                        if worst is None or over > worst[0]:
                            worst = (over, ni, i)
            if worst is None:
                return
            while evict_times and evict_times[0] <= now - evict_window:
                evict_times.pop(0)
            if len(evict_times) >= evict_budget:
                budget_deferred += 1
                return  # governor: the next event's scan retries
            over, ni, chip = worst
            pool = [(vid, e[3]) for vid, e in active.items()
                    if e[0].qos_tier == BEST_EFFORT and e[1] == ni
                    and chip in e[2]]
            if not pool:
                return
            clearing = [p for p in pool if p[1] >= over]
            vid, _ = min(clearing, key=lambda p: (p[1], p[0])) \
                if clearing else max(pool, key=lambda p: (p[1], -p[0]))
            victim = _release(vid)
            evictions += 1
            evict_times.append(now)
            max_window = max(max_window, len(evict_times))
            reclaimed += victim.hbm_mib * victim.chip_count
            pending.append(victim)  # restarts: full duration again

    while heap:
        t, kind, seq_id, payload = heapq.heappop(heap)
        advance(t)
        now = t
        if busy_start is None:
            busy_start = t
        if kind == 1:  # arrival
            if not try_place(payload):
                pending.append(payload)
        else:          # departure
            if seq_id in cancelled:
                cancelled.discard(seq_id)
                continue
            pod, ni, chip_ids, demand = active.pop(seq_id)
            node = fleet.nodes[ni]
            for cid in chip_ids:
                node.used[cid] -= demand
                if pod.qos_tier == BEST_EFFORT:
                    be[ni][cid] -= demand
            pending = [q for q in pending if not try_place(q)]
        pressure_scan()

    span = max(last_t - (busy_start or 0.0), 1e-9)
    by_tier = {}
    for tier, (n_pods, n_placed) in sorted(tier_counts.items()):
        ws = tier_waits.get(tier, [])
        by_tier[tier] = {
            "pods": n_pods, "placed": n_placed,
            "mean_wait": round(sum(ws) / len(ws), 4) if ws else 0.0,
            "p99_wait": round(_p99(ws), 4),
        }
    return QosSimReport(
        overcommit=oc,
        pods=len(trace),
        placed=placed,
        never_placed=len(pending),
        mean_wait=sum(waits) / len(waits) if waits else 0.0,
        p99_wait=_p99(waits),
        util_pct=util_integral / (fleet.total_hbm * span) * 100.0,
        makespan=span,
        evictions=evictions,
        max_window_evictions=max_window,
        budget_deferred=budget_deferred,
        reclaimed_mib=reclaimed,
        oversub_time_weighted_mib=oversub_integral / span,
        guaranteed_violations=g_viol,
        overcommit_violations=oc_viol,
        by_tier=by_tier,
        waits=waits,
    )


# -- the pinned tiered gate ---------------------------------------------------

def _gate_fleet() -> Fleet:
    return Fleet.homogeneous(
        QOS_GATE_FLEET["nodes"], QOS_GATE_FLEET["chips"],
        QOS_GATE_FLEET["hbm"], QOS_GATE_FLEET["mesh"])


def qos_gate_report(overcommit: float = GATE_OVERCOMMIT) -> QosSimReport:
    """The gate replay: standard tiered diurnal trace over the standard
    fleet. ``overcommit=1.0`` is the single-class baseline leg."""
    return run_qos_sim(_gate_fleet(), synth_diurnal(QOS_GATE_SPEC),
                       overcommit=overcommit)


def overcommit_sweep(values: tuple[float, ...] = (1.0, 1.1, 1.25, 1.5)
                     ) -> dict:
    """Sweep the overcommit knob over the gate workload — the capacity
    question the knob table sends operators here to answer. Rows keep
    trace order (the knob IS the x-axis); each carries the scorecard
    plus the tier-isolation evidence."""
    rows = []
    for v in values:
        rep = run_qos_sim(_gate_fleet(), synth_diurnal(QOS_GATE_SPEC),
                          overcommit=v)
        rows.append({
            "overcommit": v,
            "scorecard": rep.scorecard(),
            "evictions": rep.evictions,
            "guaranteed_violations": rep.guaranteed_violations,
            "overcommit_violations": rep.overcommit_violations,
            "guaranteed_p99_wait": rep.by_tier.get(
                GUARANTEED, {}).get("p99_wait", 0.0),
            "reclaimed_mib": rep.reclaimed_mib,
        })
    return {"mode": "qos-sweep", "rows": rows}


def pin_qos_golden(path: str | None = None,
                   bands: dict | None = None) -> dict:
    """Write the tiered gate golden: the overcommitted scorecard, the
    single-class baseline it must beat, and the isolation evidence.
    Deliberate re-baselining ONLY (docs/ops.md)."""
    rep = qos_gate_report()
    base = qos_gate_report(overcommit=1.0)
    golden = {
        "gate_spec": {"hours": QOS_GATE_SPEC.hours,
                      "base_rate": QOS_GATE_SPEC.base_rate,
                      "peak_rate": QOS_GATE_SPEC.peak_rate,
                      "seed": QOS_GATE_SPEC.seed,
                      "n_tiers": len(QOS_GATE_SPEC.tiers)},
        "gate_fleet": {k: (list(v) if isinstance(v, tuple) else v)
                       for k, v in QOS_GATE_FLEET.items()},
        "overcommit": GATE_OVERCOMMIT,
        "scorecard": rep.scorecard(),
        "qos": {
            "evictions": rep.evictions,
            "max_window_evictions": rep.max_window_evictions,
            "guaranteed_violations": rep.guaranteed_violations,
            "overcommit_violations": rep.overcommit_violations,
            "reclaimed_mib": rep.reclaimed_mib,
            "guaranteed_p99_wait": rep.by_tier[GUARANTEED]["p99_wait"],
            "baseline_util_pct": base.scorecard()[
                "time_weighted_util_pct"],
            "baseline_guaranteed_p99_wait":
                base.by_tier[GUARANTEED]["p99_wait"],
        },
        "bands": dict(bands or QOS_DEFAULT_BANDS),
    }
    path = path or QOS_GOLDEN_PATH
    with open(path, "w") as f:
        json.dump(golden, f, indent=1, sort_keys=True)
        f.write("\n")
    return golden


def load_qos_golden(path: str | None = None) -> dict:
    with open(path or QOS_GOLDEN_PATH) as f:
        return json.load(f)
