"""Sim-first validation of the repack rebalancer (defrag/planner core).

Replays a churn trace over a simulated fleet with the SAME planning
logic the live controller runs — :func:`tpushare.defrag.planner.
plan_moves` over :class:`NodeState` records — sweeping the per-window
migration budget, and reports each run in the PR 6 scorecard schema
(``time_weighted_util_pct`` / ``rejection_rate`` /
``p99_pending_age_s``) so simulated repack policies and the live
fleet's ``/inspect/fleet`` compare in one currency.

The sweep's headline number is **stranded-capacity recovery**: at every
defrag pass the fleet's aggregate worst-tier stranded gap (chips that
pass the count fit but sit outside the largest contiguous box — the
``tpushare_fleet_stranded_hbm_mib`` story) is measured before and after
the pass's moves; ``recovery_pct`` is the recovered fraction summed
over passes. Budget 0 is the control: same trace, same planner, no
moves allowed.

CLI: ``python -m tpushare.sim --defrag [--budgets 0,1,2,4]``.
"""

from __future__ import annotations

import heapq
from typing import Any

from tpushare.core.placement import PlacementRequest, select_chips_py
from tpushare.defrag.planner import (NodeState, RepackPlan, Victim,
                                     plan_moves, worst_tier)
from tpushare.sim.simulator import Fleet, SimPod, TraceSpec, synth_trace


class _SimState:
    """Fleet + per-node mutation counters (the sim's generation stamps)
    + the active-placement table the planner's victims come from."""

    def __init__(self, fleet: Fleet) -> None:
        self.fleet = fleet
        self.stamps = [0] * len(fleet.nodes)
        # vid -> (node index, chip ids, per-chip demand, SimPod)
        self.active: dict[int, tuple[int, tuple[int, ...], int, SimPod]] = {}
        self._by_name = {n.name: i for i, n in enumerate(fleet.nodes)}

    def place(self, vid: int, ni: int, chip_ids: tuple[int, ...],
              demand: int, pod: SimPod) -> None:
        node = self.fleet.nodes[ni]
        for cid in chip_ids:
            node.used[cid] += demand
        self.stamps[ni] += 1
        self.active[vid] = (ni, chip_ids, demand, pod)

    def evict(self, vid: int) -> None:
        ni, chip_ids, demand, _pod = self.active.pop(vid)
        node = self.fleet.nodes[ni]
        for cid in chip_ids:
            node.used[cid] = max(node.used[cid] - demand, 0)
        self.stamps[ni] += 1

    # -- planner adapters -----------------------------------------------------

    def states(self) -> list[NodeState]:
        """Every node as a stamped NodeState; in the sim all resident
        placements are movable via the restore path."""
        out = []
        victims: dict[int, list[Victim]] = {i: []
                                            for i in range(len(self.fleet.nodes))}
        for vid, (ni, chip_ids, demand, pod) in self.active.items():
            victims[ni].append(Victim(
                pod_key=str(vid), chip_ids=chip_ids,
                per_chip_mib=demand, request=pod.request))
        for ni, node in enumerate(self.fleet.nodes):
            out.append(NodeState(
                name=node.name, stamp=(0, self.stamps[ni]),
                topo=node.topo, hbm_per_chip=node.hbm,
                views=node.views(), victims=victims[ni]))
        return out

    def solve(self, req: PlacementRequest, exclude: set[str],
              claimed) -> tuple | None:
        """Best-scoring target across the fleet, with chips claimed by
        earlier moves in the plan treated as fully used — the sim
        analogue of the live planner's disjointness retry."""
        best = None
        for ni, node in enumerate(self.fleet.nodes):
            if node.name in exclude:
                continue
            taken = claimed.get(node.name, set())
            views = [v.with_used(v.total_hbm_mib) if v.idx in taken else v
                     for v in node.views()]
            p = select_chips_py(views, node.topo, req)
            if p is not None and (best is None or p.score < best[1].score):
                best = (node.name, p, (0, self.stamps[ni]))
        return best

    def apply_plan(self, plan: RepackPlan) -> int:
        """Execute a plan's moves directly on the fleet arrays (the sim
        has no apiserver to race, so every stamped move is still valid
        by construction). Returns moves applied."""
        applied = 0
        for m in plan.moves:
            vid = int(m.pod_key)
            entry = self.active.get(vid)
            if entry is None:
                continue
            _ni, _chips, demand, pod = entry
            self.evict(vid)
            tni = self._by_name[m.target]
            self.place(vid, tni, m.placement.chip_ids, demand, pod)
            applied += 1
        return applied

    def stranded_chips(self) -> int:
        """Fleet aggregate worst-tier stranded gap, in chips."""
        return sum(worst_tier(st)[1] for st in self.states())


def _try_place(state: _SimState, vid: int, pod: SimPod) -> bool:
    """tpushare's binpack policy: tightest-scoring node wins."""
    req = pod.request
    best = None
    for ni, node in enumerate(state.fleet.nodes):
        p = select_chips_py(node.views(), node.topo, req)
        if p is not None and (best is None or p.score < best[1].score):
            best = (ni, p)
    if best is None:
        return False
    demand = req.chip_demand_mib(state.fleet.nodes[best[0]].hbm)
    state.place(vid, best[0], best[1].chip_ids, demand, pod)
    return True


def run_defrag_sim(fleet: Fleet, trace: list[SimPod], budget: int,
                   defrag_period: float = 20.0) -> dict[str, Any]:
    """One churn replay with a defrag pass every ``defrag_period`` time
    units, ``budget`` moves per pass (0 = control: plan but never act).
    """
    state = _SimState(fleet)
    events: list[tuple[float, int, str, Any]] = []
    seq = 0
    for vid, pod in enumerate(trace):
        events.append((pod.arrival, seq, "arrive", (vid, pod)))
        seq += 1
    # the first defrag pass; each pass re-schedules the next while any
    # work remains, so repacking covers the drain-down tail too
    events.append((defrag_period, seq, "defrag", None))
    seq += 1
    heapq.heapify(events)

    pending: list[tuple[int, SimPod]] = []
    placed_at: dict[int, float] = {}
    waits: list[float] = []
    now = 0.0
    util_integral = 0.0
    total = fleet.total_hbm
    moves = passes = 0
    stranded_pre = stranded_post = 0
    placed_count = 0

    def advance(to: float) -> None:
        nonlocal now, util_integral
        util_integral += fleet.used_hbm * max(to - now, 0.0)
        now = to

    def retry_pending() -> None:
        nonlocal placed_count
        still = []
        for vid, pod in pending:
            if _try_place(state, vid, pod):
                placed_at[vid] = now
                waits.append(now - pod.arrival)
                placed_count += 1
                heapq.heappush(events, (now + pod.duration, 10**9 + vid,
                                        "depart", vid))
            else:
                still.append((vid, pod))
        pending[:] = still

    while events:
        when, _s, kind, payload = heapq.heappop(events)
        advance(when)
        if kind == "arrive":
            vid, pod = payload
            if _try_place(state, vid, pod):
                placed_at[vid] = now
                waits.append(0.0)
                placed_count += 1
                heapq.heappush(events, (now + pod.duration, 10**9 + vid,
                                        "depart", vid))
            else:
                pending.append((vid, pod))
        elif kind == "depart":
            if payload in state.active:
                state.evict(payload)
            retry_pending()
        elif kind == "defrag":
            passes += 1
            pre = state.stranded_chips()
            if pre > 0:
                plan = plan_moves(state.states(), state.solve, budget,
                                  per_node=budget)
                if budget > 0 and plan.moves:
                    moves += state.apply_plan(plan)
                    retry_pending()
            post = state.stranded_chips()
            stranded_pre += pre
            stranded_post += post
            if events or state.active:
                heapq.heappush(events, (now + defrag_period, seq,
                                        "defrag", None))
                seq += 1

    waits_sorted = sorted(waits)
    p99 = waits_sorted[int(0.99 * (len(waits_sorted) - 1))] \
        if waits_sorted else 0.0
    recovery = ((stranded_pre - stranded_post) / stranded_pre * 100.0
                if stranded_pre else 0.0)
    return {
        "budget": budget,
        "defrag_passes": passes,
        "moves": moves,
        "stranded_chips_observed": stranded_pre,
        "stranded_chips_after": stranded_post,
        "recovery_pct": round(recovery, 2),
        "pods": len(trace),
        "placed": placed_count,
        "never_placed": len(trace) - placed_count,
        "scorecard": {
            "time_weighted_util_pct": round(
                100.0 * util_integral / (total * now), 4)
            if total and now else 0.0,
            "rejection_rate": round(
                (len(trace) - placed_count) / len(trace), 4)
            if trace else None,
            "p99_pending_age_s": round(p99, 4),
        },
    }


def sweep_budgets(budgets=(0, 1, 2, 4), n_nodes: int = 8, chips: int = 4,
                  hbm: int = 16384, mesh: tuple[int, ...] | None = (2, 2),
                  spec: TraceSpec | None = None,
                  defrag_period: float = 20.0) -> list[dict[str, Any]]:
    """The budget sweep: identical trace + fleet per budget, so every
    difference in the reports is the repack budget's doing."""
    # moderate load on purpose (~60% offered): a saturated fleet has no
    # free chips to strand, an idle one nothing to repack — churn in the
    # middle is where departures leave diagonal half-empty meshes
    spec = spec or TraceSpec(
        n_pods=300, arrival_rate=0.5, mean_duration=40.0,
        sizes=(8192, 12288, 16384), multi_chip_fraction=0.3, seed=7)
    trace = synth_trace(spec)
    out = []
    for budget in budgets:
        fleet = Fleet.homogeneous(n_nodes, chips, hbm, mesh)
        out.append(run_defrag_sim(fleet, trace, budget,
                                  defrag_period=defrag_period))
    return out
