"""Sim-first validation of the repack rebalancer (defrag/planner core).

Replays a churn trace over a simulated fleet with the SAME planning
logic the live controller runs — :func:`tpushare.defrag.planner.
plan_moves` over :class:`NodeState` records — sweeping the per-window
migration budget, and reports each run in the PR 6 scorecard schema
(``time_weighted_util_pct`` / ``rejection_rate`` /
``p99_pending_age_s``) so simulated repack policies and the live
fleet's ``/inspect/fleet`` compare in one currency.

The sweep's headline number is **stranded-capacity recovery**: at every
defrag pass the fleet's aggregate worst-tier stranded gap (chips that
pass the count fit but sit outside the largest contiguous box — the
``tpushare_fleet_stranded_hbm_mib`` story) is measured before and after
the pass's moves; ``recovery_pct`` is the recovered fraction summed
over passes. Budget 0 is the control: same trace, same planner, no
moves allowed.

Two migration-era extensions ride on the same loop:

- **Pause model** — every applied move costs a checkpoint/restore pause
  derived from the victim's HBM footprint at a fixed drain rate
  (``pause = floor + footprint_mib / ckpt_mib_per_s``); the report
  carries pause p50/p99 in the same shape the live
  ``tpushare_defrag_pause_seconds`` histogram publishes, and a
  ``pause_budget_s`` aborts any single move whose modeled pause would
  blow ``TPUSHARE_MIGRATE_PAUSE_BUDGET_S``.
- **Forecast bias** — ``frag_weight`` mirrors the live Prioritize
  blend: at each defrag cadence the sim samples which nodes carry a
  stranded gap (the fleetwatch trend's stand-in), and placements of new
  arrivals are steered toward those nodes so small pods soak existing
  holes instead of opening fresh ones. Weight 0 is byte-identical to
  the react-only policy; :func:`sweep_forecast` runs the identical
  trace both ways and reports whether forecasting held stranded
  capacity down with strictly fewer migrations.

CLI: ``python -m tpushare.sim --defrag [--budgets 0,1,2,4]
[--frag-weight W]``.
"""

from __future__ import annotations

import heapq
from typing import Any

from tpushare.core.placement import PlacementRequest, select_chips_py
from tpushare.defrag.planner import (NodeState, RepackPlan, Victim,
                                     plan_moves, worst_tier)
from tpushare.sim.simulator import Fleet, SimPod, TraceSpec, synth_trace


class _SimState:
    """Fleet + per-node mutation counters (the sim's generation stamps)
    + the active-placement table the planner's victims come from."""

    def __init__(self, fleet: Fleet) -> None:
        self.fleet = fleet
        self.stamps = [0] * len(fleet.nodes)
        # vid -> (node index, chip ids, per-chip demand, SimPod)
        self.active: dict[int, tuple[int, tuple[int, ...], int, SimPod]] = {}
        self._by_name = {n.name: i for i, n in enumerate(fleet.nodes)}
        # node indices with a stranded gap, refreshed at the defrag
        # cadence — the sim's stand-in for the fleetwatch sample the
        # live FragForecast polls (deliberately stale between passes,
        # exactly like the live bias)
        self.frag_nodes: frozenset[int] = frozenset()
        self.frag_pressure = 0.0

    def place(self, vid: int, ni: int, chip_ids: tuple[int, ...],
              demand: int, pod: SimPod) -> None:
        node = self.fleet.nodes[ni]
        for cid in chip_ids:
            node.used[cid] += demand
        self.stamps[ni] += 1
        self.active[vid] = (ni, chip_ids, demand, pod)

    def evict(self, vid: int) -> None:
        ni, chip_ids, demand, _pod = self.active.pop(vid)
        node = self.fleet.nodes[ni]
        for cid in chip_ids:
            node.used[cid] = max(node.used[cid] - demand, 0)
        self.stamps[ni] += 1

    # -- planner adapters -----------------------------------------------------

    def states(self) -> list[NodeState]:
        """Every node as a stamped NodeState; in the sim all resident
        placements are movable via the restore path."""
        out = []
        victims: dict[int, list[Victim]] = {i: []
                                            for i in range(len(self.fleet.nodes))}
        for vid, (ni, chip_ids, demand, pod) in self.active.items():
            victims[ni].append(Victim(
                pod_key=str(vid), chip_ids=chip_ids,
                per_chip_mib=demand, request=pod.request))
        for ni, node in enumerate(self.fleet.nodes):
            out.append(NodeState(
                name=node.name, stamp=(0, self.stamps[ni]),
                topo=node.topo, hbm_per_chip=node.hbm,
                views=node.views(), victims=victims[ni]))
        return out

    def solve(self, req: PlacementRequest, exclude: set[str],
              claimed) -> tuple | None:
        """Best-scoring target across the fleet, with chips claimed by
        earlier moves in the plan treated as fully used — the sim
        analogue of the live planner's disjointness retry."""
        best = None
        for ni, node in enumerate(self.fleet.nodes):
            if node.name in exclude:
                continue
            taken = claimed.get(node.name, set())
            views = [v.with_used(v.total_hbm_mib) if v.idx in taken else v
                     for v in node.views()]
            p = select_chips_py(views, node.topo, req)
            if p is not None and (best is None or p.score < best[1].score):
                best = (node.name, p, (0, self.stamps[ni]))
        return best

    def apply_plan(self, plan: RepackPlan) -> int:
        """Execute a plan's moves directly on the fleet arrays (the sim
        has no apiserver to race, so every stamped move is still valid
        by construction). Returns moves applied."""
        return self.apply_moves(plan.moves)

    def apply_moves(self, selected) -> int:
        applied = 0
        for m in selected:
            vid = int(m.pod_key)
            entry = self.active.get(vid)
            if entry is None:
                continue
            _ni, _chips, demand, pod = entry
            self.evict(vid)
            tni = self._by_name[m.target]
            self.place(vid, tni, m.placement.chip_ids, demand, pod)
            applied += 1
        return applied

    def stranded_chips(self) -> int:
        """Fleet aggregate worst-tier stranded gap, in chips."""
        return sum(worst_tier(st)[1] for st in self.states())

    def refresh_forecast(self) -> None:
        """Recompute the scatter-bias node set and the fleet pressure
        (same shape as FragForecast: 8x the stranded HBM fraction,
        clamped to 1) — called at the defrag cadence only, so the bias
        between passes runs on a stale sample like the live path does.

        The bias set is every node that is already BROKEN — some chip
        carries load, so the node can no longer offer a pristine
        whole-mesh box. Steering hole-soakers there keeps untouched
        boxes intact for gangs, which is how admission avoids
        manufacturing the diagonal half-empty meshes defrag would
        otherwise have to repair."""
        frag = set()
        stranded_mib = 0
        for ni, st in enumerate(self.states()):
            gap = worst_tier(st)[1]
            if gap > 0:
                stranded_mib += gap * st.hbm_per_chip
            node = self.fleet.nodes[ni]
            if any(u > 0 for u in node.used):
                frag.add(ni)
        total = self.fleet.total_hbm
        self.frag_nodes = frozenset(frag)
        self.frag_pressure = min(1.0, 8.0 * stranded_mib / total) \
            if total else 0.0


def _try_place(state: _SimState, vid: int, pod: SimPod,
               frag_weight: float = 0.0) -> bool:
    """tpushare's binpack policy: tightest-scoring node wins. With
    ``frag_weight`` > 0 the choice mirrors the live Prioritize frag
    blend: binpack scores are normalized to 0..10 across candidates and
    blended against a 10-or-0 fragmentation priority at effective
    weight ``frag_weight * pressure``, steering pods toward nodes that
    already carry a stranded gap. Weight 0 takes the original code
    path verbatim.

    Only single-chip pods are steered: they are the hole-soakers. A
    multi-chip mesh dropped onto a fragmented node would eat its
    remaining contiguous box and make the stranding WORSE — the live
    blend reaches the same end through the tier factor (gangs run
    guaranteed and barely biased, scatter-tolerant singles run
    best-effort at full weight)."""
    req = pod.request
    f_eff = (frag_weight * state.frag_pressure
             if req.chip_count <= 1 else 0.0)
    if f_eff <= 0.0:
        best = None
        for ni, node in enumerate(state.fleet.nodes):
            p = select_chips_py(node.views(), node.topo, req)
            if p is not None and (best is None or p.score < best[1].score):
                best = (ni, p)
        if best is None:
            return False
        demand = req.chip_demand_mib(state.fleet.nodes[best[0]].hbm)
        state.place(vid, best[0], best[1].chip_ids, demand, pod)
        return True
    cands = []
    for ni, node in enumerate(state.fleet.nodes):
        p = select_chips_py(node.views(), node.topo, req)
        if p is not None:
            cands.append((ni, p))
    if not cands:
        return False
    lo = min(p.score for _ni, p in cands)
    hi = max(p.score for _ni, p in cands)
    best = None
    best_key = None
    for ni, p in cands:
        # lower select score = tighter fit = higher priority, same
        # normalization direction as the live handler's binpack score
        score10 = 10.0 if hi == lo else 10.0 * (hi - p.score) / (hi - lo)
        p_frag = 10.0 if ni in state.frag_nodes else 0.0
        blended = round((1.0 - f_eff) * score10 + f_eff * p_frag)
        key = (-blended, p.score, ni)  # deterministic tie-break
        if best_key is None or key < best_key:
            best, best_key = (ni, p), key
    demand = req.chip_demand_mib(state.fleet.nodes[best[0]].hbm)
    state.place(vid, best[0], best[1].chip_ids, demand, pod)
    return True


#: migration pause model defaults: a fixed floor (engine park + RPC
#: round-trips) plus footprint drained at a checkpoint write rate
PAUSE_FLOOR_S = 0.25
CKPT_MIB_PER_S = 2048.0


def _move_pause_s(m, ckpt_mib_per_s: float = CKPT_MIB_PER_S,
                  floor_s: float = PAUSE_FLOOR_S) -> float:
    """Deterministic modeled pause for one move: the victim's full HBM
    footprint checkpointed then restored at ``ckpt_mib_per_s``."""
    footprint_mib = len(m.victim_chip_ids) * m.per_chip_mib
    return floor_s + footprint_mib / ckpt_mib_per_s


def run_defrag_sim(fleet: Fleet, trace: list[SimPod], budget: int,
                   defrag_period: float = 20.0,
                   frag_weight: float = 0.0,
                   pause_budget_s: float | None = None,
                   ckpt_mib_per_s: float = CKPT_MIB_PER_S,
                   stranded_target_chips: int | None = None
                   ) -> dict[str, Any]:
    """One churn replay with a defrag pass every ``defrag_period`` time
    units, ``budget`` moves per pass (0 = control: plan but never act).

    ``frag_weight`` > 0 turns on the forecast placement bias (see
    :func:`_try_place`); ``pause_budget_s`` aborts any planned move
    whose modeled pause exceeds the budget, mirroring the executor's
    ``TPUSHARE_MIGRATE_PAUSE_BUDGET_S`` rollback.

    ``stranded_target_chips`` switches the pass trigger from
    react-only (repack whenever ANY chip is stranded — migrations chase
    zero) to pressure-gated (repack only once the stranded gap exceeds
    the target). Every migration is a paused workload, so the
    forecast policy tolerates gaps the fleet can absorb and spends
    pauses only when the SLO is actually threatened; the admission bias
    is what keeps the below-target drift from compounding between
    passes.
    """
    state = _SimState(fleet)
    events: list[tuple[float, int, str, Any]] = []
    seq = 0
    for vid, pod in enumerate(trace):
        events.append((pod.arrival, seq, "arrive", (vid, pod)))
        seq += 1
    # the first defrag pass; each pass re-schedules the next while any
    # work remains, so repacking covers the drain-down tail too
    events.append((defrag_period, seq, "defrag", None))
    seq += 1
    heapq.heapify(events)

    pending: list[tuple[int, SimPod]] = []
    placed_at: dict[int, float] = {}
    waits: list[float] = []
    now = 0.0
    util_integral = 0.0
    total = fleet.total_hbm
    moves = passes = 0
    stranded_pre = stranded_post = 0
    placed_count = 0
    pauses: list[float] = []
    aborted_over_budget = 0
    max_stranded = 0

    def advance(to: float) -> None:
        nonlocal now, util_integral
        util_integral += fleet.used_hbm * max(to - now, 0.0)
        now = to

    def retry_pending() -> None:
        nonlocal placed_count
        still = []
        for vid, pod in pending:
            if _try_place(state, vid, pod, frag_weight):
                placed_at[vid] = now
                waits.append(now - pod.arrival)
                placed_count += 1
                heapq.heappush(events, (now + pod.duration, 10**9 + vid,
                                        "depart", vid))
            else:
                still.append((vid, pod))
        pending[:] = still

    while events:
        when, _s, kind, payload = heapq.heappop(events)
        advance(when)
        if kind == "arrive":
            vid, pod = payload
            if _try_place(state, vid, pod, frag_weight):
                placed_at[vid] = now
                waits.append(0.0)
                placed_count += 1
                heapq.heappush(events, (now + pod.duration, 10**9 + vid,
                                        "depart", vid))
            else:
                pending.append((vid, pod))
        elif kind == "depart":
            if payload in state.active:
                state.evict(payload)
            retry_pending()
        elif kind == "defrag":
            passes += 1
            pre = state.stranded_chips()
            max_stranded = max(max_stranded, pre)
            act = (pre > 0 if stranded_target_chips is None
                   else pre > stranded_target_chips)
            if act:
                plan = plan_moves(state.states(), state.solve, budget,
                                  per_node=budget)
                if budget > 0 and plan.moves:
                    for m in plan.moves:
                        pause = _move_pause_s(m, ckpt_mib_per_s)
                        if (pause_budget_s is not None
                                and pause > pause_budget_s):
                            aborted_over_budget += 1
                            continue
                        if state.apply_moves([m]):
                            moves += 1
                            pauses.append(pause)
                    retry_pending()
            post = state.stranded_chips()
            stranded_pre += pre
            stranded_post += post
            if frag_weight > 0.0:
                state.refresh_forecast()
            if events or state.active:
                heapq.heappush(events, (now + defrag_period, seq,
                                        "defrag", None))
                seq += 1

    waits_sorted = sorted(waits)
    p99 = waits_sorted[int(0.99 * (len(waits_sorted) - 1))] \
        if waits_sorted else 0.0
    recovery = ((stranded_pre - stranded_post) / stranded_pre * 100.0
                if stranded_pre else 0.0)
    pauses_sorted = sorted(pauses)

    def _pq(q: float) -> float:
        if not pauses_sorted:
            return 0.0
        return pauses_sorted[int(q * (len(pauses_sorted) - 1))]

    return {
        "budget": budget,
        "frag_weight": frag_weight,
        "defrag_passes": passes,
        "moves": moves,
        "migration": {
            "pauses": len(pauses),
            "pause_p50_s": round(_pq(0.50), 4),
            "pause_p99_s": round(_pq(0.99), 4),
            "aborted_over_budget": aborted_over_budget,
        },
        "stranded_target_chips": stranded_target_chips,
        "stranded_chips_observed": stranded_pre,
        "stranded_chips_after": stranded_post,
        "avg_stranded_chips_per_pass": round(stranded_pre / passes, 3)
        if passes else 0.0,
        "max_stranded_chips": max_stranded,
        "recovery_pct": round(recovery, 2),
        "pods": len(trace),
        "placed": placed_count,
        "never_placed": len(trace) - placed_count,
        "scorecard": {
            "time_weighted_util_pct": round(
                100.0 * util_integral / (total * now), 4)
            if total and now else 0.0,
            "rejection_rate": round(
                (len(trace) - placed_count) / len(trace), 4)
            if trace else None,
            "p99_pending_age_s": round(p99, 4),
        },
    }


def sweep_budgets(budgets=(0, 1, 2, 4), n_nodes: int = 8, chips: int = 4,
                  hbm: int = 16384, mesh: tuple[int, ...] | None = (2, 2),
                  spec: TraceSpec | None = None,
                  defrag_period: float = 20.0) -> list[dict[str, Any]]:
    """The budget sweep: identical trace + fleet per budget, so every
    difference in the reports is the repack budget's doing."""
    # moderate load on purpose (~60% offered): a saturated fleet has no
    # free chips to strand, an idle one nothing to repack — churn in the
    # middle is where departures leave diagonal half-empty meshes
    spec = spec or TraceSpec(
        n_pods=300, arrival_rate=0.5, mean_duration=40.0,
        sizes=(8192, 12288, 16384), multi_chip_fraction=0.3, seed=7)
    trace = synth_trace(spec)
    out = []
    for budget in budgets:
        fleet = Fleet.homogeneous(n_nodes, chips, hbm, mesh)
        out.append(run_defrag_sim(fleet, trace, budget,
                                  defrag_period=defrag_period))
    return out


def sweep_forecast(frag_weight: float = 0.6, budget: int = 2,
                   stranded_target_chips: int = 3,
                   n_nodes: int = 8, chips: int = 4, hbm: int = 16384,
                   mesh: tuple[int, ...] | None = (2, 2),
                   spec: TraceSpec | None = None,
                   defrag_period: float = 20.0,
                   pause_budget_s: float | None = None) -> dict[str, Any]:
    """The migration A/B the tentpole ships on: the IDENTICAL trace run
    two ways with the same move budget.

    - **react** — ``frag_weight=0``, no stranded target: defrag chases
      every stranded chip back to zero, paying a workload pause per
      move.
    - **forecast** — admission steers hole-soakers under fragmentation
      pressure and repack triggers only once the stranded gap exceeds
      ``stranded_target_chips``.

    The verdict keys say whether the forecast run held average stranded
    capacity below the target while performing STRICTLY fewer
    migrations — the claim the live ``TPUSHARE_FRAG_WEIGHT`` knob
    ships on: tolerate the fragmentation the fleet can absorb, spend
    checkpoint pauses only when the SLO is threatened."""
    spec = spec or TraceSpec(
        n_pods=300, arrival_rate=0.5, mean_duration=40.0,
        sizes=(8192, 12288, 16384), multi_chip_fraction=0.3, seed=7)
    trace = synth_trace(spec)
    runs = {}
    for label, w, tgt in (("react", 0.0, None),
                          ("forecast", frag_weight, stranded_target_chips)):
        fleet = Fleet.homogeneous(n_nodes, chips, hbm, mesh)
        runs[label] = run_defrag_sim(
            fleet, trace, budget, defrag_period=defrag_period,
            frag_weight=w, pause_budget_s=pause_budget_s,
            stranded_target_chips=tgt)
    react, fore = runs["react"], runs["forecast"]
    return {
        "frag_weight": frag_weight,
        "budget": budget,
        "stranded_target_chips": stranded_target_chips,
        "react": react,
        "forecast": fore,
        "verdict": {
            "react_moves": react["moves"],
            "forecast_moves": fore["moves"],
            "fewer_migrations": fore["moves"] < react["moves"],
            "react_avg_stranded": react["avg_stranded_chips_per_pass"],
            "forecast_avg_stranded": fore["avg_stranded_chips_per_pass"],
            "stranded_held_below_target": (
                fore["avg_stranded_chips_per_pass"]
                <= stranded_target_chips),
        },
    }
