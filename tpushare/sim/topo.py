"""Mesh-aware placement wind tunnel: topology-scored serving replicas.

The classic wind tunnel measures *whether* pods fit; this module
measures *where* — the ABI v7 question. Serving replicas declare a
``mesh-shape`` (their dp x tp JAX Mesh) and pay a **step-time tax**
when the box they land on has poor ICI contiguity: a replica's service
duration stretches by ``1 + slowdown * (1 - q)`` where ``q`` is the
achieved box's adjacency quality (:func:`adjacency_quality` fraction;
0 for scatter). That is the physical claim the tentpole monetizes —
collectives over a tight box ride short rings; a strung-out or
scattered replica burns its quantum on hops — rendered as the only
currency a scheduler simulation speaks: occupancy time.

Two legs replay the SAME trace over the SAME fleet:

- **mesh-aware** — requests carry the declared shape, so per-node
  selection walks congruent boxes first (``congruent_first``), and the
  node choice blends binpack leftover with adjacency exactly like the
  live Prioritize handler (normalize leftovers to 0..10, ``p_adj =
  10 * adj / ADJ_SCALE``, ``final = round((1-w) * p_bin + w * p_adj)``,
  first-best ties) at the guaranteed-tier effective weight.
- **shape-blind** — the identical loop with the shape stripped and
  weight 0: pure tightest-fit, today's behavior.

Both legs pay the same step-time tax, so the gate's claim is causal:
the blend buys its lower serving wait tail *by* landing replicas on
better boxes (the adjacency scorecard must be strictly better), not by
admitting fewer pods (utilization must hold). Because stretch shifts
departure times, the two legs' dynamics are COUPLED — a single
divergent choice cascades — so the pinned gate aggregates over
``GATE_SEEDS`` to average out placement chaos rather than betting the
claim on one trajectory. Pinned as
``tests/data/topo_wind_tunnel_golden.json``; re-pin deliberately with
``python -m tpushare.sim --topo --pin`` (docs/ops.md).

Everything is a pure function of (fleet, trace, knobs) — no wall
clock, no ambient randomness — so the golden is byte-reproducible.
"""

from __future__ import annotations

import heapq
import json
import os
import random
from dataclasses import dataclass, field, replace

from tpushare.core.placement import PlacementRequest, select_chips_py
from tpushare.core.topology import ADJ_SCALE, congruent
from tpushare.sim.simulator import Fleet, SimPod, _p99

# The gate workload: 2x2-replica serving traffic over 2x4 hosts, with
# single-chip fillers churning fast enough to fragment rows unevenly.
# Fillers are what make the two legs diverge — they carve nodes into
# states where one host still has a pristine 2x2 while another (often
# the binpack-tightest one) only has a 1x4 or worse left.
TOPO_GATE_FLEET = {"nodes": 8, "chips": 8, "hbm": 16384, "mesh": (2, 4)}
GATE_TOPO_WEIGHT = 0.5   # TPUSHARE_TOPO_WEIGHT default x guaranteed tier
GATE_SLOWDOWN = 1.5      # step-time stretch at q=0 (scatter)
# Chaos-averaging: the gate's numbers are means over these replays.
GATE_SEEDS = (23, 24, 25, 26, 27)

TOPO_GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "data",
    "topo_wind_tunnel_golden.json")

# same semantics as qos.QOS_DEFAULT_BANDS: deterministic replays, so
# bands absorb intended small shifts while a regression cannot hide
TOPO_DEFAULT_BANDS = {
    "time_weighted_util_pct": 1.5,
    "rejection_rate": 0.03,
    "p99_pending_age_s": 1.0,
}

# One-sided tolerances for the adjacency scorecard: quality may drift
# up freely, but a drop past these margins reds the gate. Sized so the
# shape-blind baseline leg violates every one of them (falsifiability).
TOPO_ADJ_TOL = {
    "mean_quality": 0.005,
    "congruent_rate": 0.02,
    "stretch_time": 1.5,
}


@dataclass(frozen=True)
class TopoSpec:
    """Synthetic serving+filler mix (sizes MiB, times abstract units)."""
    n_pods: int = 400
    arrival_rate: float = 28.0
    serve_fraction: float = 0.4      # 4-chip mesh-declared replicas
    serve_hbm: int = 6144
    serve_mean_duration: float = 1.6
    filler_sizes: tuple[int, ...] = (4096, 8192, 12288)
    filler_mean_duration: float = 2.4
    seed: int = 23


TOPO_GATE_SPEC = TopoSpec()


def synth_topo(spec: TopoSpec) -> list[SimPod]:
    """Seeded trace: serving replicas declare a (2, 2) mesh; fillers
    are single-chip and shape-blind. Deterministic in ``spec.seed``."""
    rng = random.Random(spec.seed)
    t = 0.0
    pods = []
    for _ in range(spec.n_pods):
        t += rng.expovariate(spec.arrival_rate)
        if rng.random() < spec.serve_fraction:
            dur = rng.expovariate(1.0 / spec.serve_mean_duration)
            pods.append(SimPod(t, dur, spec.serve_hbm, chip_count=4,
                               qos_tier="guaranteed",
                               mesh_shape=(2, 2)))
        else:
            dur = rng.expovariate(1.0 / spec.filler_mean_duration)
            pods.append(SimPod(t, dur, rng.choice(spec.filler_sizes)))
    return pods


@dataclass
class TopoSimReport:
    mesh_aware: bool
    topo_weight: float
    pods: int
    placed: int
    never_placed: int
    mean_wait: float
    p99_wait: float
    serve_p99_wait: float        # the gate's headline: replica wait tail
    util_pct: float              # granted bytes, time-weighted
    makespan: float
    # the adjacency scorecard (multi-chip placements only):
    adj_placements: int
    adj_mean: float              # 0..1 (1 = best box for the count)
    adj_min: float
    congruent_rate: float        # placements landing a declared-shape box
    stretch_time: float          # total extra occupancy paid to poor q
    by_kind: dict = field(default_factory=dict)
    waits: list[float] = field(default_factory=list, repr=False)

    def scorecard(self) -> dict:
        """Same currency as SimReport.scorecard / fleetwatch."""
        return {
            "time_weighted_util_pct": round(self.util_pct, 4),
            "rejection_rate": round(self.never_placed / self.pods, 4)
            if self.pods else None,
            "p99_pending_age_s": round(self.p99_wait, 4),
        }

    def adjacency(self) -> dict:
        """Same keys as the live fleet sampler's adjacency scorecard."""
        return {
            "placements": self.adj_placements,
            "mean_quality": round(self.adj_mean, 4),
            "min_quality": round(self.adj_min, 4),
            "congruent_rate": round(self.congruent_rate, 4),
            "stretch_time": round(self.stretch_time, 4),
        }

    def to_json(self) -> dict:
        out = {k: (round(v, 4) if isinstance(v, float) else v)
               for k, v in self.__dict__.items() if k != "waits"}
        out["scorecard"] = self.scorecard()
        out["adjacency"] = self.adjacency()
        return {k: out[k] for k in sorted(out)}


def _choose(fleet: Fleet, req: PlacementRequest, topo_weight: float):
    """One scheduling decision: per-node best placement via the real
    kernel, node choice via the live Prioritize arithmetic. Returns
    (node_index, Placement) or None."""
    cands = []
    for ni, node in enumerate(fleet.nodes):
        if node.down:
            continue
        p = select_chips_py(node.views(), node.topo, req)
        if p is not None:
            cands.append((ni, p))
    if not cands:
        return None
    if req.mesh_shape is None or topo_weight <= 0.0:
        # tightest fit, first-best ties — _policy_binpack's argmin
        return min(cands, key=lambda c: (c[1].score, c[0]))
    scores = [p.score for _ni, p in cands]
    lo, hi = min(scores), max(scores)
    best = None
    for ni, p in cands:
        p_bin = 10 if hi == lo else round(10 * (hi - p.score) / (hi - lo))
        p_adj = 10 * p.adjacency / ADJ_SCALE
        final = round((1.0 - topo_weight) * p_bin + topo_weight * p_adj)
        key = (-final, ni)  # scheduler picks max score, first-best ties
        if best is None or key < best[0]:
            best = (key, ni, p)
    return best[1], best[2]


def run_topo_sim(fleet: Fleet, trace: list[SimPod],
                 mesh_aware: bool = True,
                 topo_weight: float = GATE_TOPO_WEIGHT,
                 slowdown: float = GATE_SLOWDOWN) -> TopoSimReport:
    """Replay ``trace``; serving durations stretch with poor adjacency.

    ``mesh_aware=False`` strips every declared shape and zeroes the
    blend weight — the shape-blind baseline leg. The step-time tax
    applies to BOTH legs (physics does not care what the scheduler
    knew), which is what makes the A/B causal.
    """
    w = topo_weight if mesh_aware else 0.0
    heap: list[tuple] = []
    for seq, pod in enumerate(sorted(trace, key=lambda p: p.arrival)):
        heapq.heappush(heap, (pod.arrival, 1, seq, pod))
    pending: list[SimPod] = []
    waits: list[float] = []
    serve_waits: list[float] = []
    kind_counts: dict[str, list[int]] = {}
    for pod in trace:
        kind = "serve" if pod.mesh_shape is not None else "filler"
        kind_counts.setdefault(kind, [0, 0])[0] += 1
    placed = 0
    adj_samples: list[float] = []
    congruent_hits = 0
    stretch_total = 0.0
    active: dict[int, tuple] = {}
    now = 0.0
    last_t = 0.0
    util_integral = 0.0
    busy_start: float | None = None
    seq2 = len(trace)

    def advance(to: float) -> None:
        nonlocal util_integral, last_t
        dt = to - last_t
        if dt > 0:
            util_integral += fleet.used_hbm * dt
        last_t = to

    def req_of(pod: SimPod) -> PlacementRequest:
        return PlacementRequest(
            hbm_mib=pod.hbm_mib, chip_count=pod.chip_count,
            topology=pod.topology,
            mesh_shape=pod.mesh_shape if mesh_aware else None)

    def try_place(pod: SimPod) -> bool:
        nonlocal placed, seq2, congruent_hits, stretch_total
        got = _choose(fleet, req_of(pod), w)
        if got is None:
            return False
        ni, p = got
        node = fleet.nodes[ni]
        for cid in p.chip_ids:
            node.used[cid] += pod.hbm_mib
        q = max(0, p.adjacency) / ADJ_SCALE
        if pod.chip_count > 1:
            adj_samples.append(q)
            if pod.mesh_shape is not None and p.box is not None \
                    and congruent(p.box, pod.mesh_shape):
                congruent_hits += 1
        stretch = pod.duration * slowdown * (1.0 - q)
        stretch_total += stretch
        heapq.heappush(heap, (now + pod.duration + stretch, 0, seq2,
                              (ni, p.chip_ids, pod.hbm_mib)))
        active[seq2] = (pod, ni, p.chip_ids)
        seq2 += 1
        placed += 1
        kind = "serve" if pod.mesh_shape is not None else "filler"
        kind_counts.setdefault(kind, [0, 0])[1] += 1
        waits.append(now - pod.arrival)
        if pod.mesh_shape is not None:
            serve_waits.append(now - pod.arrival)
        return True

    while heap:
        t, kind, seq_id, payload = heapq.heappop(heap)
        advance(t)
        now = t
        if busy_start is None:
            busy_start = t
        if kind == 1:  # arrival
            if not try_place(payload):
                pending.append(payload)
        else:          # departure
            pod, ni, chip_ids = active.pop(seq_id)
            node = fleet.nodes[ni]
            for cid in chip_ids:
                node.used[cid] -= pod.hbm_mib
            pending = [q_ for q_ in pending if not try_place(q_)]

    span = max(last_t - (busy_start or 0.0), 1e-9)
    by_kind = {k: {"pods": n, "placed": pl}
               for k, (n, pl) in sorted(kind_counts.items())}
    return TopoSimReport(
        mesh_aware=mesh_aware,
        topo_weight=w,
        pods=len(trace),
        placed=placed,
        never_placed=len(pending),
        mean_wait=sum(waits) / len(waits) if waits else 0.0,
        p99_wait=_p99(waits),
        serve_p99_wait=_p99(serve_waits),
        util_pct=util_integral / (fleet.total_hbm * span) * 100.0,
        makespan=span,
        adj_placements=len(adj_samples),
        adj_mean=sum(adj_samples) / len(adj_samples)
        if adj_samples else 0.0,
        adj_min=min(adj_samples) if adj_samples else 0.0,
        congruent_rate=congruent_hits / len(adj_samples)
        if adj_samples else 0.0,
        stretch_time=stretch_total,
        by_kind=by_kind,
        waits=waits,
    )


# -- the pinned topo gate -----------------------------------------------------

def _gate_fleet() -> Fleet:
    return Fleet.homogeneous(
        TOPO_GATE_FLEET["nodes"], TOPO_GATE_FLEET["chips"],
        TOPO_GATE_FLEET["hbm"], TOPO_GATE_FLEET["mesh"])


def topo_gate_report(mesh_aware: bool = True,
                     topo_weight: float = GATE_TOPO_WEIGHT,
                     seed: int | None = None) -> TopoSimReport:
    """One gate replay: standard serving mix over the standard fleet.
    ``mesh_aware=False`` is the shape-blind baseline leg."""
    spec = TOPO_GATE_SPEC if seed is None else replace(TOPO_GATE_SPEC,
                                                       seed=seed)
    return run_topo_sim(_gate_fleet(), synth_topo(spec),
                        mesh_aware=mesh_aware, topo_weight=topo_weight)


def gate_aggregate(mesh_aware: bool = True,
                   topo_weight: float = GATE_TOPO_WEIGHT) -> dict:
    """Seed-averaged gate numbers — what the golden pins. Means over
    ``GATE_SEEDS`` so a single chaotic trajectory (stretch perturbs
    departure times, which perturbs every later choice) cannot decide
    the A/B either way."""
    reps = [topo_gate_report(mesh_aware=mesh_aware,
                             topo_weight=topo_weight, seed=s)
            for s in GATE_SEEDS]
    n = len(reps)
    return {
        "scorecard": {
            "time_weighted_util_pct":
                round(sum(r.util_pct for r in reps) / n, 4),
            "rejection_rate":
                round(sum(r.never_placed / r.pods for r in reps) / n, 4),
            "p99_pending_age_s":
                round(sum(r.p99_wait for r in reps) / n, 4),
        },
        "adjacency": {
            "placements": sum(r.adj_placements for r in reps),
            "mean_quality":
                round(sum(r.adj_mean for r in reps) / n, 4),
            "min_quality": round(min(r.adj_min for r in reps), 4),
            "congruent_rate":
                round(sum(r.congruent_rate for r in reps) / n, 4),
            "stretch_time":
                round(sum(r.stretch_time for r in reps) / n, 4),
        },
        "serve_p99_wait":
            round(sum(r.serve_p99_wait for r in reps) / n, 4),
    }


def weight_sweep(values: tuple[float, ...] = (0.0, 0.25, 0.5, 1.0)
                 ) -> dict:
    """Sweep TPUSHARE_TOPO_WEIGHT over the gate workload — the tuning
    question docs/perf.md sends operators here to answer. Weight 0.0 is
    byte-identical to the shape-blind leg (the blend multiplies out)."""
    rows = []
    for v in values:
        agg = gate_aggregate(mesh_aware=v > 0.0, topo_weight=v)
        rows.append({"topo_weight": v, **agg})
    return {"mode": "topo-sweep", "seeds": list(GATE_SEEDS),
            "rows": rows}


def pin_topo_golden(path: str | None = None,
                    bands: dict | None = None) -> dict:
    """Write the topo gate golden: the seed-averaged mesh-aware
    scorecard, the shape-blind baseline it must beat, and the adjacency
    evidence. Deliberate re-baselining ONLY (docs/ops.md)."""
    agg = gate_aggregate()
    base = gate_aggregate(mesh_aware=False)
    golden = {
        "gate_spec": {"n_pods": TOPO_GATE_SPEC.n_pods,
                      "arrival_rate": TOPO_GATE_SPEC.arrival_rate,
                      "serve_fraction": TOPO_GATE_SPEC.serve_fraction,
                      "seeds": list(GATE_SEEDS)},
        "gate_fleet": {k: (list(v) if isinstance(v, tuple) else v)
                       for k, v in TOPO_GATE_FLEET.items()},
        "topo_weight": GATE_TOPO_WEIGHT,
        "slowdown": GATE_SLOWDOWN,
        **agg,
        "baseline": base,
        "bands": dict(bands or TOPO_DEFAULT_BANDS),
    }
    path = path or TOPO_GOLDEN_PATH
    with open(path, "w") as f:
        json.dump(golden, f, indent=1, sort_keys=True)
        f.write("\n")
    return golden


def load_topo_golden(path: str | None = None) -> dict:
    with open(path or TOPO_GOLDEN_PATH) as f:
        return json.load(f)


def check_topo(agg: dict, golden: dict) -> list[str]:
    """Compare a gate aggregate against the pinned golden. Scorecard
    metrics are two-sided (within ``bands``); the adjacency scorecard
    is one-sided (improvement is free, degradation past ``TOPO_ADJ_TOL``
    is a violation); the headline serving tail must keep beating the
    pinned shape-blind baseline."""
    from tpushare.sim.autotune import check_scorecard
    violations = check_scorecard(agg["scorecard"], golden)
    adj, g = agg["adjacency"], golden["adjacency"]
    for key, tol in TOPO_ADJ_TOL.items():
        got, want = adj.get(key), g[key]
        if got is None:
            violations.append(f"adjacency.{key}: missing")
        elif key == "stretch_time":
            if got > want + tol:
                violations.append(
                    f"adjacency.{key}: {got} exceeds pinned {want} "
                    f"by more than {tol}")
        elif got < want - tol:
            violations.append(
                f"adjacency.{key}: {got} below pinned {want} "
                f"by more than {tol}")
    base_p99 = golden["baseline"]["serve_p99_wait"]
    if not agg["serve_p99_wait"] < base_p99:
        violations.append(
            f"serve_p99_wait: {agg['serve_p99_wait']} does not beat "
            f"the pinned shape-blind baseline {base_p99}")
    return violations
