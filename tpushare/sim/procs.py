"""Multi-process sim replay: wall-clock scale-out + determinism proof.

``python -m tpushare.sim --procs N`` runs the FULL standard replay in N
genuine OS processes (spawned interpreters — no shared state, no shared
GIL) and in one process, then reports aggregate placements/sec for
both. Two claims ride on it:

1. **determinism**: every process must emit a byte-identical canonical
   scorecard. The simulator is seeded and single-threaded, so any
   divergence across fresh interpreters is a real nondeterminism bug
   (hash randomization leaking into iteration order, time-dependent
   tie-breaks, ...) — exactly the class of bug that turns a sharded
   production fleet's replicas into silent disagreement.
2. **throughput**: N processes vs 1 is the honest multi-core number the
   in-process `--shards` mode cannot produce. The speedup is only
   ASSERTED (`speedup_asserted`) when the box has at least N cores;
   on fewer cores it is published informationally.

The worker lives here (not in ``__main__``) so `multiprocessing`'s
spawn pickling resolves it by module path regardless of how the CLI was
invoked.
"""

from __future__ import annotations

import json
import os
import time


def replay_once(payload: dict) -> str:
    """One full standard replay, rendered as canonical JSON (sorted
    keys) so byte-comparison across processes is meaningful.

    ``payload["engine"]`` selects the placement loop: ``"python"`` (the
    spec path, default — absent key keeps old payloads working) or
    ``"native"`` (the engine loop, tpushare/sim/engine_loop.py). The
    determinism claim is per-engine: N native workers must agree with
    each other byte-for-byte, and — because default-knob native replays
    are decision-identical to the spec — with the python arm too."""
    from tpushare.sim.simulator import (
        Fleet, TraceSpec, run_sim, synth_trace)
    spec = TraceSpec(**payload["spec"])
    trace = synth_trace(spec)
    mesh = tuple(payload["mesh"]) if payload.get("mesh") else None
    fleet = Fleet.homogeneous(payload["nodes"], payload["chips"],
                              payload["hbm"], mesh)
    if payload.get("engine", "python") == "native":
        from tpushare.sim.engine_loop import run_sim_native
        report, _stats = run_sim_native(fleet, trace)
    else:
        report = run_sim(fleet, trace, payload["policy"],
                         preempt=payload.get("preempt", "off"))
    return json.dumps(report.to_json(), sort_keys=True)


def run_procs(payload: dict, n_procs: int) -> dict:
    import multiprocessing as mp
    t0 = time.perf_counter()
    base = replay_once(payload)
    single_wall = time.perf_counter() - t0
    # spawn, not fork: each replica starts from a FRESH interpreter, so
    # the byte-identical claim covers interpreter-level state too
    ctx = mp.get_context("spawn")
    t0 = time.perf_counter()
    with ctx.Pool(n_procs) as pool:
        outs = pool.map(replay_once, [payload] * n_procs)
    wall = time.perf_counter() - t0
    pods = payload["spec"]["n_pods"]
    identical = all(o == base for o in outs)
    cores = os.cpu_count() or 1
    single_rate = pods / single_wall if single_wall else 0.0
    agg_rate = n_procs * pods / wall if wall else 0.0
    return {
        "mode": "procs",
        "engine": payload.get("engine", "python"),
        "procs": n_procs,
        "pods_per_proc": pods,
        "cores": cores,
        "single_wall_s": round(single_wall, 3),
        "procs_wall_s": round(wall, 3),
        "single_placements_per_sec": round(single_rate, 1),
        "aggregate_placements_per_sec": round(agg_rate, 1),
        "speedup": round(agg_rate / single_rate, 2) if single_rate
        else None,
        "speedup_asserted": cores >= n_procs,
        "scorecards_identical": identical,
        "scorecard": json.loads(base)["scorecard"],
    }
