"""Native-engine sim hot loop: the wind tunnel's placement engine.

:func:`tpushare.sim.simulator.run_sim` is the behavioral spec — every
arrival runs ``select_chips_py`` against every node, O(pods x nodes)
Python. That caps the simulator at policy-duel scale. This module
replays the SAME discrete-event protocol through the production
engine's resident :class:`~tpushare.core.native.engine.FleetArena`:

- **resident arena, delta accounting**: every node is an arena entry
  keyed by its index and stamped with a per-node mutation counter.
  Between events only the nodes an event actually touched move their
  stamp, so the arena re-packs exactly the mutated slots — a departure
  on one host re-syncs one slot, not 50k.
- **per-signature score residency**: the loop keeps one int64 score
  vector per request signature (the :func:`tpushare.cache.batch.
  request_signature` equivalence class — the same definition of "same
  pod" the server's BatchPlanner coalesces on). A signature's first use
  pays one fleet-wide ``arena.score`` call; afterwards each use
  re-scores only the nodes mutated since (the mutation log + a
  per-signature cursor), then the wave resolves with an argmin plus ONE
  single-entry ABI v4 ``arena.cycle`` call that materializes the
  winner's chips. Ties break to the lowest node index — exactly
  ``_policy_binpack``'s first-best-wins rule — so default-knob replays
  are decision-for-decision identical to the Python spec path and the
  standard-trace scorecards compare byte-for-byte (the parity gate in
  tests/test_sim_engine_loop.py).
- **no-fit fast path**: each signature tracks how many nodes currently
  fit; a departure wave whose pending signatures all sit at zero is
  skipped in O(distinct signatures), which is what keeps saturated
  spike windows from going quadratic in the backlog.

The remaining knobs deliberately DIVERGE from the spec path — they are
the policy surface ``--autotune`` sweeps (tpushare/sim/autotune.py):

- ``batch_window``  — coalesce arrivals inside a sim-time window and
  solve same-signature groups with the disjoint multi-pod semantics of
  ``tpushare_solve_batch`` (taken chips leave the pool, untouched nodes
  preferred — the BatchPlanner's solve, replayed offline).
- ``index_scheme``  — a conservative max-free prune (off/pow2/exact)
  over full and delta re-scores: certain-no-fit nodes skip the native
  scan. Pure throughput; pruning is superset-safe so decisions never
  change (the production capacity-index story, miniaturized).
- ``eqclass_lru``   — how many signature score vectors stay resident;
  an evicted signature pays a fresh fleet-wide scan on next use.
- ``defrag_budget`` / ``defrag_period`` — run the live repack planner
  (:func:`tpushare.defrag.planner.plan_moves`) every period with that
  move budget, applying moves as live migrations.
- ``scatter_util_pct`` — binpack-vs-scatter threshold: below this fleet
  utilization, scatter-tolerant multi-chip requests are forced
  contiguous (keep big boxes while there is room); 0 honors the
  request as written (spec behavior).

Concurrency: the loop itself is single-threaded. ``self._lock`` is the
arena-era bookkeeping lock — it guards ONLY the signature-table
(install/evict) and the progress counters that :meth:`EngineLoop.
snapshot` reads, so an autotune worker's progress can be observed from
another thread mid-run. It is never held across an arena call, a
native scan, or any placement work (the lock-order lint classifies it
accordingly).
"""

from __future__ import annotations

import heapq
from dataclasses import asdict, dataclass
import threading

from tpushare.cache.batch import request_signature
from tpushare.core.native import engine as native
from tpushare.core.placement import PlacementRequest
from tpushare.metrics import Counter, LabeledCounter
from tpushare.sim.simulator import (
    Fleet, SimPod, SimReport, _is_contiguous_box, _p99)

# wind-tunnel loop telemetry (docs/observability.md catalog): counters
# are bulk-incremented once per run — the sim is offline, what matters
# is the totals a bench/autotune harness can diff, not per-event cost
SIM_EVENTS = LabeledCounter(
    "tpushare_sim_events_total",
    "Wind-tunnel engine-loop events replayed, by kind (arrival / "
    "departure / flush = batch-window close / defrag_pass)",
    ("kind",))
SIM_SCORE_REFRESHES = LabeledCounter(
    "tpushare_sim_score_refreshes_total",
    "Signature score-vector refreshes in the engine loop: full = "
    "fleet-wide build (first use or post-LRU-eviction), delta = only "
    "the nodes the mutation log marked dirty. Full growth at steady "
    "state means the eqclass LRU is thrashing",
    ("path",))
SIM_PRUNED_NODES = Counter(
    "tpushare_sim_pruned_nodes_total",
    "Dirty or candidate nodes the engine loop's max-free index scheme "
    "skipped as certain no-fits without a native scan (index_scheme "
    "knob; pruning is superset-safe so decisions never change)")
SIM_BATCH_PODS = LabeledCounter(
    "tpushare_sim_batch_pods_total",
    "Pods leaving a closed batch window in the engine loop, by outcome "
    "(placed via the disjoint multi-pod solve, or pending when the "
    "group solve ran out of fleet)",
    ("outcome",))

# score-vector sentinel for "no placement on this node": large enough
# that a plain argmin lands on a real fit whenever one exists (real
# scores are bounded by total fleet HBM), so the hot path needs no mask
_NOFIT = 1 << 62

# dirty sets at or below this size refresh via per-node native selects
# (lower fixed cost than an arena gather, and every placement feeds the
# signature's memo); larger sets go through the arena in one call
_SELECT_THRESHOLD = 16


@dataclass(frozen=True)
class LoopKnobs:
    """The autotune policy surface. Defaults are the SPEC point: every
    knob at its default makes the loop decision-identical to run_sim."""

    batch_window: float = 0.0
    index_scheme: str = "off"        # off | pow2 | exact
    eqclass_lru: int = 32
    defrag_budget: int = 0
    defrag_period: float = 4.0
    scatter_util_pct: float = 0.0

    def __post_init__(self) -> None:
        if self.index_scheme not in ("off", "pow2", "exact"):
            raise ValueError(f"index_scheme {self.index_scheme!r} "
                             "not in off|pow2|exact")
        if self.batch_window < 0 or self.eqclass_lru < 1 \
                or self.defrag_budget < 0 or self.defrag_period <= 0:
            raise ValueError("bad knobs")


def _pow2_floor(v: int) -> int:
    return 1 << (v.bit_length() - 1) if v > 0 else 0


class _Sig:
    """One resident request-signature: its score vector (value =
    binpack score, _NOFIT = no placement), the count of fitting nodes,
    the mutation-log cursor of the last refresh, and a small
    placement memo (node -> (version, Placement)) fed by the refresh
    scans — in steady-state packing the argmin winner is usually a
    node the refresh just re-scored, so its placement is already
    materialized and the wave costs no extra native call."""

    __slots__ = ("req", "scores", "n_fit", "cursor", "pcache")

    def __init__(self, req, scores, n_fit, cursor) -> None:
        self.req = req
        self.scores = scores
        self.n_fit = n_fit
        self.cursor = cursor
        self.pcache: dict[int, tuple] = {}


class EngineLoop:
    """One wind-tunnel replay: fleet + trace + knobs -> SimReport.

    Use :func:`run_sim_native` unless you need mid-run :meth:`snapshot`
    access (the autotune progress reader).
    """

    def __init__(self, fleet: Fleet, knobs: LoopKnobs | None = None
                 ) -> None:
        import numpy as np
        self._np = np
        self.fleet = fleet
        self.knobs = knobs or LoopKnobs()
        n = len(fleet.nodes)
        self._n = n
        self._arena = native.FleetArena()
        # per-node delta accounting: mutation counter (the arena stamp)
        # and a lazily rebuilt ChipView snapshot, invalidated on mutation
        self._versions = [0] * n
        self._view_cache: list = [None] * n
        self._log: list[int] = []        # mutation log (node indices)
        # max-free index (the index_scheme prune) + exclusive-chip counts
        self._maxfree = np.fromiter(
            (nd.hbm - min(nd.used) for nd in fleet.nodes), np.int64, n)
        self._freechips = np.fromiter(
            (sum(1 for u in nd.used if u == 0) for nd in fleet.nodes),
            np.int64, n)
        # fragmentation bookkeeping: free-value histogram + lazy max-heap
        # (run_sim recomputes fragmentation() fleet-wide per event; this
        # maintains the same max(free)/total_free pair incrementally).
        # Fault-aware: the histogram and _free_sum span HEALTHY chips
        # only — exactly the set core.placement.fragmentation() reduces
        # over — while _used_total spans every chip (run_sim's
        # fleet.used_hbm does too: a pod finishing on a degraded chip
        # still occupies HBM until it departs)
        self._free_cnt: dict[int, int] = {}
        self._free_heap: list[int] = []
        self._total_hbm = fleet.total_hbm
        self._used_total = 0
        self._free_sum = 0
        for nd in fleet.nodes:
            for i, u in enumerate(nd.used):
                self._used_total += u
                if nd.chip_healthy(i):
                    f = nd.hbm - u
                    self._free_cnt[f] = self._free_cnt.get(f, 0) + 1
                    self._free_sum += f
        for f in self._free_cnt:
            heapq.heappush(self._free_heap, -f)
        # signature residency (the eqclass LRU)
        from collections import OrderedDict
        self._sigs: "OrderedDict[tuple, _Sig]" = OrderedDict()
        self._key_reqs: dict[tuple, PlacementRequest] = {}
        # arena-era bookkeeping lock: signature-table install/evict and
        # the snapshot counters ONLY — never held across an arena call
        # or native scan (lock-order lint: engine_loop.py/self._lock)
        self._lock = threading.Lock()
        # run state
        self._active: dict[int, tuple] = {}
        self._cancelled: set[int] = set()   # fault-killed departures
        self._stalled = 0                   # open brownout/crash windows
        self._dep_heap: list[tuple] = []
        self._pending: list[tuple] = []
        self._pending_keys: dict[tuple, int] = {}
        self._stable_sigs = self.knobs.scatter_util_pct <= 0
        self._waits: list[float] = []
        self._hp_waits: list[float] = []
        self._placed = 0
        self._violations = 0
        self._seq2 = 0
        self._now = 0.0
        self._last_t = 0.0
        self._util_integral = 0.0
        self._frag_integral = 0.0
        self._peak = 0.0
        self._busy_start: float | None = None
        # per-run stats (module metrics get the totals once, at the end)
        self._arrivals = self._departures = 0
        self._full_builds = self._delta_refreshes = 0
        self._rescored = self._pruned = self._sig_evictions = 0
        self._batch_groups = self._batch_pods = 0
        self._batch_pods_pending = 0
        self._defrag_passes = self._defrag_moves = 0
        self._faults_applied = self._fault_lost = 0

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        """Consistent multi-counter read for a concurrent observer
        (the autotune progress thread)."""
        with self._lock:
            return {"placed": self._placed,
                    "arrivals": self._arrivals,
                    "departures": self._departures,
                    "pending": len(self._pending),
                    "resident_sigs": len(self._sigs),
                    "sim_now": self._now}

    # -- node bookkeeping -----------------------------------------------------

    def _views_of(self, ni: int):
        v = self._view_cache[ni]
        if v is None:
            v = self.fleet.nodes[ni].views()
            self._view_cache[ni] = v
        return v

    def _entry(self, ni: int):
        return (ni, (0, self._versions[ni]), self._views_of(ni),
                self.fleet.nodes[ni].topo)

    def _mutate(self, ni: int, chip_ids, delta: int) -> None:
        node = self.fleet.nodes[ni]
        used = node.used
        hbm = node.hbm
        cnt = self._free_cnt
        # faulted chips are absent from the frag histogram (they are
        # invisible to fragmentation()); their used still moves
        faulted = node.down or node.unhealthy
        for cid in chip_ids:
            old = used[cid]
            new = old + delta
            assert 0 <= new <= hbm, "sim oversubscription"
            used[cid] = new
            if faulted and not node.chip_healthy(cid):
                continue
            of, nf = hbm - old, hbm - new
            c = cnt[of] - 1
            if c:
                cnt[of] = c
            else:
                del cnt[of]
            if nf in cnt:
                cnt[nf] += 1
            else:
                cnt[nf] = 1
                heapq.heappush(self._free_heap, -nf)
            self._free_sum -= delta
        self._used_total += delta * len(chip_ids)
        self._versions[ni] += 1
        self._view_cache[ni] = None
        self._log.append(ni)
        # on a faulted node these stay conservative OVERestimates (the
        # unhealthy chips' free counts in) — the index prune skips less
        # and never skips a node the native scan could place on
        self._maxfree[ni] = hbm - min(used)
        self._freechips[ni] = sum(1 for u in used if u == 0)

    def _exclude_chips(self, ni: int, cids) -> None:
        """Drop chips from the frag histogram (node_down / degrade)."""
        node = self.fleet.nodes[ni]
        cnt = self._free_cnt
        for cid in cids:
            f = node.hbm - node.used[cid]
            c = cnt[f] - 1
            if c:
                cnt[f] = c
            else:
                del cnt[f]
            self._free_sum -= f

    def _include_chips(self, ni: int, cids) -> None:
        """Re-admit chips to the frag histogram (node_up)."""
        node = self.fleet.nodes[ni]
        cnt = self._free_cnt
        for cid in cids:
            f = node.hbm - node.used[cid]
            if f in cnt:
                cnt[f] += 1
            else:
                cnt[f] = 1
                heapq.heappush(self._free_heap, -f)
            self._free_sum += f

    def _max_free_chip(self) -> int:
        heap, cnt = self._free_heap, self._free_cnt
        while heap and -heap[0] not in cnt:
            heapq.heappop(heap)
        return -heap[0] if heap else 0

    def _advance(self, to: float) -> None:
        dt = to - self._last_t
        if dt > 0:
            used = self._used_total
            self._util_integral += used * dt
            # _free_sum == total_hbm - used while the fleet is healthy;
            # under faults it is the healthy-chip free total, exactly
            # fragmentation()'s denominator
            total_free = self._free_sum
            frag = 0.0 if total_free == 0 \
                else 1.0 - self._max_free_chip() / total_free
            self._frag_integral += frag * dt
            self._peak = max(self._peak,
                             used / self._total_hbm * 100.0)
        self._last_t = to

    # -- fault schedule (ISSUE 13) --------------------------------------------

    def _fault_dirty(self, ni: int) -> None:
        """A fault changed a node's schedulability WITHOUT a chip-usage
        mutation: bump the version so resident score vectors, placement
        memos and the arena slot all see the node as dirty."""
        self._versions[ni] += 1
        self._view_cache[ni] = None
        self._log.append(ni)

    def _apply_fault(self, ev) -> None:
        """Mirror of run_sim's kind==-1 branch, byte-for-byte in its
        observable effects (tests/test_sim_faults.py proves it)."""
        self._faults_applied += 1
        kind = ev.kind
        if kind in ("brownout_start", "replica_crash"):
            self._stalled += 1
        elif kind in ("brownout_end", "replica_restart"):
            self._stalled = max(0, self._stalled - 1)
        elif kind == "node_down":
            ni = ev.node
            nd = self.fleet.nodes[ni]
            if not nd.down:
                self._exclude_chips(ni, [c for c in range(len(nd.used))
                                         if c not in nd.unhealthy])
                nd.down = True
            if ev.lose_pods:
                for vid in sorted(v for v, e in self._active.items()
                                  if e[0] == ni):
                    vni, chips, demand, pod = self._active.pop(vid)
                    self._mutate(vni, chips, -demand)
                    self._cancelled.add(vid)
                    self._fault_lost += 1
                    key, req = self._effective(pod)
                    self._pend(pod, req, key)
            self._fault_dirty(ni)
        elif kind == "node_up":
            ni = ev.node
            nd = self.fleet.nodes[ni]
            if nd.down:
                nd.down = False
                self._include_chips(ni, [c for c in range(len(nd.used))
                                         if c not in nd.unhealthy])
            self._fault_dirty(ni)
        elif kind == "degrade":
            ni = ev.node
            nd = self.fleet.nodes[ni]
            fresh = [c for c in ev.chips if c not in nd.unhealthy]
            if not nd.down:
                self._exclude_chips(ni, fresh)
            nd.unhealthy.update(fresh)
            self._fault_dirty(ni)
        # run_sim retries the pending FIFO after every fault unless a
        # stall window is open — capacity/schedulability may have moved
        if self._stalled == 0:
            self._retry_pending()

    # -- the index_scheme prune (superset-safe no-fit certificates) -----------

    def _prune_threshold(self, req) -> int:
        if req.hbm_mib == 0:
            return 0
        if self.knobs.index_scheme == "exact":
            return req.hbm_mib
        return _pow2_floor(req.hbm_mib)      # coarser tier: prunes less

    def _pruned_node(self, ni: int, req) -> bool:
        if self.knobs.index_scheme == "off":
            return False
        if req.hbm_mib == 0:
            return int(self._freechips[ni]) < req.chip_count
        return int(self._maxfree[ni]) < self._prune_threshold(req)

    def _candidates(self, req):
        """Full-build candidate set after pruning (node index list)."""
        np = self._np
        if self.knobs.index_scheme == "off":
            return range(self._n)
        if req.hbm_mib == 0:
            keep = self._freechips >= req.chip_count
        else:
            keep = self._maxfree >= self._prune_threshold(req)
        idxs = np.nonzero(keep)[0]
        self._pruned += self._n - len(idxs)
        return [int(i) for i in idxs]

    # -- signature score residency --------------------------------------------

    def _get_sig(self, key: tuple, req) -> _Sig:
        sig = self._sigs.get(key)
        if sig is not None:
            self._sigs.move_to_end(key)
            return sig
        np = self._np
        scores = np.full(self._n, _NOFIT, np.int64)
        cursor = len(self._log)
        cand = self._candidates(req)
        n_fit = 0
        sig = _Sig(req, scores, n_fit, cursor)
        if len(cand):
            # the whole-fleet build is ONE resident-arena cycle_fleet
            # call: scores for every candidate plus the best entry's
            # materialized Placement — so the wave that faulted this
            # signature in resolves from this same call (the memo)
            entries = [self._entry(ni) for ni in cand]
            out = self._arena.cycle(entries, req)
            for ni, (s, p) in zip(cand, out):
                if s is not None:
                    scores[ni] = s
                    n_fit += 1
                    if p is not None:
                        sig.pcache[ni] = (self._versions[ni], p)
            sig.n_fit = n_fit
        self._key_reqs.setdefault(key, req)
        with self._lock:
            self._sigs[key] = sig
            self._full_builds += 1
            lru = self.knobs.eqclass_lru
            while len(self._sigs) > lru:
                self._sigs.popitem(last=False)
                self._sig_evictions += 1
        return sig

    def _refresh(self, sig: _Sig) -> None:
        log = self._log
        if sig.cursor >= len(log):
            return
        dirty = sorted(set(log[sig.cursor:]))
        sig.cursor = len(log)
        scores = sig.scores
        scan = []
        for ni in dirty:
            if self._pruned_node(ni, sig.req):
                if scores[ni] != _NOFIT:
                    sig.n_fit -= 1
                    scores[ni] = _NOFIT
                self._pruned += 1
            else:
                scan.append(ni)
        if scan:
            if len(scan) <= _SELECT_THRESHOLD:
                # a handful of dirty nodes: per-node native selects are
                # cheaper than an arena gather AND hand back every
                # node's placement for the memo (same kernel, same
                # scores — the arena path is the same math at scale)
                pcache = sig.pcache
                if len(pcache) > 64:
                    pcache.clear()
                for ni in scan:
                    p = native.select_chips(
                        self._views_of(ni), self.fleet.nodes[ni].topo,
                        sig.req)
                    old_fit = int(scores[ni]) != _NOFIT
                    if p is None:
                        scores[ni] = _NOFIT
                        sig.n_fit -= old_fit
                    else:
                        scores[ni] = p.score
                        sig.n_fit += 1 - old_fit
                        pcache[ni] = (self._versions[ni], p)
            else:
                entries = [self._entry(ni) for ni in scan]
                out = self._arena.cycle(entries, sig.req)
                for ni, (s, p) in zip(scan, out):
                    old_fit = int(scores[ni]) != _NOFIT
                    new = _NOFIT if s is None else s
                    scores[ni] = new
                    sig.n_fit += (new != _NOFIT) - old_fit
                    if p is not None:
                        sig.pcache[ni] = (self._versions[ni], p)
            self._rescored += len(scan)
        self._delta_refreshes += 1

    def _winner_placement(self, ni: int, req, sig: _Sig | None = None):
        if sig is not None:
            hit = sig.pcache.get(ni)
            if hit is not None and hit[0] == self._versions[ni]:
                return hit[1]
        p = native.select_chips(self._views_of(ni),
                                self.fleet.nodes[ni].topo, req)
        assert p is not None, "cached fit vanished without a mutation"
        if sig is not None:
            sig.pcache[ni] = (self._versions[ni], p)
        return p

    # -- placement ------------------------------------------------------------

    def _effective(self, pod: SimPod):
        """The request as policy sees it: the scatter_util_pct knob may
        force contiguity while the fleet still has room."""
        req = pod.request
        if self.knobs.scatter_util_pct > 0 and req.allow_scatter \
                and self._used_total < self._total_hbm \
                * self.knobs.scatter_util_pct / 100.0:
            req = PlacementRequest(req.hbm_mib, req.chip_count,
                                   req.topology, allow_scatter=False)
        return request_signature(req), req

    def _place(self, pod: SimPod, ni: int, p, req) -> None:
        node = self.fleet.nodes[ni]
        if pod.topology is not None and not (
                p.box == pod.topology or _is_contiguous_box(
                    node.topo, p.chip_ids, pod.topology)):
            self._violations += 1
        demand = req.chip_demand_mib(node.hbm)
        self._mutate(ni, p.chip_ids, demand)
        vid = self._seq2
        self._seq2 += 1
        self._active[vid] = (ni, p.chip_ids, demand, pod)
        heapq.heappush(self._dep_heap, (self._now + pod.duration, vid))
        self._placed += 1
        wait = self._now - pod.arrival
        self._waits.append(wait)
        if pod.priority > 0:
            self._hp_waits.append(wait)

    def _try_place_now(self, pod: SimPod, key: tuple, req) -> bool:
        sig = self._get_sig(key, req)
        self._refresh(sig)
        if sig.n_fit == 0:
            return False
        ni = int(self._np.argmin(sig.scores))
        self._place(pod, ni, self._winner_placement(ni, req, sig), req)
        return True

    def _pend(self, pod: SimPod, req, key: tuple) -> None:
        self._pending.append((pod, req, key))
        if self._stable_sigs:
            # a pod can pend before its signature ever scanned (stalled
            # arrival, fault-killed restart): register the request so
            # the no-fit fast path can fault the signature in later
            self._key_reqs.setdefault(key, req)
            self._pending_keys[key] = self._pending_keys.get(key, 0) + 1

    def _retry_pending(self) -> None:
        """One FIFO pass over pending, exactly run_sim's departure
        semantics — with an O(distinct signatures) skip when nothing
        can fit anywhere (the saturated-backlog fast path)."""
        if not self._pending:
            return
        if self._stable_sigs:
            any_fit = False
            for key in self._pending_keys:
                sig = self._get_sig(key, self._key_reqs[key])
                self._refresh(sig)
                if sig.n_fit:
                    any_fit = True
                    break
            if not any_fit:
                return
        still = []
        for pod, req, key in self._pending:
            if not self._stable_sigs:
                key, req = self._effective(pod)
            if not self._try_place_now(pod, key, req):
                still.append((pod, req, key))
        self._pending = still
        if self._stable_sigs:
            keys: dict[tuple, int] = {}
            for _pod, _req, key in still:
                keys[key] = keys.get(key, 0) + 1
            self._pending_keys = keys

    # -- batched waves (the BatchPlanner's solve, replayed offline) -----------

    def _solve_excluding(self, ni: int, req, taken: set):
        views = [v.with_healthy(False) if v.idx in taken else v
                 for v in self._views_of(ni)]
        return native.select_chips(views, self.fleet.nodes[ni].topo, req)

    def _solve_group(self, key: tuple, req, k: int) -> list:
        """k chip-disjoint placements for one signature group — the
        semantics of ``tpushare_solve_batch`` (taken chips leave the
        pool entirely; untouched nodes preferred over ANY touched
        node's score; ties to the lowest node index), computed against
        the resident score vector instead of a fresh fleet marshal."""
        np = self._np
        sig = self._get_sig(key, req)
        self._refresh(sig)
        scores = sig.scores
        out = []
        taken: dict[int, set] = {}
        touched: dict[int, object] = {}
        saved: dict[int, int] = {}
        for _ in range(k):
            ni = int(np.argmin(scores))
            if scores[ni] != _NOFIT:             # best untouched node
                p = self._winner_placement(ni, req, sig)
                saved[ni] = int(scores[ni])
                scores[ni] = _NOFIT              # mask: now touched
            else:                                # only touched nodes left
                best = None
                for ti, tp in touched.items():
                    if tp is not None and (best is None
                                           or (tp.score, ti) < best[:2]):
                        best = (tp.score, ti, tp)
                if best is None:
                    break
                ni, p = best[1], best[2]
            out.append((ni, p))
            taken.setdefault(ni, set()).update(p.chip_ids)
            touched[ni] = self._solve_excluding(ni, req, taken[ni])
        for ni, s in saved.items():
            if scores[ni] == _NOFIT:             # restore masked reality
                scores[ni] = s
        return out

    def _flush(self, buf: list) -> None:
        if self._stalled:
            # window closed inside a brownout: nothing can bind
            for pod in buf:
                key, req = self._effective(pod)
                self._pend(pod, req, key)
            return
        groups: dict[tuple, list] = {}
        order: list[tuple] = []
        for pod in buf:
            key, req = self._effective(pod)
            g = groups.get(key)
            if g is None:
                groups[key] = g = [req]
                order.append(key)
            g.append(pod)
        for key in order:
            req, *members = groups[key]
            if len(members) == 1:
                if not self._try_place_now(members[0], key, req):
                    self._pend(members[0], req, key)
                continue
            self._batch_groups += 1
            placements = self._solve_group(key, req, len(members))
            for i, pod in enumerate(members):
                if i < len(placements):
                    self._place(pod, placements[i][0],
                                placements[i][1], req)
                    self._batch_pods += 1
                else:
                    self._pend(pod, req, key)
                    self._batch_pods_pending += 1

    # -- defrag passes (the live repack planner, applied as migrations) -------

    def _defrag_pass(self) -> None:
        from tpushare.defrag.planner import NodeState, Victim, plan_moves
        victims: dict[int, list] = {}
        for vid, (ni, chips, demand, pod) in self._active.items():
            victims.setdefault(ni, []).append(Victim(
                pod_key=str(vid), chip_ids=chips, per_chip_mib=demand,
                request=pod.request))
        states = [NodeState(
            name=nd.name, stamp=(0, self._versions[ni]), topo=nd.topo,
            hbm_per_chip=nd.hbm, views=self._views_of(ni),
            victims=victims.get(ni, []))
            for ni, nd in enumerate(self.fleet.nodes)]
        by_name = {nd.name: ni for ni, nd in enumerate(self.fleet.nodes)}
        np = self._np

        def solve(req, exclude, claimed):
            key = request_signature(req)
            sig = self._get_sig(key, req)
            self._refresh(sig)
            scores = sig.scores
            masked = sorted({by_name[n] for n in exclude}
                            | {by_name[n] for n in claimed})
            saved = scores[masked].copy() if masked else None
            if masked:
                scores[masked] = _NOFIT
            ni = int(np.argmin(scores))
            s = int(scores[ni])
            if masked:
                scores[masked] = saved
            best = (s, ni, None) if s != _NOFIT else None
            for name, chips in claimed.items():
                ci = by_name[name]
                if name in exclude:
                    continue
                views = [v.with_used(v.total_hbm_mib)
                         if v.idx in chips else v
                         for v in self._views_of(ci)]
                p = native.select_chips(views,
                                        self.fleet.nodes[ci].topo, req)
                if p is not None and (best is None
                                      or (p.score, ci) < best[:2]):
                    best = (p.score, ci, p)
            if best is None:
                return None
            s, ni, p = best
            if p is None:
                p = self._winner_placement(ni, req, sig)
            return (self.fleet.nodes[ni].name, p,
                    (0, self._versions[ni]))

        plan = plan_moves(states, solve, self.knobs.defrag_budget,
                          per_node=self.knobs.defrag_budget)
        self._defrag_passes += 1
        for m in plan.moves:
            vid = int(m.pod_key)
            entry = self._active.get(vid)
            if entry is None:
                continue
            ni, chips, demand, pod = entry
            self._mutate(ni, chips, -demand)
            tni = by_name[m.target]
            self._mutate(tni, m.placement.chip_ids, demand)
            # live migration: the departure event keys into _active, so
            # the pod simply departs from its NEW chips at its old time
            self._active[vid] = (tni, m.placement.chip_ids, demand, pod)
            self._defrag_moves += 1

    # -- the event loop -------------------------------------------------------

    def run(self, trace, faults=None) -> SimReport:
        """Replay ``trace`` (list or arrival-ordered iterator of
        SimPod). Event ordering is run_sim's exactly: faults before
        departures before arrivals at equal times, departures by
        placement order, trace order among simultaneous arrivals — so
        default-knob replays yield byte-identical scorecards.
        ``faults`` is the same time-sorted FaultEvent list run_sim
        takes (tpushare.sim.traces.synth_faults)."""
        INF = float("inf")
        if isinstance(trace, list):
            trace = sorted(trace, key=lambda p: p.arrival)
        arrivals = iter(trace)
        nxt = next(arrivals, None)
        dep = self._dep_heap
        window = self.knobs.batch_window
        buf: list[SimPod] = []
        flush_at = INF
        defrag_on = self.knobs.defrag_budget > 0
        next_defrag = self.knobs.defrag_period if defrag_on else INF
        faults = list(faults) if faults else []
        fi = 0
        nfaults = len(faults)
        pods = 0
        flushes = 0
        while nxt is not None or dep or buf or fi < nfaults:
            ta = nxt.arrival if nxt is not None else INF
            td = dep[0][0] if dep else INF
            tf = flush_at if buf else INF
            tflt = faults[fi].time if fi < nfaults else INF
            # defrag is a maintenance tick, not workload: it only fires
            # while real events remain, so a drained sim terminates
            tdf = next_defrag if defrag_on and (nxt is not None or dep) \
                else INF
            t = min(ta, td, tf, tdf, tflt)
            if tflt <= t:                  # fault (wins ALL ties, as
                self._advance(tflt)        # run_sim's kind -1 does)
                self._now = tflt
                if self._busy_start is None:
                    self._busy_start = tflt
                self._apply_fault(faults[fi])
                fi += 1
                continue
            if tf <= t:                    # close the batch window
                self._advance(tf)
                self._now = tf
                if self._busy_start is None:
                    self._busy_start = tf
                batch, buf, flush_at = buf, [], INF
                self._flush(batch)
                flushes += 1
                continue
            if tdf <= t:                   # defrag tick
                self._advance(tdf)
                self._now = tdf
                next_defrag += self.knobs.defrag_period
                self._defrag_pass()
                continue
            if td <= t:                    # departure (wins arrival ties)
                _, vid = heapq.heappop(dep)
                self._advance(td)
                self._now = td
                if self._busy_start is None:
                    self._busy_start = td
                if vid in self._cancelled:
                    # fault-killed earlier: chips already freed then
                    self._cancelled.discard(vid)
                    continue
                ni, chip_ids, demand, _pod = self._active.pop(vid)
                self._mutate(ni, chip_ids, -demand)
                self._departures += 1
                if not self._stalled:
                    self._retry_pending()
                continue
            # arrival
            self._advance(ta)
            self._now = ta
            if self._busy_start is None:
                self._busy_start = ta
            pods += 1
            self._arrivals += 1
            if window > 0:
                if not buf:
                    flush_at = ta + window
                buf.append(nxt)
            elif self._stalled:
                key, req = self._effective(nxt)
                self._pend(nxt, req, key)
            else:
                key, req = self._effective(nxt)
                if not self._try_place_now(nxt, key, req):
                    self._pend(nxt, req, key)
            nxt = next(arrivals, None)

        # telemetry lands once per run (the sim is offline: totals, not
        # per-event increments, are what observers diff)
        SIM_EVENTS.inc("arrival", n=self._arrivals)
        SIM_EVENTS.inc("departure", n=self._departures)
        if flushes:
            SIM_EVENTS.inc("flush", n=flushes)
        if self._defrag_passes:
            SIM_EVENTS.inc("defrag_pass", n=self._defrag_passes)
        if self._faults_applied:
            SIM_EVENTS.inc("fault", n=self._faults_applied)
        if self._full_builds:
            SIM_SCORE_REFRESHES.inc("full", n=self._full_builds)
        if self._delta_refreshes:
            SIM_SCORE_REFRESHES.inc("delta", n=self._delta_refreshes)
        if self._pruned:
            SIM_PRUNED_NODES.inc(self._pruned)
        if self._batch_pods:
            SIM_BATCH_PODS.inc("placed", n=self._batch_pods)
        if self._batch_pods_pending:
            SIM_BATCH_PODS.inc("pending", n=self._batch_pods_pending)

        waits = self._waits
        hp = self._hp_waits
        span = max(self._last_t - (self._busy_start or 0.0), 1e-9)
        return SimReport(
            policy="binpack",
            pods=pods,
            placed=self._placed,
            never_placed=len(self._pending),
            mean_wait=sum(waits) / len(waits) if waits else 0.0,
            p99_wait=_p99(waits),
            util_pct=self._util_integral / (self._total_hbm * span)
            * 100.0,
            peak_util_pct=self._peak,
            frag_time_weighted=self._frag_integral / span,
            makespan=span,
            contig_violations=self._violations,
            hp_mean_wait=sum(hp) / len(hp) if hp else 0.0,
            hp_p99_wait=_p99(hp),
            faults_applied=self._faults_applied,
            fault_lost_pods=self._fault_lost,
            waits=waits,
        )

    def stats(self) -> dict:
        """Engine-loop internals for bench/autotune output (NOT part of
        the scorecard — never feeds a ranking)."""
        return {
            "engine": "native" if native.available()
            else "python-fallback",
            "arrivals": self._arrivals,
            "departures": self._departures,
            "full_builds": self._full_builds,
            "delta_refreshes": self._delta_refreshes,
            "rescored_nodes": self._rescored,
            "pruned_nodes": self._pruned,
            "resident_sigs": len(self._sigs),
            "sig_evictions": self._sig_evictions,
            "batch_groups": self._batch_groups,
            "batch_pods_placed": self._batch_pods,
            "batch_pods_pending": self._batch_pods_pending,
            "defrag_passes": self._defrag_passes,
            "defrag_moves": self._defrag_moves,
            "faults_applied": self._faults_applied,
            "fault_lost_pods": self._fault_lost,
            "knobs": asdict(self.knobs),
            "arena": self._arena.describe(),
        }


def run_sim_native(fleet: Fleet, trace,
                   knobs: LoopKnobs | None = None,
                   faults=None) -> tuple[SimReport, dict]:
    """The wind tunnel's entry point: replay ``trace`` over ``fleet``
    through the native engine loop. Returns (report, stats) — the
    report is scorecard-compatible with :func:`run_sim` and, at default
    knobs, byte-identical to it (with or without a ``faults``
    schedule)."""
    loop = EngineLoop(fleet, knobs)
    report = loop.run(trace, faults=faults)
    return report, loop.stats()
