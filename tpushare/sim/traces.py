"""Diurnal wind-tunnel traces: multi-hour arrival mixes at fleet scale.

The standard :func:`tpushare.sim.simulator.synth_trace` draws a flat
Poisson arrival process — fine for policy duels on a dozen hosts,
useless for the capacity questions ROADMAP item 4 asks ("what does MY
workload mix do to a 50k-node fleet across a business day?"). This
module synthesizes that day:

- **diurnal arrival rate**: a seeded inhomogeneous Poisson process whose
  rate follows a sinusoid between ``base_rate`` (trough, t=0) and
  ``peak_rate`` (peak, half a period later), sampled by thinning — the
  textbook exact method: propose at the ceiling rate, accept with
  probability rate(t)/ceiling, so the empirical arrival count over any
  window converges to the rate integral (tests/test_sim_traces.py
  checks exactly that).
- **spike windows**: multiplicative bursts (a failover, a launch, a
  batch-job wave) on top of the sinusoid, landing exactly where
  configured.
- **tiered pod shapes**: a weighted mix of request tiers (single-chip
  HBM slices through exclusive topology-pinned quads), each with its
  own mean duration — churn differs per tier, as it does in real
  fleets (inference replicas cycle fast, training jobs squat).

Everything is a pure function of the spec (``random.Random(seed)``,
no wall clock), so traces are byte-reproducible across processes —
the property the autotune ranking and the ``--procs`` determinism
proof both sit on. :func:`iter_diurnal` streams pods in arrival order
so a million-pod trace never has to be resident (the engine loop
consumes the iterator directly); :func:`synth_diurnal` materializes a
list for the parity/oracle paths.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Iterator

from tpushare.sim.simulator import Fleet, SimPod

# default period of the diurnal sinusoid, in trace time units ("hours")
DAY = 24.0


@dataclass(frozen=True)
class PodTier:
    """One shape class in the workload mix. ``weight`` is relative;
    ``mean_duration`` is this tier's churn knob (expovariate holding
    time, same distribution the flat-trace generator uses)."""

    name: str
    weight: float
    hbm_mib: int
    chip_count: int = 1
    topology: tuple[int, ...] | None = None
    mean_duration: float = 1.0
    priority: int = 0
    qos_tier: str = "burstable"


@dataclass(frozen=True)
class SpikeWindow:
    """Multiplicative arrival burst over [start, start + duration)."""

    start: float
    duration: float
    multiplier: float

    def active(self, t: float) -> bool:
        return self.start <= t < self.start + self.duration


# The default mix: mostly single-chip inference slices with fast churn,
# a long tail of topology-pinned training quads that squat. Weights and
# sizes are v5e-flavored (16 GiB chips); tests pin the proportions.
DEFAULT_TIERS: tuple[PodTier, ...] = (
    PodTier("s1-2g", 0.45, 2048, mean_duration=0.5),
    PodTier("s1-4g", 0.25, 4096, mean_duration=1.0),
    PodTier("s1-8g", 0.12, 8192, mean_duration=2.0),
    PodTier("pair-4g", 0.08, 4096, chip_count=2, mean_duration=1.5),
    PodTier("quad-2x2", 0.07, 4096, chip_count=4, topology=(2, 2),
            mean_duration=3.0),
    PodTier("excl-2x2", 0.03, 0, chip_count=4, topology=(2, 2),
            mean_duration=4.0),
)


@dataclass(frozen=True)
class DiurnalSpec:
    """Knobs of one wind-tunnel day (or several). Rates are arrivals
    per time unit; the sinusoid troughs at t=0 and peaks at DAY/2."""

    hours: float = 24.0
    base_rate: float = 40.0
    peak_rate: float = 160.0
    tiers: tuple[PodTier, ...] = DEFAULT_TIERS
    spikes: tuple[SpikeWindow, ...] = ()
    seed: int = 0
    period: float = DAY

    def __post_init__(self) -> None:
        if self.hours <= 0 or self.base_rate < 0 \
                or self.peak_rate < self.base_rate:
            raise ValueError("bad diurnal spec (hours > 0, "
                             "0 <= base_rate <= peak_rate)")
        if not self.tiers or any(t.weight <= 0 for t in self.tiers):
            raise ValueError("tiers must be non-empty with "
                             "positive weights")


def rate_at(spec: DiurnalSpec, t: float) -> float:
    """Instantaneous arrival rate at trace time ``t`` — the spec the
    thinning sampler realizes and the integral test integrates."""
    lam = spec.base_rate + (spec.peak_rate - spec.base_rate) * 0.5 * (
        1.0 - math.cos(2.0 * math.pi * t / spec.period))
    for s in spec.spikes:
        if s.active(t):
            lam *= s.multiplier
    return lam


def expected_arrivals(spec: DiurnalSpec, t0: float = 0.0,
                      t1: float | None = None, steps: int = 4096) -> float:
    """Numeric integral of :func:`rate_at` over [t0, t1] (midpoint
    rule): the expected arrival count the trace realizes in that
    window, up to Poisson noise."""
    if t1 is None:
        t1 = spec.hours
    dt = (t1 - t0) / steps
    return sum(rate_at(spec, t0 + (i + 0.5) * dt)
               for i in range(steps)) * dt


def iter_diurnal(spec: DiurnalSpec) -> Iterator[SimPod]:
    """Stream the trace in arrival order (thinning sampler). Pure
    function of the spec; a million-pod day never lives in memory."""
    rng = random.Random(spec.seed)
    ceiling = spec.peak_rate * max(
        [1.0] + [s.multiplier for s in spec.spikes if s.multiplier > 1.0])
    if ceiling <= 0:
        return
    weights = [t.weight for t in spec.tiers]
    cum = []
    acc = 0.0
    for w in weights:
        acc += w
        cum.append(acc)
    total_w = acc
    t = 0.0
    while True:
        t += rng.expovariate(ceiling)
        if t >= spec.hours:
            return
        # thinning: accept proposals at the instantaneous/ceiling ratio
        if rng.random() * ceiling >= rate_at(spec, t):
            continue
        r = rng.random() * total_w
        tier = spec.tiers[-1]
        for i, c in enumerate(cum):
            if r < c:
                tier = spec.tiers[i]
                break
        duration = rng.expovariate(1.0 / tier.mean_duration)
        yield SimPod(arrival=t, duration=duration, hbm_mib=tier.hbm_mib,
                     chip_count=tier.chip_count, topology=tier.topology,
                     priority=tier.priority, qos_tier=tier.qos_tier)


def synth_diurnal(spec: DiurnalSpec) -> list[SimPod]:
    """Materialized form of :func:`iter_diurnal` for the oracle paths
    (run_sim wants a list; parity tests replay both engines over the
    same object)."""
    return list(iter_diurnal(spec))


@dataclass(frozen=True)
class GangSpec:
    """Knobs of a gang-heavy slice workload (the ``--gangs`` leg):
    cross-host exclusive gangs (shapes that CANNOT fit one host box, so
    they exist only under slice-aware placement) over a single-chip
    sharing-tenant background. Pure function of the seed, like every
    other trace generator here."""

    n_pods: int = 200
    seed: int = 0
    gang_fraction: float = 0.5
    # default shapes target a v5e-16 (2x2 hosts of 2x2 chips): 2x4 and
    # 4x2 each span two hosts in one axis; 2x2 fits one host and keeps
    # the solver honest about NOT crossing hosts when it needn't
    shapes: tuple[tuple[int, ...], ...] = ((2, 4), (4, 2), (2, 2))
    arrival_rate: float = 1.0
    mean_duration: float = 30.0
    single_hbm: tuple[int, ...] = (4096, 8192)

    def __post_init__(self) -> None:
        if self.n_pods <= 0 or not (0.0 <= self.gang_fraction <= 1.0) \
                or self.arrival_rate <= 0 or self.mean_duration <= 0:
            raise ValueError("bad gang spec")
        if not self.shapes:
            raise ValueError("gang spec needs at least one shape")


def synth_gangs(spec: GangSpec) -> list[SimPod]:
    """Materialize the gang-heavy trace: Poisson arrivals, expovariate
    holds, gang shapes drawn uniformly from ``spec.shapes`` (exclusive:
    hbm_mib=0 means whole-chip demand), singles from
    ``spec.single_hbm``."""
    rng = random.Random(spec.seed)
    t = 0.0
    out: list[SimPod] = []
    for _ in range(spec.n_pods):
        t += rng.expovariate(spec.arrival_rate)
        dur = rng.expovariate(1.0 / spec.mean_duration)
        if rng.random() < spec.gang_fraction:
            shape = rng.choice(spec.shapes)
            n = 1
            for d in shape:
                n *= d
            out.append(SimPod(arrival=t, duration=dur, hbm_mib=0,
                              chip_count=n, topology=tuple(shape)))
        else:
            out.append(SimPod(arrival=t, duration=dur,
                              hbm_mib=rng.choice(spec.single_hbm),
                              chip_count=1))
    return out


def synth_fleet(n_nodes: int, chips: int = 4, hbm: int = 16384,
                mesh: tuple[int, ...] | None = (2, 2)) -> Fleet:
    """Fleet synthesis to wind-tunnel scale. Thin veneer over
    Fleet.homogeneous, named so call sites read as what they are —
    bench.py builds 50k-node fleets through this."""
    return Fleet.homogeneous(n_nodes, chips, hbm, mesh)


# -- fault schedules (the fault-domain wind tunnel, ISSUE 13) ----------------

# FaultEvent.kind values. Node-scoped kinds carry ``node`` (and
# ``chips`` for degradation); the fleet-scoped stall kinds
# (brownout / replica crash) carry no target — the sim models one
# logical scheduler, so any of them pauses scheduling; the chaos
# conductor maps ``replica`` onto a real process instead.
FAULT_KINDS = ("node_down", "node_up", "degrade",
               "brownout_start", "brownout_end",
               "replica_crash", "replica_restart")


@dataclass(frozen=True)
class FaultEvent:
    """One deterministic fault at one sim instant.

    - ``node_down`` / ``node_up``: the node becomes unschedulable /
      schedulable again. ``lose_pods`` on the down edge kills every
      running pod on the node (they restart: full duration, wait keyed
      to original arrival — a crash); False models NotReady (running
      pods survive, nothing new lands).
    - ``degrade``: ``chips`` drop out of the node's healthy set
      permanently (an HBM/ICI fault shrinking the chip set). Running
      pods on those chips finish; nothing new lands on them.
    - ``brownout_start`` / ``brownout_end``: the apiserver goes dark —
      scheduling stalls (arrivals queue, departures free capacity but
      nothing retries) until the window closes.
    - ``replica_crash`` / ``replica_restart``: a scheduler replica
      dies and comes back. In the sim this is a scheduling stall like
      a brownout; the chaos conductor kills/restarts the real process
      ``replica`` names.
    """

    time: float
    kind: str
    node: int = -1
    chips: tuple[int, ...] = ()
    lose_pods: bool = False
    replica: int = -1

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.time < 0:
            raise ValueError("fault time must be >= 0")


@dataclass(frozen=True)
class FaultSpec:
    """Knobs of one seeded fault schedule. Everything is a pure
    function of ``seed`` (``random.Random``, no wall clock), so the
    same spec replays byte-identically in run_sim, the native engine
    loop, and the real-fleet chaos conductor."""

    hours: float = 24.0
    n_nodes: int = 8
    chips_per_node: int = 4
    node_crashes: int = 1        # down windows that KILL running pods
    notready_windows: int = 1    # down windows running pods survive
    degradations: int = 1        # permanent chip-set shrinks
    brownouts: int = 1           # apiserver-dark windows
    replica_crashes: int = 1     # scheduler replica crash+restart pairs
    replicas: int = 2
    mean_outage: float = 0.5     # expovariate outage length (time units)
    seed: int = 0

    def __post_init__(self) -> None:
        if self.hours <= 0 or self.n_nodes <= 0 \
                or self.chips_per_node <= 0 or self.mean_outage <= 0:
            raise ValueError("bad fault spec (hours/n_nodes/"
                             "chips_per_node/mean_outage must be > 0)")
        if min(self.node_crashes, self.notready_windows,
               self.degradations, self.brownouts,
               self.replica_crashes) < 0 or self.replicas < 1:
            raise ValueError("fault counts must be >= 0, replicas >= 1")


def synth_faults(spec: FaultSpec) -> list[FaultEvent]:
    """Materialize the schedule: paired down/up windows clamped inside
    ``hours``, sorted by time (stable, so the draw order breaks ties
    deterministically). Both sim engines consume this list as-is, and
    the chaos conductor replays the same objects against real
    processes — one schedule, three consumers."""
    rng = random.Random(spec.seed)
    events: list[FaultEvent] = []

    def window(kind_down: str, kind_up: str, **kw) -> None:
        t0 = rng.uniform(0.0, spec.hours)
        t1 = min(t0 + rng.expovariate(1.0 / spec.mean_outage),
                 spec.hours)
        events.append(FaultEvent(time=t0, kind=kind_down, **kw))
        events.append(FaultEvent(time=t1, kind=kind_up,
                                 node=kw.get("node", -1),
                                 replica=kw.get("replica", -1)))

    for _ in range(spec.node_crashes):
        window("node_down", "node_up",
               node=rng.randrange(spec.n_nodes), lose_pods=True)
    for _ in range(spec.notready_windows):
        window("node_down", "node_up",
               node=rng.randrange(spec.n_nodes), lose_pods=False)
    for _ in range(spec.degradations):
        k = 1 + rng.randrange(max(1, spec.chips_per_node // 2))
        events.append(FaultEvent(
            time=rng.uniform(0.0, spec.hours), kind="degrade",
            node=rng.randrange(spec.n_nodes),
            chips=tuple(sorted(rng.sample(range(spec.chips_per_node),
                                          k)))))
    for _ in range(spec.brownouts):
        window("brownout_start", "brownout_end")
    for _ in range(spec.replica_crashes):
        window("replica_crash", "replica_restart",
               replica=rng.randrange(spec.replicas))
    return sorted(events, key=lambda e: e.time)
