"""Diurnal wind-tunnel traces: multi-hour arrival mixes at fleet scale.

The standard :func:`tpushare.sim.simulator.synth_trace` draws a flat
Poisson arrival process — fine for policy duels on a dozen hosts,
useless for the capacity questions ROADMAP item 4 asks ("what does MY
workload mix do to a 50k-node fleet across a business day?"). This
module synthesizes that day:

- **diurnal arrival rate**: a seeded inhomogeneous Poisson process whose
  rate follows a sinusoid between ``base_rate`` (trough, t=0) and
  ``peak_rate`` (peak, half a period later), sampled by thinning — the
  textbook exact method: propose at the ceiling rate, accept with
  probability rate(t)/ceiling, so the empirical arrival count over any
  window converges to the rate integral (tests/test_sim_traces.py
  checks exactly that).
- **spike windows**: multiplicative bursts (a failover, a launch, a
  batch-job wave) on top of the sinusoid, landing exactly where
  configured.
- **tiered pod shapes**: a weighted mix of request tiers (single-chip
  HBM slices through exclusive topology-pinned quads), each with its
  own mean duration — churn differs per tier, as it does in real
  fleets (inference replicas cycle fast, training jobs squat).

Everything is a pure function of the spec (``random.Random(seed)``,
no wall clock), so traces are byte-reproducible across processes —
the property the autotune ranking and the ``--procs`` determinism
proof both sit on. :func:`iter_diurnal` streams pods in arrival order
so a million-pod trace never has to be resident (the engine loop
consumes the iterator directly); :func:`synth_diurnal` materializes a
list for the parity/oracle paths.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Iterator

from tpushare.sim.simulator import Fleet, SimPod

# default period of the diurnal sinusoid, in trace time units ("hours")
DAY = 24.0


@dataclass(frozen=True)
class PodTier:
    """One shape class in the workload mix. ``weight`` is relative;
    ``mean_duration`` is this tier's churn knob (expovariate holding
    time, same distribution the flat-trace generator uses)."""

    name: str
    weight: float
    hbm_mib: int
    chip_count: int = 1
    topology: tuple[int, ...] | None = None
    mean_duration: float = 1.0
    priority: int = 0


@dataclass(frozen=True)
class SpikeWindow:
    """Multiplicative arrival burst over [start, start + duration)."""

    start: float
    duration: float
    multiplier: float

    def active(self, t: float) -> bool:
        return self.start <= t < self.start + self.duration


# The default mix: mostly single-chip inference slices with fast churn,
# a long tail of topology-pinned training quads that squat. Weights and
# sizes are v5e-flavored (16 GiB chips); tests pin the proportions.
DEFAULT_TIERS: tuple[PodTier, ...] = (
    PodTier("s1-2g", 0.45, 2048, mean_duration=0.5),
    PodTier("s1-4g", 0.25, 4096, mean_duration=1.0),
    PodTier("s1-8g", 0.12, 8192, mean_duration=2.0),
    PodTier("pair-4g", 0.08, 4096, chip_count=2, mean_duration=1.5),
    PodTier("quad-2x2", 0.07, 4096, chip_count=4, topology=(2, 2),
            mean_duration=3.0),
    PodTier("excl-2x2", 0.03, 0, chip_count=4, topology=(2, 2),
            mean_duration=4.0),
)


@dataclass(frozen=True)
class DiurnalSpec:
    """Knobs of one wind-tunnel day (or several). Rates are arrivals
    per time unit; the sinusoid troughs at t=0 and peaks at DAY/2."""

    hours: float = 24.0
    base_rate: float = 40.0
    peak_rate: float = 160.0
    tiers: tuple[PodTier, ...] = DEFAULT_TIERS
    spikes: tuple[SpikeWindow, ...] = ()
    seed: int = 0
    period: float = DAY

    def __post_init__(self) -> None:
        if self.hours <= 0 or self.base_rate < 0 \
                or self.peak_rate < self.base_rate:
            raise ValueError("bad diurnal spec (hours > 0, "
                             "0 <= base_rate <= peak_rate)")
        if not self.tiers or any(t.weight <= 0 for t in self.tiers):
            raise ValueError("tiers must be non-empty with "
                             "positive weights")


def rate_at(spec: DiurnalSpec, t: float) -> float:
    """Instantaneous arrival rate at trace time ``t`` — the spec the
    thinning sampler realizes and the integral test integrates."""
    lam = spec.base_rate + (spec.peak_rate - spec.base_rate) * 0.5 * (
        1.0 - math.cos(2.0 * math.pi * t / spec.period))
    for s in spec.spikes:
        if s.active(t):
            lam *= s.multiplier
    return lam


def expected_arrivals(spec: DiurnalSpec, t0: float = 0.0,
                      t1: float | None = None, steps: int = 4096) -> float:
    """Numeric integral of :func:`rate_at` over [t0, t1] (midpoint
    rule): the expected arrival count the trace realizes in that
    window, up to Poisson noise."""
    if t1 is None:
        t1 = spec.hours
    dt = (t1 - t0) / steps
    return sum(rate_at(spec, t0 + (i + 0.5) * dt)
               for i in range(steps)) * dt


def iter_diurnal(spec: DiurnalSpec) -> Iterator[SimPod]:
    """Stream the trace in arrival order (thinning sampler). Pure
    function of the spec; a million-pod day never lives in memory."""
    rng = random.Random(spec.seed)
    ceiling = spec.peak_rate * max(
        [1.0] + [s.multiplier for s in spec.spikes if s.multiplier > 1.0])
    if ceiling <= 0:
        return
    weights = [t.weight for t in spec.tiers]
    cum = []
    acc = 0.0
    for w in weights:
        acc += w
        cum.append(acc)
    total_w = acc
    t = 0.0
    while True:
        t += rng.expovariate(ceiling)
        if t >= spec.hours:
            return
        # thinning: accept proposals at the instantaneous/ceiling ratio
        if rng.random() * ceiling >= rate_at(spec, t):
            continue
        r = rng.random() * total_w
        tier = spec.tiers[-1]
        for i, c in enumerate(cum):
            if r < c:
                tier = spec.tiers[i]
                break
        duration = rng.expovariate(1.0 / tier.mean_duration)
        yield SimPod(arrival=t, duration=duration, hbm_mib=tier.hbm_mib,
                     chip_count=tier.chip_count, topology=tier.topology,
                     priority=tier.priority)


def synth_diurnal(spec: DiurnalSpec) -> list[SimPod]:
    """Materialized form of :func:`iter_diurnal` for the oracle paths
    (run_sim wants a list; parity tests replay both engines over the
    same object)."""
    return list(iter_diurnal(spec))


def synth_fleet(n_nodes: int, chips: int = 4, hbm: int = 16384,
                mesh: tuple[int, ...] | None = (2, 2)) -> Fleet:
    """Fleet synthesis to wind-tunnel scale. Thin veneer over
    Fleet.homogeneous, named so call sites read as what they are —
    bench.py builds 50k-node fleets through this."""
    return Fleet.homogeneous(n_nodes, chips, hbm, mesh)
