"""CLI: compare placement policies on a synthetic workload trace.

    python -m tpushare.sim --nodes 8 --chips 4 --hbm 16384 --mesh 2x2 \
        --pods 400 --policy all

Prints one JSON object per policy run.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpushare.sim.simulator import (
    POLICIES, Fleet, TraceSpec, run_sim, synth_trace)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tpushare-sim")
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--chips", type=int, default=4)
    ap.add_argument("--hbm", type=int, default=16384,
                    help="HBM MiB per chip")
    ap.add_argument("--mesh", default=None,
                    help='host ICI mesh, e.g. "2x2" (default: 1-D)')
    ap.add_argument("--pods", type=int, default=400)
    ap.add_argument("--arrival-rate", type=float, default=2.0)
    ap.add_argument("--mean-duration", type=float, default=40.0)
    ap.add_argument("--multi-chip-fraction", type=float, default=0.15)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--policy", default="all",
                    choices=["all", *POLICIES])
    ap.add_argument("--preempt", default="off",
                    choices=["off", "scalar", "refined"],
                    help="priority preemption for unplaceable arrivals: "
                         "scalar = node-level victim arithmetic (the "
                         "no-extender failure mode), refined = per-chip "
                         "victim refinement (the preempt verb)")
    ap.add_argument("--high-priority-fraction", type=float, default=0.0)
    ap.add_argument("--defrag", action="store_true",
                    help="repack-rebalancer mode: replay a churn trace "
                         "through the defrag planner core, sweeping the "
                         "per-pass migration budget; one JSON report per "
                         "budget (tpushare/sim/defrag.py)")
    ap.add_argument("--budgets", default="0,1,2,4",
                    help="--defrag: comma-separated move budgets to sweep")
    ap.add_argument("--shards", type=int, default=0, metavar="N",
                    help="active-active sharding mode: replay the "
                         "standard arrival trace against 1, 2 and 4 "
                         "simulated shard owners (or 1 and N when N is "
                         "given and not in {1,2,4}); one JSON report "
                         "per shard count, proving the scorecard is "
                         "unchanged by shard ownership")
    ap.add_argument("--procs", type=int, default=0, metavar="N",
                    help="wall-clock scale-out mode: run the full "
                         "standard replay in N spawned OS processes "
                         "and in one, report aggregate placements/sec "
                         "for both, and prove every process emitted a "
                         "byte-identical scorecard (cross-process "
                         "determinism; tpushare/sim/procs.py). Exits "
                         "nonzero on scorecard divergence")
    ap.add_argument("--slice", action="store_true",
                    help="multi-host slice (gang) mode: one v5e-16 "
                         "(2x2 hosts of 2x2 chips), mixed single-chip "
                         "tenants + 2x2/2x4 exclusive gangs through "
                         "core/slice.select_gang; compares the 'pack' "
                         "and 'spread' singles policies "
                         "(docs/designs/multihost-gang.md)")
    args = ap.parse_args(argv)

    if args.defrag:
        from tpushare.sim.defrag import sweep_budgets
        mesh = tuple(int(d) for d in args.mesh.split("x")) \
            if args.mesh else ((2, 2) if args.chips == 4 else None)
        budgets = tuple(int(b) for b in args.budgets.split(","))
        for report in sweep_budgets(budgets, n_nodes=args.nodes,
                                    chips=args.chips, hbm=args.hbm,
                                    mesh=mesh):
            print(json.dumps(report))
        return 0

    if args.slice:
        # slice mode simulates a fixed v5e-16 (2x2 hosts of 2x2 chips)
        # and runs BOTH singles policies; flags that would silently not
        # apply are rejected rather than ignored
        for flag, default in (("nodes", 8), ("chips", 4), ("hbm", 16384),
                              ("mesh", None), ("policy", "all"),
                              ("preempt", "off"),
                              ("high_priority_fraction", 0.0)):
            if getattr(args, flag) != default:
                ap.error(f"--{flag.replace('_', '-')} does not apply to "
                         "--slice mode (fixed v5e-16 geometry, "
                         "pack-vs-spread duel)")
        from tpushare.sim.simulator import run_slice_sim, synth_slice_trace
        strace = synth_slice_trace(
            n_pods=args.pods, seed=args.seed,
            gang_fraction=args.multi_chip_fraction,
            arrival_rate=args.arrival_rate,
            mean_duration=args.mean_duration)
        for policy in ("spread", "pack"):
            print(json.dumps(run_slice_sim(strace, policy)))
        return 0

    mesh = tuple(int(d) for d in args.mesh.split("x")) if args.mesh else None
    if mesh is not None:
        n = 1
        for d in mesh:
            n *= d
        if n != args.chips:
            # a silent mismatch would compare policies on different
            # geometry (the placement kernel falls back to a 1-D mesh)
            ap.error(f"--mesh {args.mesh} has {n} chips but --chips is "
                     f"{args.chips}")
    spec = TraceSpec(n_pods=args.pods, arrival_rate=args.arrival_rate,
                     mean_duration=args.mean_duration,
                     multi_chip_fraction=args.multi_chip_fraction,
                     high_priority_fraction=args.high_priority_fraction,
                     seed=args.seed)
    if args.procs:
        # real OS processes, one replay each: the multi-core number and
        # the cross-process determinism proof (tpushare/sim/procs.py)
        from tpushare.sim.procs import run_procs
        if args.shards:
            ap.error("--shards does not apply to --procs mode")
        policy = "binpack" if args.policy == "all" else args.policy
        out = run_procs({
            "nodes": args.nodes, "chips": args.chips, "hbm": args.hbm,
            "mesh": list(mesh) if mesh else None,
            "policy": policy, "preempt": args.preempt,
            "spec": {"n_pods": args.pods,
                     "arrival_rate": args.arrival_rate,
                     "mean_duration": args.mean_duration,
                     "multi_chip_fraction": args.multi_chip_fraction,
                     "high_priority_fraction":
                         args.high_priority_fraction,
                     "seed": args.seed}}, args.procs)
        print(json.dumps(out))
        # a scorecard that differs across fresh interpreters is a
        # nondeterminism bug, not a tuning question: fail loudly
        return 0 if out["scorecards_identical"] else 1

    trace = synth_trace(spec)
    if args.shards:
        # sharding changes who HANDLES a bind, never its verdict: every
        # shard count must emit an identical scorecard. One JSON per
        # count, with the owned/spillover split attached.
        from tpushare.sim.simulator import run_sim_sharded
        if args.preempt != "off":
            ap.error("--preempt does not apply to --shards mode")
        policy = "binpack" if args.policy == "all" else args.policy
        counts = [1, 2, 4] if args.shards in (1, 2, 4) else [1, args.shards]
        for shards in counts:
            fleet = Fleet.homogeneous(args.nodes, args.chips, args.hbm,
                                      mesh)
            report, stats = run_sim_sharded(fleet, trace, policy,
                                            shards=shards)
            out = report.to_json()
            out["sharding"] = stats
            print(json.dumps(out))
        return 0

    policies = list(POLICIES) if args.policy == "all" else [args.policy]
    for policy in policies:
        fleet = Fleet.homogeneous(args.nodes, args.chips, args.hbm, mesh)
        report = run_sim(fleet, trace, policy, preempt=args.preempt)
        print(json.dumps(report.to_json()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
