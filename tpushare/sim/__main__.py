"""CLI: compare placement policies on a synthetic workload trace.

    python -m tpushare.sim --nodes 8 --chips 4 --hbm 16384 --mesh 2x2 \
        --pods 400 --policy all

Prints one JSON object per policy run. Flags are grouped: *trace*
(what workload), *engine* (what replays it), *sweep modes* (which
harness), *output* (where results land) — ``--help`` shows the groups.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpushare.sim.simulator import (
    POLICIES, Fleet, TraceSpec, run_sim, synth_trace)


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="tpushare-sim",
        description="Discrete-event fleet simulator over the real "
                    "placement kernel: policy duels, preemption and "
                    "defrag studies, scale-out proofs, and the "
                    "million-pod wind tunnel (--engine native).")

    tg = ap.add_argument_group(
        "trace", "the synthetic workload: flat Poisson by default, "
                 "diurnal wind-tunnel day with --diurnal")
    tg.add_argument("--pods", type=int, default=400)
    tg.add_argument("--arrival-rate", type=float, default=2.0)
    tg.add_argument("--mean-duration", type=float, default=40.0)
    tg.add_argument("--multi-chip-fraction", type=float, default=0.15)
    tg.add_argument("--high-priority-fraction", type=float, default=0.0)
    tg.add_argument("--seed", type=int, default=0)
    tg.add_argument("--diurnal", action="store_true",
                    help="replace the flat trace with the seeded "
                         "diurnal generator (tpushare/sim/traces.py): "
                         "sinusoidal arrival rate, tiered pod shapes, "
                         "per-tier churn")
    tg.add_argument("--hours", type=float, default=24.0,
                    help="--diurnal: trace length in hours")
    tg.add_argument("--base-rate", type=float, default=40.0,
                    help="--diurnal: trough arrivals/hour")
    tg.add_argument("--peak-rate", type=float, default=160.0,
                    help="--diurnal: peak arrivals/hour")

    eg = ap.add_argument_group(
        "engine", "the fleet geometry and the loop that replays the "
                  "trace over it")
    eg.add_argument("--nodes", type=int, default=8)
    eg.add_argument("--chips", type=int, default=4)
    eg.add_argument("--hbm", type=int, default=16384,
                    help="HBM MiB per chip")
    eg.add_argument("--mesh", default=None,
                    help='host ICI mesh, e.g. "2x2" (default: 1-D)')
    eg.add_argument("--engine", default="python",
                    choices=["python", "native"],
                    help="python = the behavioral-spec loop (one "
                         "select_chips_py per pod per node — the "
                         "parity oracle); native = the resident-arena "
                         "engine loop (tpushare/sim/engine_loop.py), "
                         "byte-identical scorecards at default knobs")
    eg.add_argument("--policy", default="all",
                    choices=["all", *POLICIES])
    eg.add_argument("--preempt", default="off",
                    choices=["off", "scalar", "refined"],
                    help="priority preemption for unplaceable arrivals: "
                         "scalar = node-level victim arithmetic (the "
                         "no-extender failure mode), refined = per-chip "
                         "victim refinement (the preempt verb)")
    eg.add_argument("--batch-window", type=float, default=0.0,
                    help="--engine native: coalesce arrivals for this "
                         "many sim-time units and solve same-signature "
                         "groups disjointly (the BatchPlanner replayed "
                         "offline); 0 = spec-parity waves")
    eg.add_argument("--index-scheme", default="off",
                    choices=["off", "pow2", "exact"],
                    help="--engine native: max-free no-fit prune over "
                         "delta re-scores (throughput only — decisions "
                         "never change)")
    eg.add_argument("--eqclass-lru", type=int, default=32,
                    help="--engine native: resident signature score "
                         "vectors kept before LRU eviction")
    eg.add_argument("--defrag-budget", type=int, default=0,
                    help="--engine native: live-migration moves per "
                         "defrag pass (0 = no defrag)")
    eg.add_argument("--defrag-period", type=float, default=4.0,
                    help="--engine native: sim-time between defrag "
                         "passes")
    eg.add_argument("--scatter-util-pct", type=float, default=0.0,
                    help="--engine native: below this fleet "
                         "utilization, scatter-tolerant requests are "
                         "forced contiguous (0 = honor the request)")

    sg = ap.add_argument_group(
        "sweep modes", "alternative harnesses around the replay "
                       "(mutually exclusive with each other)")
    sg.add_argument("--replay", default=None, metavar="JOURNAL",
                    help="incident replay: read a decision journal "
                         "(file or TPUSHARE_JOURNAL_DIR directory, "
                         "tpushare/obs/journal.py), rebuild the "
                         "recorded arrival window as a SimPod trace, "
                         "re-drive it through the simulator on the "
                         "recorded fleet geometry, and diff the "
                         "replayed scorecard against the journal's own "
                         "recorded aggregate (tpushare/sim/replay.py); "
                         "deterministic — the same journal emits "
                         "byte-identical output")
    sg.add_argument("--autotune", action="store_true",
                    help="ranked knob sweep: replay the wind-tunnel "
                         "sweep workload under 18 knob configurations "
                         "and print the winners table ranked by "
                         "scorecard (tpushare/sim/autotune.py); "
                         "throughput is published but never ranks")
    sg.add_argument("--pin", action="store_true",
                    help="--autotune: re-baseline the tier-1 scorecard "
                         "gate — write the winner's standard-trace "
                         "scorecard + tolerance bands to "
                         "tests/data/wind_tunnel_golden.json "
                         "(deliberate act; see docs/ops.md)")
    sg.add_argument("--qos", action="store_true",
                    help="tiered QoS mode: replay the standard tiered "
                         "diurnal mix under the overcommit sweep "
                         "(1.0/1.1/1.25/1.5) — scorecard, evictions, "
                         "and the zero-violation isolation proof per "
                         "point (tpushare/sim/qos.py); with --pin, "
                         "re-baseline the tier-1 QoS gate golden "
                         "tests/data/qos_wind_tunnel_golden.json")
    sg.add_argument("--topo", action="store_true",
                    help="mesh-aware placement mode: replay the serving "
                         "mix sweeping TPUSHARE_TOPO_WEIGHT "
                         "(0/0.25/0.5/1.0) — seed-averaged scorecard, "
                         "adjacency quality, and serving wait tail per "
                         "weight (tpushare/sim/topo.py); with --pin, "
                         "re-baseline the tier-1 topo gate golden "
                         "tests/data/topo_wind_tunnel_golden.json")
    sg.add_argument("--defrag", action="store_true",
                    help="repack-rebalancer mode: replay a churn trace "
                         "through the defrag planner core, sweeping the "
                         "per-pass migration budget; one JSON report per "
                         "budget (tpushare/sim/defrag.py)")
    sg.add_argument("--budgets", default="0,1,2,4",
                    help="--defrag: comma-separated move budgets to sweep")
    sg.add_argument("--frag-weight", type=float, default=0.0,
                    metavar="W",
                    help="--defrag: > 0 switches to the migration A/B — "
                         "the identical trace run react-only vs "
                         "forecast-biased admission (weight W) with "
                         "pressure-gated repack; reports both runs plus "
                         "the fewer-migrations / stranded-held verdict "
                         "(tpushare/sim/defrag.py sweep_forecast)")
    sg.add_argument("--shards", type=int, default=0, metavar="N",
                    help="active-active sharding mode: replay the "
                         "standard arrival trace against 1, 2 and 4 "
                         "simulated shard owners (or 1 and N when N is "
                         "given and not in {1,2,4}); one JSON report "
                         "per shard count, proving the scorecard is "
                         "unchanged by shard ownership")
    sg.add_argument("--procs", type=int, default=0, metavar="N",
                    help="wall-clock scale-out mode: run the full "
                         "standard replay in N spawned OS processes "
                         "and in one, report aggregate placements/sec "
                         "for both, and prove every process emitted a "
                         "byte-identical scorecard (cross-process "
                         "determinism; tpushare/sim/procs.py). Honors "
                         "--engine. Exits nonzero on scorecard "
                         "divergence")
    sg.add_argument("--gangs", action="store_true",
                    help="gang-solve A/B mode: a gang-heavy trace "
                         "(cross-host 2x4/4x2 exclusive gangs + "
                         "sharing-tenant background, "
                         "sim/traces.synth_gangs) replayed through "
                         "BOTH gang kernels on one v5e-16 — the ABI v5 "
                         "one-shot solve and the sequential Python "
                         "spec; emits one standard scorecard per "
                         "engine (identical by the parity contract)")
    sg.add_argument("--slice", action="store_true",
                    help="multi-host slice (gang) mode: one v5e-16 "
                         "(2x2 hosts of 2x2 chips), mixed single-chip "
                         "tenants + 2x2/2x4 exclusive gangs through "
                         "core/slice.select_gang; compares the 'pack' "
                         "and 'spread' singles policies "
                         "(docs/designs/multihost-gang.md)")

    og = ap.add_argument_group("output")
    og.add_argument("--out", default=None, metavar="FILE",
                    help="write the JSON lines to FILE instead of "
                         "stdout")
    og.add_argument("--stats", action="store_true",
                    help="--engine native: attach the engine loop's "
                         "internals (refresh/prune/batch counters, "
                         "arena delta accounting) to each report")
    return ap


def _knobs_from(args):
    from tpushare.sim.engine_loop import LoopKnobs
    return LoopKnobs(batch_window=args.batch_window,
                     index_scheme=args.index_scheme,
                     eqclass_lru=args.eqclass_lru,
                     defrag_budget=args.defrag_budget,
                     defrag_period=args.defrag_period,
                     scatter_util_pct=args.scatter_util_pct)


def main(argv: list[str] | None = None) -> int:
    ap = _build_parser()
    args = ap.parse_args(argv)
    sink = open(args.out, "w") if args.out else sys.stdout

    def emit(obj) -> None:
        print(json.dumps(obj), file=sink)

    try:
        return _run(ap, args, emit)
    finally:
        if args.out:
            sink.close()


def _run(ap, args, emit) -> int:
    knob_flags_set = (args.batch_window != 0.0
                      or args.index_scheme != "off"
                      or args.eqclass_lru != 32
                      or args.defrag_budget != 0
                      or args.defrag_period != 4.0
                      or args.scatter_util_pct != 0.0)
    if args.engine == "python" and knob_flags_set and not args.autotune:
        ap.error("engine knobs (--batch-window/--index-scheme/"
                 "--eqclass-lru/--defrag-budget/--defrag-period/"
                 "--scatter-util-pct) require --engine native")
    if args.pin and not (args.autotune or args.qos or args.topo):
        ap.error("--pin re-baselines a pinned gate: it requires "
                 "--autotune, --qos, or --topo")

    if args.replay:
        # incident replay owns its workload (the journal) and geometry
        # (the journal header); trace/engine flags would silently not
        # apply and are rejected rather than ignored
        for flag, default in (("pods", 400), ("arrival_rate", 2.0),
                              ("mean_duration", 40.0),
                              ("multi_chip_fraction", 0.15),
                              ("high_priority_fraction", 0.0),
                              ("nodes", 8), ("chips", 4),
                              ("hbm", 16384), ("mesh", None),
                              ("preempt", "off"), ("engine", "python"),
                              ("diurnal", False), ("seed", 0),
                              ("shards", 0), ("procs", 0)):
            if getattr(args, flag) != default:
                ap.error(f"--{flag.replace('_', '-')} does not apply "
                         "to --replay (workload and geometry come from "
                         "the journal: tpushare/sim/replay.py)")
        if args.autotune or args.qos or args.topo or args.defrag \
                or args.gangs or args.slice:
            ap.error("sweep modes do not apply to --replay")
        from tpushare.sim.replay import replay_journal
        policy = "binpack" if args.policy == "all" else args.policy
        emit(replay_journal(args.replay, policy))
        return 0

    if args.topo:
        from tpushare.sim import topo
        out = topo.weight_sweep()
        if args.pin:
            out["golden"] = topo.pin_topo_golden()
            out["golden_path"] = topo.TOPO_GOLDEN_PATH
        emit(out)
        return 0

    if args.qos:
        from tpushare.sim import qos
        out = qos.overcommit_sweep()
        if args.pin:
            out["golden"] = qos.pin_qos_golden()
            out["golden_path"] = qos.QOS_GOLDEN_PATH
        emit(out)
        return 0

    if args.autotune:
        # the sweep owns its workload and fleet so the winners table —
        # and the golden --pin writes — mean one fixed, comparable
        # thing; flags that would silently not apply are rejected
        for flag, default in (("pods", 400), ("arrival_rate", 2.0),
                              ("mean_duration", 40.0),
                              ("multi_chip_fraction", 0.15),
                              ("high_priority_fraction", 0.0),
                              ("nodes", 8), ("chips", 4),
                              ("hbm", 16384), ("mesh", None),
                              ("policy", "all"), ("preempt", "off"),
                              ("shards", 0), ("procs", 0), ("seed", 0)):
            if getattr(args, flag) != default:
                ap.error(f"--{flag.replace('_', '-')} does not apply "
                         "to --autotune (fixed sweep workload: "
                         "tpushare/sim/autotune.py SWEEP_SPEC)")
        if args.slice or args.defrag:
            ap.error("--slice/--defrag do not apply to --autotune")
        from tpushare.sim import autotune
        from tpushare.sim.engine_loop import LoopKnobs
        out = autotune.run_sweep()
        if args.pin:
            winner = out["winner"]
            golden = autotune.pin_golden(LoopKnobs(**winner["knobs"]))
            out["golden"] = golden
            out["golden_path"] = autotune.GOLDEN_PATH
        emit(out)
        return 0

    if args.defrag:
        from tpushare.sim.defrag import sweep_budgets, sweep_forecast
        mesh = tuple(int(d) for d in args.mesh.split("x")) \
            if args.mesh else ((2, 2) if args.chips == 4 else None)
        if args.frag_weight > 0.0:
            emit(sweep_forecast(frag_weight=args.frag_weight,
                                n_nodes=args.nodes, chips=args.chips,
                                hbm=args.hbm, mesh=mesh))
            return 0
        budgets = tuple(int(b) for b in args.budgets.split(","))
        for report in sweep_budgets(budgets, n_nodes=args.nodes,
                                    chips=args.chips, hbm=args.hbm,
                                    mesh=mesh):
            emit(report)
        return 0

    if args.gangs:
        # gang mode replays ONE gang-heavy trace through both gang
        # kernels on the fixed v5e-16; flags that would silently not
        # apply are rejected rather than ignored
        for flag, default in (("nodes", 8), ("chips", 4), ("hbm", 16384),
                              ("mesh", None), ("policy", "all"),
                              ("preempt", "off"), ("engine", "python"),
                              ("high_priority_fraction", 0.0),
                              ("slice", False)):
            if getattr(args, flag) != default:
                ap.error(f"--{flag.replace('_', '-')} does not apply to "
                         "--gangs mode (fixed v5e-16 geometry, "
                         "oneshot-vs-sequential duel)")
        from tpushare.sim.simulator import run_slice_sim
        from tpushare.sim.traces import GangSpec, synth_gangs
        gtrace = synth_gangs(GangSpec(
            n_pods=args.pods, seed=args.seed,
            gang_fraction=max(args.multi_chip_fraction, 0.5),
            arrival_rate=args.arrival_rate,
            mean_duration=args.mean_duration))
        for eng in ("sequential", "oneshot"):
            emit(run_slice_sim(gtrace, "pack", engine=eng))
        return 0

    if args.slice:
        # slice mode simulates a fixed v5e-16 (2x2 hosts of 2x2 chips)
        # and runs BOTH singles policies; flags that would silently not
        # apply are rejected rather than ignored
        for flag, default in (("nodes", 8), ("chips", 4), ("hbm", 16384),
                              ("mesh", None), ("policy", "all"),
                              ("preempt", "off"), ("engine", "python"),
                              ("high_priority_fraction", 0.0)):
            if getattr(args, flag) != default:
                ap.error(f"--{flag.replace('_', '-')} does not apply to "
                         "--slice mode (fixed v5e-16 geometry, "
                         "pack-vs-spread duel)")
        from tpushare.sim.simulator import run_slice_sim, synth_slice_trace
        strace = synth_slice_trace(
            n_pods=args.pods, seed=args.seed,
            gang_fraction=args.multi_chip_fraction,
            arrival_rate=args.arrival_rate,
            mean_duration=args.mean_duration)
        for policy in ("spread", "pack"):
            emit(run_slice_sim(strace, policy))
        return 0

    if args.engine == "native":
        if args.preempt != "off":
            ap.error("--preempt applies to the python spec loop only "
                     "(the native engine loop has no preemption model)")
        if args.policy not in ("all", "binpack"):
            ap.error("--engine native replays the binpack policy (the "
                     "production engine); use --engine python for "
                     "policy duels")
        if args.shards:
            ap.error("--shards does not apply to --engine native "
                     "(sharding attribution wraps the python policies)")

    mesh = tuple(int(d) for d in args.mesh.split("x")) if args.mesh else None
    if mesh is not None:
        n = 1
        for d in mesh:
            n *= d
        if n != args.chips:
            # a silent mismatch would compare policies on different
            # geometry (the placement kernel falls back to a 1-D mesh)
            ap.error(f"--mesh {args.mesh} has {n} chips but --chips is "
                     f"{args.chips}")

    diurnal_spec = None
    if args.diurnal:
        # the diurnal generator has its own tiered shape mix; flat-trace
        # shape flags would silently not apply
        for flag, default in (("pods", 400), ("arrival_rate", 2.0),
                              ("mean_duration", 40.0),
                              ("multi_chip_fraction", 0.15),
                              ("high_priority_fraction", 0.0)):
            if getattr(args, flag) != default:
                ap.error(f"--{flag.replace('_', '-')} does not apply "
                         "with --diurnal (tiered mix: "
                         "tpushare/sim/traces.py DEFAULT_TIERS)")
        from tpushare.sim.traces import DiurnalSpec
        diurnal_spec = DiurnalSpec(hours=args.hours,
                                   base_rate=args.base_rate,
                                   peak_rate=args.peak_rate,
                                   seed=args.seed)

    spec = TraceSpec(n_pods=args.pods, arrival_rate=args.arrival_rate,
                     mean_duration=args.mean_duration,
                     multi_chip_fraction=args.multi_chip_fraction,
                     high_priority_fraction=args.high_priority_fraction,
                     seed=args.seed)
    if args.procs:
        # real OS processes, one replay each: the multi-core number and
        # the cross-process determinism proof (tpushare/sim/procs.py)
        from tpushare.sim.procs import run_procs
        if args.shards:
            ap.error("--shards does not apply to --procs mode")
        if args.diurnal:
            ap.error("--diurnal does not apply to --procs mode "
                     "(standard replay only)")
        policy = "binpack" if args.policy == "all" else args.policy
        out = run_procs({
            "nodes": args.nodes, "chips": args.chips, "hbm": args.hbm,
            "mesh": list(mesh) if mesh else None,
            "policy": policy, "preempt": args.preempt,
            "engine": args.engine,
            "spec": {"n_pods": args.pods,
                     "arrival_rate": args.arrival_rate,
                     "mean_duration": args.mean_duration,
                     "multi_chip_fraction": args.multi_chip_fraction,
                     "high_priority_fraction":
                         args.high_priority_fraction,
                     "seed": args.seed}}, args.procs)
        emit(out)
        # a scorecard that differs across fresh interpreters is a
        # nondeterminism bug, not a tuning question: fail loudly
        return 0 if out["scorecards_identical"] else 1

    if diurnal_spec is not None:
        from tpushare.sim.traces import synth_diurnal
        trace = synth_diurnal(diurnal_spec)
    else:
        trace = synth_trace(spec)

    if args.engine == "native":
        from tpushare.sim.engine_loop import run_sim_native
        fleet = Fleet.homogeneous(args.nodes, args.chips, args.hbm, mesh)
        report, stats = run_sim_native(fleet, trace, _knobs_from(args))
        out = report.to_json()
        out["engine"] = "native"
        if args.stats:
            out["engine_stats"] = stats
        emit(out)
        return 0

    if args.shards:
        # sharding changes who HANDLES a bind, never its verdict: every
        # shard count must emit an identical scorecard. One JSON per
        # count, with the owned/spillover split attached.
        from tpushare.sim.simulator import run_sim_sharded
        if args.preempt != "off":
            ap.error("--preempt does not apply to --shards mode")
        policy = "binpack" if args.policy == "all" else args.policy
        counts = [1, 2, 4] if args.shards in (1, 2, 4) else [1, args.shards]
        for shards in counts:
            fleet = Fleet.homogeneous(args.nodes, args.chips, args.hbm,
                                      mesh)
            report, stats = run_sim_sharded(fleet, trace, policy,
                                            shards=shards)
            out = report.to_json()
            out["sharding"] = stats
            emit(out)
        return 0

    policies = list(POLICIES) if args.policy == "all" else [args.policy]
    for policy in policies:
        fleet = Fleet.homogeneous(args.nodes, args.chips, args.hbm, mesh)
        report = run_sim(fleet, trace, policy, preempt=args.preempt)
        emit(report.to_json())
    return 0


if __name__ == "__main__":
    sys.exit(main())
