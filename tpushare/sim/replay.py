"""Incident replay: re-drive a recorded decision journal in the wind tunnel.

The decision journal (tpushare/obs/journal.py) records every
admitted/rejected/bound pod a live server saw, each with its
placement-relevant spec in SimPod vocabulary — the journal's pod schema
IS the sim trace format by construction. This module closes the loop:
``python -m tpushare.sim --replay <journal>`` rebuilds the recorded
arrival window as a SimPod trace, re-drives it through the simulator on
the recorded fleet geometry, and diffs the replayed scorecard against
the aggregate the journal itself recorded. A production incident ("why
did admissions crater at 14:32") becomes a deterministic wind-tunnel
case that can be re-run, bisected, and attached to a bug.

Determinism contract: replaying the SAME journal emits byte-identical
output (tests/test_journal.py proves it). Everything derives from the
journal's own timestamps — no wall clock, no randomness; arrivals are
offsets from the window start and every pod outlives the window, so the
replay is a pure placement problem over the recorded arrival order.

What replay can and cannot prove: the simulator re-decides placement
with its own policy over the recorded *arrivals*; the journal records
what the live fleet *actually decided* (including wirecache/native
serves, preemptions, operator actions). The diff is therefore a signal,
not an identity — a large admission-rate gap between recorded and
replayed is exactly the anomaly worth investigating.
"""

from __future__ import annotations

from typing import Any

from tpushare.sim.simulator import Fleet, SimPod, run_sim

# fallback geometry when the journal header carries no fleet info (an
# old journal, or a server started without a synced cache snapshot)
DEFAULT_FLEET = {"n_nodes": 8, "chips_per_node": 4,
                 "hbm_per_chip_mib": 16384, "mesh": None}


def load_window(path: str) -> dict[str, Any]:
    """Parse a journal file/directory into the replay inputs: header
    fleet info, the first filter decision per pod (arrival order), and
    the recorded aggregate recomputed from the decision records
    themselves (NOT trusted from memory — the journal is the record)."""
    from tpushare.obs.journal import read_journal
    fleet_info: dict[str, Any] | None = None
    first_filter: dict[str, dict[str, Any]] = {}
    agg = {"pods": 0, "admitted": 0, "rejected": 0,
           "binds": 0, "bind_failures": 0}
    records = 0
    t_min: float | None = None
    t_max: float | None = None
    for rec in read_journal(path):
        if rec.get("kind") == "header":
            if fleet_info is None and isinstance(rec.get("fleet"), dict):
                fleet_info = rec["fleet"]
            continue
        if rec.get("kind") != "decision":
            continue
        records += 1
        t = rec.get("t")
        if isinstance(t, (int, float)):
            t_min = t if t_min is None else min(t_min, t)
            t_max = t if t_max is None else max(t_max, t)
        verb = rec.get("verb")
        key = rec.get("pod_key")
        if verb == "filter" and isinstance(key, str):
            if key not in first_filter:
                first_filter[key] = rec
                agg["pods"] += 1
            if rec.get("ok"):
                agg["admitted"] += 1
            else:
                agg["rejected"] += 1
        elif verb == "bind":
            if rec.get("outcome") == "bound":
                agg["binds"] += 1
            else:
                agg["bind_failures"] += 1
    filters = agg["admitted"] + agg["rejected"]
    agg["admission_rate"] = (round(agg["admitted"] / filters, 4)
                             if filters else None)
    return {
        "fleet_info": fleet_info,
        "first_filter": first_filter,
        "recorded": agg,
        "records": records,
        "t_min": t_min,
        "t_max": t_max,
    }


def build_trace(window: dict[str, Any]) -> list[SimPod]:
    """One SimPod per recorded pod, in arrival (journal) order.

    Arrival = offset of the pod's first filter decision from the window
    start; duration = the whole window plus slack, so nothing departs
    mid-replay — the replay is the recorded ARRIVAL sequence as a pure
    placement problem, deterministic and independent of wall clock."""
    t_min = window["t_min"] or 0.0
    t_max = window["t_max"] or t_min
    span = max(t_max - t_min, 1.0)
    trace: list[SimPod] = []
    for rec in window["first_filter"].values():
        spec = rec.get("spec") or {}
        t = rec.get("t")
        arrival = (t - t_min) if isinstance(t, (int, float)) else 0.0
        topo = spec.get("topology")
        mesh = spec.get("mesh_shape")
        trace.append(SimPod(
            arrival=round(max(arrival, 0.0), 6),
            duration=round(span * 2.0, 6),
            hbm_mib=int(spec.get("hbm_mib") or 0),
            chip_count=max(int(spec.get("chip_count") or 1), 1),
            topology=tuple(topo) if topo else None,
            priority=int(spec.get("priority") or 0),
            qos_tier=str(spec.get("qos_tier") or "burstable"),
            mesh_shape=tuple(mesh) if mesh else None,
        ))
    trace.sort(key=lambda p: p.arrival)
    return trace


def _fleet_from(info: dict[str, Any] | None) -> Fleet:
    merged = dict(DEFAULT_FLEET)
    if isinstance(info, dict):
        for k in merged:
            if info.get(k) is not None:
                merged[k] = info[k]
    mesh = merged["mesh"]
    return Fleet.homogeneous(int(merged["n_nodes"]),
                             int(merged["chips_per_node"]),
                             int(merged["hbm_per_chip_mib"]),
                             tuple(mesh) if mesh else None)


def replay_journal(path: str, policy: str = "binpack") -> dict[str, Any]:
    """The --replay entry: journal in, {recorded, replay, diff} out.

    ``recorded`` is the aggregate recomputed from the journal's own
    decision records; ``replay`` is the standard SimReport of re-driving
    the rebuilt trace; ``diff`` compares the two admission views."""
    window = load_window(path)
    trace = build_trace(window)
    fleet = _fleet_from(window["fleet_info"])
    report = run_sim(fleet, trace, policy)
    out = report.to_json()
    recorded = window["recorded"]
    rec_rate = recorded["admission_rate"]
    rep_rate = (round(report.placed / report.pods, 4)
                if report.pods else None)
    return {
        "mode": "replay",
        "policy": policy,
        "records": window["records"],
        "window_s": (round(window["t_max"] - window["t_min"], 3)
                     if window["t_max"] is not None else 0.0),
        "fleet": window["fleet_info"] or dict(DEFAULT_FLEET),
        "recorded": recorded,
        "replay": out,
        "diff": {
            "recorded_admission_rate": rec_rate,
            "replayed_admission_rate": rep_rate,
            "admission_rate_delta": (round(rep_rate - rec_rate, 4)
                                     if rec_rate is not None
                                     and rep_rate is not None else None),
            "recorded_pods": recorded["pods"],
            "replayed_pods": report.pods,
        },
    }
