"""Policy autotuning sweep: ranked search over the wind tunnel's knobs.

``python -m tpushare.sim --autotune`` replays one seeded wind-tunnel
trace under every configuration in :func:`knob_grid` (18 points over
batch window x scatter threshold x defrag budget, with the throughput
knobs — index scheme, eqclass LRU — cycled through so their pods/sec
effect is visible in the table) and ranks the results by SCORECARD:

    (rejection_rate, p99_pending_age_s, -time_weighted_util_pct)

Admission first, latency second, packing density third — the same
priority order the ops runbook uses to read a live fleet's scorecard.
Wall-clock throughput (``sim_pods_per_sec``) is published per row but
NEVER ranks: every replay is a pure function of (trace, fleet, knobs),
so the ranking is byte-reproducible run-to-run and machine-to-machine,
which is what lets the winner be pinned as a CI gate.

The sweep parallelizes across a thread pool — the native scans release
the GIL, and each config's replay is deterministic and independent, so
concurrency cannot perturb the ranking.

**The pinned gate** (:func:`pin_golden` / :func:`check_scorecard`): the
winner's scorecard on the STANDARD gate trace is written to
``tests/data/wind_tunnel_golden.json`` with per-metric tolerance bands.
tests/test_wind_tunnel_gate.py replays the gate every tier-1 run and
reds when the scorecard leaves the bands — protecting placement
QUALITY, not just throughput, from regressions (a deliberate policy
downgrade, e.g. worstfit, lands far outside the bands — the test
proves that too). Re-baselining is an explicit act:
``python -m tpushare.sim --autotune --pin`` (see docs/ops.md).
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass

from tpushare.metrics import LabeledCounter
from tpushare.sim.engine_loop import LoopKnobs, run_sim_native
from tpushare.sim.simulator import Fleet, TraceSpec, synth_trace
from tpushare.sim.traces import DiurnalSpec, SpikeWindow, synth_diurnal

SIM_AUTOTUNE_RUNS = LabeledCounter(
    "tpushare_sim_autotune_runs_total",
    "Autotune sweep replays by outcome (ok = scorecard produced, "
    "error = the config's replay raised and was excluded from the "
    "ranking — any error makes the sweep non-exhaustive, so a nonzero "
    "rate deserves a look before trusting a winner)",
    ("outcome",))

# The standard GATE workload: a saturating replay on a small fleet —
# heavy enough that policy quality moves every scorecard axis (the
# binpack-vs-worstfit duel in tests/test_sim.py uses this exact
# pressure), small enough for tier-1. The golden pins the winner's
# scorecard HERE, so the gate is stable even when the sweep trace grows.
GATE_TRACE = TraceSpec(n_pods=300, arrival_rate=8.0, mean_duration=60.0,
                       multi_chip_fraction=0.3, seed=42)
GATE_FLEET = {"nodes": 12, "chips": 4, "hbm": 16384, "mesh": (2, 2)}

# The default SWEEP workload: one full diurnal period compressed into
# two hours over a 100-node fleet, saturating at the peak plus a spike
# window — enough pressure that batching / scatter / defrag genuinely
# separate in the ranking (pending backlogs form at the peak), small
# enough that 18 replays finish in well under a minute.
SWEEP_SPEC = DiurnalSpec(hours=2.0, period=2.0, base_rate=500.0,
                         peak_rate=1500.0, seed=7,
                         spikes=(SpikeWindow(0.6, 0.25, 1.6),))
SWEEP_FLEET = {"nodes": 100, "chips": 4, "hbm": 16384, "mesh": (2, 2)}

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "tests", "data",
    "wind_tunnel_golden.json")

# tolerance bands around the pinned scorecard: replays are
# deterministic, so the bands exist to absorb INTENDED small shifts
# (a kernel tie-break reshuffle, a trace-generator tweak) while a
# policy-quality regression — worstfit moves utilization by tens of
# points on the gate trace — cannot hide inside them
DEFAULT_BANDS = {
    "time_weighted_util_pct": 1.0,
    "rejection_rate": 0.03,
    "p99_pending_age_s": 3.0,
}


@dataclass(frozen=True)
class SweepRow:
    """One ranked configuration of the winners table."""

    rank: int
    config_id: int
    knobs: LoopKnobs
    scorecard: dict
    sim_pods_per_sec: float        # informational ONLY — never ranks
    pods: int
    placed: int

    def to_json(self) -> dict:
        return {
            "rank": self.rank,
            "config_id": self.config_id,
            "knobs": asdict(self.knobs),
            "scorecard": self.scorecard,
            "sim_pods_per_sec": round(self.sim_pods_per_sec, 1),
            "pods": self.pods,
            "placed": self.placed,
        }


def knob_grid() -> list[LoopKnobs]:
    """The 18-point sweep: full cross of the three QUALITY knobs
    (batch window x scatter threshold x defrag budget), with the two
    THROUGHPUT knobs cycled so their pods/sec effect shows in the
    table without exploding the grid (they cannot change a scorecard —
    the engine-loop tests pin that invariance)."""
    schemes = ("off", "pow2", "exact")
    lrus = (32, 8, 4)
    grid = []
    for bw in (0.0, 0.05, 0.2):
        for scatter in (0.0, 70.0, 90.0):
            for budget in (0, 2):
                i = len(grid)
                grid.append(LoopKnobs(
                    batch_window=bw,
                    scatter_util_pct=scatter,
                    defrag_budget=budget,
                    index_scheme=schemes[i % 3],
                    eqclass_lru=lrus[i % 3]))
    return grid


def _rank_key(row: tuple) -> tuple:
    """(rejection, p99 pending age, -util, config id): admission beats
    latency beats density; the config id makes total order explicit."""
    cid, _knobs, card, _pps, _pods, _placed = row
    return (card["rejection_rate"] or 0.0, card["p99_pending_age_s"],
            -card["time_weighted_util_pct"], cid)


def run_sweep(trace=None, fleet_spec: dict | None = None,
              grid: list[LoopKnobs] | None = None,
              workers: int | None = None) -> dict:
    """Replay every grid config over the trace, rank by scorecard.

    ``trace`` defaults to the diurnal SWEEP_SPEC; pass a list of
    SimPod to sweep a custom workload (the CLI's trace flags do).
    Returns the winners table: ``{"rows": [...], "winner": {...},
    "errors": [...]}``.
    """
    import time
    from concurrent.futures import ThreadPoolExecutor

    if trace is None:
        trace = synth_diurnal(SWEEP_SPEC)
    fleet_spec = fleet_spec or SWEEP_FLEET
    grid = grid if grid is not None else knob_grid()

    def one(cid_knobs):
        cid, knobs = cid_knobs
        fleet = Fleet.homogeneous(
            fleet_spec["nodes"], fleet_spec["chips"], fleet_spec["hbm"],
            tuple(fleet_spec["mesh"]) if fleet_spec.get("mesh") else None)
        t0 = time.perf_counter()
        report, _stats = run_sim_native(fleet, trace, knobs)
        wall = time.perf_counter() - t0
        SIM_AUTOTUNE_RUNS.inc("ok")
        return (cid, knobs, report.scorecard(),
                report.pods / wall if wall > 0 else 0.0,
                report.pods, report.placed)

    rows, errors = [], []
    if workers is None:
        workers = min(4, os.cpu_count() or 1)
    with ThreadPoolExecutor(max_workers=max(1, workers)) as pool:
        futures = [(cid, knobs, pool.submit(one, (cid, knobs)))
                   for cid, knobs in enumerate(grid)]
        for cid, knobs, fut in futures:
            try:
                rows.append(fut.result())
            except Exception as e:  # a broken config must not sink the sweep
                SIM_AUTOTUNE_RUNS.inc("error")
                errors.append({"config_id": cid, "knobs": asdict(knobs),
                               "error": f"{type(e).__name__}: {e}"})
    rows.sort(key=_rank_key)
    table = [SweepRow(rank=i + 1, config_id=cid, knobs=knobs,
                      scorecard=card, sim_pods_per_sec=pps, pods=pods,
                      placed=placed)
             for i, (cid, knobs, card, pps, pods, placed)
             in enumerate(rows)]
    return {
        "mode": "autotune",
        "configs": len(grid),
        "ranked": len(table),
        "errors": errors,
        "rank_key": "(rejection_rate, p99_pending_age_s, -util_pct)",
        "rows": [r.to_json() for r in table],
        "winner": table[0].to_json() if table else None,
    }


# -- the pinned regression gate ----------------------------------------------

def gate_scorecard(knobs: LoopKnobs) -> dict:
    """The winner's scorecard on the STANDARD gate workload — the
    number the golden pins and tier-1 replays."""
    fleet = Fleet.homogeneous(GATE_FLEET["nodes"], GATE_FLEET["chips"],
                              GATE_FLEET["hbm"], GATE_FLEET["mesh"])
    report, _ = run_sim_native(fleet, synth_trace(GATE_TRACE), knobs)
    return report.scorecard()


def pin_golden(winner_knobs: LoopKnobs, path: str | None = None,
               bands: dict | None = None) -> dict:
    """Write the gate golden: winner knobs + their gate-trace scorecard
    + tolerance bands. Deliberate re-baselining ONLY (docs/ops.md)."""
    golden = {
        "gate_trace": asdict(GATE_TRACE),
        "gate_fleet": {k: (list(v) if isinstance(v, tuple) else v)
                       for k, v in GATE_FLEET.items()},
        "winner_knobs": asdict(winner_knobs),
        "scorecard": gate_scorecard(winner_knobs),
        "bands": dict(bands or DEFAULT_BANDS),
    }
    path = path or GOLDEN_PATH
    with open(path, "w") as f:
        json.dump(golden, f, indent=1, sort_keys=True)
        f.write("\n")
    return golden


def load_golden(path: str | None = None) -> dict:
    with open(path or GOLDEN_PATH) as f:
        return json.load(f)


def check_scorecard(scorecard: dict, golden: dict) -> list[str]:
    """Band check: empty list = inside every band; otherwise one
    human-readable violation per metric (what the gate test prints)."""
    out = []
    pinned = golden["scorecard"]
    for metric, band in golden["bands"].items():
        want = pinned[metric]
        got = scorecard.get(metric)
        if want is None or got is None:
            if got != want:
                out.append(f"{metric}: got {got!r}, pinned {want!r}")
            continue
        if abs(got - want) > band:
            out.append(f"{metric}: {got} outside pinned {want} "
                       f"+/- {band}")
    return out
