"""Discrete-event fleet simulator over the real placement kernel.

Pods arrive (poisson-ish, seeded), hold chips for a duration, and leave;
placement goes through :func:`tpushare.core.placement.select_chips_py` —
the behavioral spec the extender's native engine mirrors — so simulated
numbers reflect production decisions. Pods that don't fit wait in a FIFO
pending queue and retry at every departure (the default scheduler's
retry-on-timeout, collapsed to its next useful moment).

Three policies quantify the design choices:

- ``binpack``   — tpushare's: min-free-that-fits chips, contiguous
                  sub-slice multi-chip, tightest-scoring node.
- ``reference`` — the reference fork's semantics (allocateGPUID binpack
                  for one device, nodeinfo.go:283-286; first-fit-by-index
                  scatter for N, nodeinfo.go:312-363; first fitting node).
- ``worstfit``  — anti-policy control: most-free chips/node (spreads load,
                  maximizes fragmentation).

Reported utilization is time-weighted (integral of used HBM over the busy
interval), the honest number for capacity planning — peak and rejection
counts come along for sizing headroom.
"""

from __future__ import annotations

import heapq
import itertools
import random
from dataclasses import dataclass, field
from typing import Callable

from tpushare.core.chips import ChipView
from tpushare.core.placement import (
    PlacementRequest, fragmentation, select_chips_py)
from tpushare.core.topology import MeshTopology


@dataclass(frozen=True)
class SimPod:
    arrival: float
    duration: float
    hbm_mib: int
    chip_count: int = 1
    topology: tuple[int, ...] | None = None
    priority: int = 0
    # QoS tier (ISSUE 17): consumed only by the tiered oversubscription
    # sim (tpushare.sim.qos); the classic loops ignore it, so existing
    # traces and goldens are untouched.
    qos_tier: str = "burstable"
    # declared dp x tp mesh shape (ABI v7): consumed only by the
    # topology wind tunnel (tpushare.sim.topo); the classic loops and
    # the `request` property ignore it, so existing goldens hold.
    mesh_shape: tuple[int, ...] | None = None

    @property
    def request(self) -> PlacementRequest:
        return PlacementRequest(
            hbm_mib=self.hbm_mib, chip_count=self.chip_count,
            topology=self.topology,
            allow_scatter=self.chip_count > 1 and self.topology is None)


@dataclass(frozen=True)
class TraceSpec:
    """Synthetic workload knobs (all sizes MiB, times in abstract units)."""
    n_pods: int = 200
    arrival_rate: float = 2.0          # mean arrivals per time unit
    mean_duration: float = 40.0
    sizes: tuple[int, ...] = (1024, 2048, 4096, 8192)
    multi_chip_fraction: float = 0.15  # of pods; count drawn from {2, 4}
    high_priority_fraction: float = 0.0  # of pods; priority 100 vs 0
    seed: int = 0


def _p99(xs: list[float]) -> float:
    """Sorted-percentile idiom shared by both sim loops."""
    return sorted(xs)[int(0.99 * (len(xs) - 1))] if xs else 0.0


def synth_trace(spec: TraceSpec) -> list[SimPod]:
    rng = random.Random(spec.seed)
    t = 0.0
    pods = []
    for _ in range(spec.n_pods):
        t += rng.expovariate(spec.arrival_rate)
        duration = rng.expovariate(1.0 / spec.mean_duration)
        size = rng.choice(spec.sizes)
        prio = 100 if rng.random() < spec.high_priority_fraction else 0
        if rng.random() < spec.multi_chip_fraction:
            count = rng.choice((2, 4))
            topo = (2, 2) if count == 4 and rng.random() < 0.5 else None
            pods.append(SimPod(t, duration, size, count, topo,
                               priority=prio))
        else:
            pods.append(SimPod(t, duration, size, priority=prio))
    return pods


class _Node:
    def __init__(self, name: str, chips: int, hbm: int,
                 mesh: tuple[int, ...] | None) -> None:
        self.name = name
        self.topo = MeshTopology(mesh) if mesh \
            else MeshTopology.for_chip_count(chips)
        self.hbm = hbm
        self.used = [0] * chips
        # fault state (ISSUE 13): a down node schedules nothing; a
        # degraded chip is permanently out of the healthy set
        self.down = False
        self.unhealthy: set[int] = set()

    def chip_healthy(self, i: int) -> bool:
        return not self.down and i not in self.unhealthy

    def views(self) -> list[ChipView]:
        if not self.down and not self.unhealthy:
            # healthy fast path: identical objects to the pre-fault code
            return [ChipView(i, self.topo.coords(i), self.hbm, u)
                    for i, u in enumerate(self.used)]
        return [ChipView(i, self.topo.coords(i), self.hbm, u,
                         self.chip_healthy(i))
                for i, u in enumerate(self.used)]


class Fleet:
    """A set of simulated hosts, e.g. ``Fleet.homogeneous(8, 4, 16384,
    (2, 2))`` = eight 4-chip v5e hosts."""

    def __init__(self) -> None:
        self.nodes: list[_Node] = []

    @classmethod
    def homogeneous(cls, n_nodes: int, chips: int, hbm_per_chip: int,
                    mesh: tuple[int, ...] | None = None) -> "Fleet":
        f = cls()
        for i in range(n_nodes):
            f.nodes.append(_Node(f"sim-{i}", chips, hbm_per_chip, mesh))
        return f

    @property
    def total_hbm(self) -> int:
        return sum(n.hbm * len(n.used) for n in self.nodes)

    @property
    def used_hbm(self) -> int:
        return sum(sum(n.used) for n in self.nodes)

    def all_views(self) -> list[ChipView]:
        out: list[ChipView] = []
        for n in self.nodes:
            out.extend(n.views())
        return out


# -- policies: (fleet, request) -> (node_index, chip_ids) or None ------------

def _eligible(view: ChipView, req: PlacementRequest) -> bool:
    if not view.healthy:
        return False
    if req.hbm_mib == 0:
        return view.used_hbm_mib == 0
    return view.free_hbm_mib >= req.hbm_mib


def _policy_binpack(fleet: Fleet, req: PlacementRequest):
    best = None
    for ni, node in enumerate(fleet.nodes):
        p = select_chips_py(node.views(), node.topo, req)
        if p is not None and (best is None or p.score < best[2]):
            best = (ni, p.chip_ids, p.score)
    return (best[0], best[1]) if best else None


def _policy_reference(fleet: Fleet, req: PlacementRequest):
    for ni, node in enumerate(fleet.nodes):
        views = node.views()
        elig = [v for v in views if _eligible(v, req)]
        if len(elig) < req.chip_count:
            continue
        if req.chip_count == 1:
            # allocateGPUID: min free that fits (nodeinfo.go:283-286)
            chosen = min(elig, key=lambda v: (v.free_hbm_mib, v.idx))
            return ni, (chosen.idx,)
        # fork's allocateGPUIDs: first-fit by device index
        return ni, tuple(v.idx for v in elig[:req.chip_count])
    return None


def _policy_worstfit(fleet: Fleet, req: PlacementRequest):
    best = None
    for ni, node in enumerate(fleet.nodes):
        elig = sorted((v for v in node.views() if _eligible(v, req)),
                      key=lambda v: (-v.free_hbm_mib, v.idx))
        if len(elig) < req.chip_count:
            continue
        free = sum(v.free_hbm_mib for v in elig[:req.chip_count])
        if best is None or free > best[2]:
            best = (ni, tuple(v.idx for v in elig[:req.chip_count]), free)
    return (best[0], best[1]) if best else None


POLICIES: dict[str, Callable] = {
    "binpack": _policy_binpack,
    "reference": _policy_reference,
    "worstfit": _policy_worstfit,
}


def _is_contiguous_box(topo: MeshTopology, chip_ids: tuple[int, ...],
                       shape: tuple[int, ...]) -> bool:
    """Do the chips form an axis-aligned sub-box of the given shape?"""
    coords = sorted(topo.coords(c) for c in chip_ids)
    if len(coords) != len(set(coords)):
        return False
    lo = tuple(min(c[d] for c in coords) for d in range(len(coords[0])))
    want = sorted(
        tuple(lo[d] + off[d] for d in range(len(lo)))
        for off in _box_offsets(shape, len(lo)))
    return want == coords


def _box_offsets(shape: tuple[int, ...], rank: int):
    dims = tuple(shape) + (1,) * (rank - len(shape))
    def rec(d):
        if d == rank:
            yield ()
            return
        for i in range(dims[d]):
            for rest in rec(d + 1):
                yield (i,) + rest
    return list(rec(0))


@dataclass
class SimReport:
    policy: str
    pods: int
    placed: int
    never_placed: int
    mean_wait: float
    p99_wait: float
    util_pct: float          # time-weighted used/total over the busy span
    peak_util_pct: float
    frag_time_weighted: float
    makespan: float
    # pods whose ICI-topology pin (e.g. 2x2) was placed on NON-contiguous
    # chips: such a workload runs degraded (inter-chip traffic off the
    # mesh sub-slice) — the failure mode tpushare's contiguous placement
    # exists to prevent, and the reason scatter policies' utilization
    # numbers are not comparable at face value
    contig_violations: int = 0
    # preemption (when enabled): total evictions; evictions that did NOT
    # make the preemptor placeable (the scalar policy's failure mode);
    # high-priority wait stats
    preempt_mode: str = "off"
    evictions: int = 0
    wasted_evictions: int = 0
    # scalar mode: preemption "succeeded" with zero victims (aggregate
    # free looked sufficient) but the pod still couldn't place — the
    # real-cluster livelock of a scheduler whose preemption dry-run
    # skips extenders without a PreemptVerb: it nominates the node,
    # evicts nobody, and nothing ever changes
    noop_preemptions: int = 0
    hp_mean_wait: float = 0.0
    hp_p99_wait: float = 0.0
    # fault schedule (ISSUE 13): events consumed from the trace's fault
    # list, and running pods killed by node_down(lose_pods=True) —
    # those restart with full duration, so fault cost lands in the
    # victims' wait tail exactly like preemption evictions
    faults_applied: int = 0
    fault_lost_pods: int = 0
    waits: list[float] = field(default_factory=list, repr=False)

    def scorecard(self) -> dict:
        """The placement-quality scorecard in the SAME schema the live
        fleet publishes on /inspect/fleet (obs/fleetwatch.Scorecard)
        and bench.py's fleet_health section self-checks — so simulated
        policy sweeps and production fleets are compared in one
        currency (time-weighted utilization, rejection rate, p99
        pending age)."""
        return {
            "time_weighted_util_pct": round(self.util_pct, 4),
            "rejection_rate": round(self.never_placed / self.pods, 4)
            if self.pods else None,
            "p99_pending_age_s": round(self.p99_wait, 4),
        }

    def to_json(self) -> dict:
        # key order is sorted, NOT dataclass-declaration order: the
        # --procs determinism proof and the autotune ranking both
        # compare serialized reports byte-for-byte, so the ordering is
        # part of the contract (tests/test_sim.py pins it)
        out = {k: (round(v, 4) if isinstance(v, float) else v)
               for k, v in self.__dict__.items() if k != "waits"}
        out["scorecard"] = self.scorecard()
        return {k: out[k] for k in sorted(out)}


def run_sim(fleet: Fleet, trace: list[SimPod],
            policy: str = "binpack", preempt: str = "off",
            faults: list | None = None) -> SimReport:
    """Run one policy over one trace. Deterministic for a given input.

    ``faults`` is an optional :class:`tpushare.sim.traces.FaultEvent`
    schedule (see :func:`tpushare.sim.traces.synth_faults`). Fault
    events enter the same event heap with a kind that sorts BEFORE
    departures and arrivals at equal times, so both engines observe
    the fault at the same instant; the native engine loop consumes the
    identical list and must produce a byte-identical report
    (tests/test_sim_faults.py).

    ``preempt`` models priority preemption for arrivals that fit nowhere:

    - ``"off"``     — they wait in the pending queue (reference behavior:
                      the verb is unregistered).
    - ``"scalar"``  — kube-scheduler-without-extender semantics: victims
                      are chosen by NODE-level arithmetic (evict
                      lowest-priority pods until aggregate free >= the
                      request); the eviction happens even when no single
                      chip/sub-slice becomes free enough — those are
                      counted in ``wasted_evictions``.
    - ``"refined"`` — the preempt verb's semantics: per-chip greedy +
                      prune victim refinement (NodeInfo.victims_to_fit);
                      eviction only on a node where a 1-minimal subset
                      provably frees a placement.

    Evicted pods restart: they return to the pending queue with their
    full duration (waits keep their original arrival, so eviction cost
    shows up in the victims' wait tail).
    """
    assert preempt in ("off", "scalar", "refined"), preempt
    # a callable policy is accepted for wrappers (run_sim_sharded
    # decorates a named policy with ownership attribution)
    place = policy if callable(policy) else POLICIES[policy]
    policy = policy if isinstance(policy, str) \
        else getattr(policy, "policy_name", "custom")
    # event heap: (time, kind, seq, payload); kind -1=fault,
    # 0=departure, 1=arrival (faults first at equal times — the fleet
    # changes state before capacity frees or pods land; then
    # departures: free capacity before retrying)
    heap: list[tuple] = []
    for seq, pod in enumerate(sorted(trace, key=lambda p: p.arrival)):
        heapq.heappush(heap, (pod.arrival, 1, seq, pod))
    for fidx, ev in enumerate(faults or []):
        heapq.heappush(heap, (ev.time, -1, fidx, ev))
    pending: list[SimPod] = []
    waits: list[float] = []
    hp_waits: list[float] = []
    placed = 0
    violations = 0
    evictions = 0
    wasted_evictions = 0
    noop_preemptions = 0
    faults_applied = 0
    fault_lost = 0
    stalled = 0  # open brownout/replica-crash windows: scheduling pauses
    # seq2 id -> (pod, node_index, chip_ids, demand); departures whose id
    # is in `cancelled` were evicted and are skipped lazily
    active: dict[int, tuple] = {}
    cancelled: set[int] = set()
    now = 0.0
    util_integral = 0.0
    frag_integral = 0.0
    peak = 0.0
    busy_start: float | None = None
    last_t = 0.0
    seq2 = len(trace)

    def advance(to: float) -> None:
        nonlocal util_integral, frag_integral, last_t, peak
        dt = to - last_t
        if dt > 0:
            used = fleet.used_hbm
            util_integral += used * dt
            frag_integral += fragmentation(fleet.all_views()) * dt
            peak = max(peak, used / fleet.total_hbm * 100.0)
        last_t = to

    def try_place(pod: SimPod) -> bool:
        nonlocal placed, seq2, violations
        decision = place(fleet, pod.request)
        if decision is None:
            return False
        ni, chip_ids = decision
        node = fleet.nodes[ni]
        if pod.topology is not None and not _is_contiguous_box(
                node.topo, chip_ids, pod.topology):
            violations += 1
        demand = pod.request.chip_demand_mib(node.hbm)
        for cid in chip_ids:
            node.used[cid] += demand
            assert node.used[cid] <= node.hbm, "sim oversubscription"
        heapq.heappush(heap, (now + pod.duration, 0, seq2,
                              (ni, chip_ids, demand)))
        active[seq2] = (pod, ni, chip_ids, demand)
        seq2 += 1
        placed += 1
        waits.append(now - pod.arrival)
        if pod.priority > 0:
            hp_waits.append(now - pod.arrival)
        return True

    def _evict(vid: int) -> SimPod:
        nonlocal evictions
        pod, ni, chip_ids, demand = active.pop(vid)
        node = fleet.nodes[ni]
        for cid in chip_ids:
            node.used[cid] -= demand
        cancelled.add(vid)
        evictions += 1
        return pod

    def try_preempt(pod: SimPod) -> bool:
        """Arrival that fits nowhere: evict lower-priority pods.
        Returns True when the pod got placed."""
        nonlocal wasted_evictions, noop_preemptions
        req = pod.request
        best = None  # (n_victims, freed_hbm, node_index, victim_ids)
        for ni, node in enumerate(fleet.nodes):
            # cheapest eviction first: (priority, total HBM, id)
            vics = sorted(
                ((vid, e) for vid, e in active.items()
                 if e[1] == ni and e[0].priority < pod.priority),
                key=lambda t: (t[1][0].priority,
                               t[1][3] * len(t[1][2]), t[0]))
            if preempt == "scalar":
                # node-level arithmetic: free aggregate >= total request.
                # chosen may come out EMPTY (aggregate already "fits"):
                # kube-scheduler's preemption dry-run skips extenders
                # without a PreemptVerb, so such a node is a legitimate
                # zero-victim candidate that the scheduler PREFERS
                # (fewest victims) — modeling it is the point
                total_req = req.chip_demand_mib(node.hbm) * max(
                    req.chip_count, 1)
                free = node.hbm * len(node.used) - sum(node.used)
                chosen = []
                for vid, e in vics:
                    if free >= total_req:
                        break
                    chosen.append(vid)
                    free += e[3] * len(e[2])
                if free < total_req:
                    continue
            else:
                if not vics:
                    continue
                # refined: per-chip greedy + prune over hypothetical
                # usage (the verb's victims_to_fit)
                def fits_without(evicted_ids):
                    freed = {}
                    for vid in evicted_ids:
                        e = active[vid]
                        for cid in e[2]:
                            freed[cid] = freed.get(cid, 0) + e[3]
                    views = [ChipView(i, node.topo.coords(i), node.hbm,
                                      u - freed.get(i, 0),
                                      node.chip_healthy(i))
                             for i, u in enumerate(node.used)]
                    return select_chips_py(views, node.topo, req) is not None
                chosen = []
                for vid, _ in vics:
                    chosen.append(vid)
                    if fits_without(chosen):
                        break
                else:
                    continue
                for vid in list(reversed(chosen[:-1])):
                    trial = [u for u in chosen if u != vid]
                    if fits_without(trial):
                        chosen = trial
            freed_hbm = sum(active[v][3] * len(active[v][2])
                            for v in chosen)
            key = (len(chosen), freed_hbm)
            if best is None or key < best[:2]:
                best = (*key, ni, chosen)
        if best is None:
            return False
        _, _, ni, victim_ids = best
        for vid in victim_ids:
            victim = _evict(vid)
            pending.append(victim)  # restarts: full duration again
        if try_place(pod):
            return True
        # scalar mode reaches here when node-level arithmetic said the
        # node would fit but no chip/sub-slice actually works: either
        # pods were killed for nothing, or (zero victims) the scheduler
        # nominated a node and changed nothing — the two faces of the
        # blind spot the preempt verb fixes
        if victim_ids:
            wasted_evictions += len(victim_ids)
        else:
            noop_preemptions += 1
        return False

    while heap:
        t, kind, seq_id, payload = heapq.heappop(heap)
        advance(t)
        now = t
        if busy_start is None:
            busy_start = t
        if kind == -1:  # fault event (traces.FaultEvent)
            ev = payload
            faults_applied += 1
            if ev.kind in ("brownout_start", "replica_crash"):
                stalled += 1
            elif ev.kind in ("brownout_end", "replica_restart"):
                stalled = max(0, stalled - 1)
            elif ev.kind == "node_down":
                node = fleet.nodes[ev.node]
                node.down = True
                if ev.lose_pods:
                    # crash: running pods die and restart — free their
                    # chips, cancel their queued departures lazily, and
                    # requeue with full duration (waits keep the
                    # original arrival, like preemption evictions)
                    for vid in sorted(v for v, e in active.items()
                                      if e[1] == ev.node):
                        pod, ni, chip_ids, demand = active.pop(vid)
                        for cid in chip_ids:
                            fleet.nodes[ni].used[cid] -= demand
                        cancelled.add(vid)
                        fault_lost += 1
                        pending.append(pod)
            elif ev.kind == "node_up":
                fleet.nodes[ev.node].down = False
            elif ev.kind == "degrade":
                fleet.nodes[ev.node].unhealthy.update(ev.chips)
            # any fault may have moved capacity or schedulability
            # (restored node, killed pods freeing room elsewhere via
            # restarts, healed brownout) — retry unless still stalled
            if stalled == 0:
                pending = [q for q in pending if not try_place(q)]
        elif kind == 1:  # arrival
            if stalled:
                pending.append(payload)  # apiserver dark: nothing binds
            elif not try_place(payload):
                attempted = preempt != "off" and payload.priority > 0
                if not (attempted and try_preempt(payload)):
                    pending.append(payload)
                if attempted and pending:
                    # ANY preemption attempt may have moved capacity —
                    # victims evicted (even when the preemptor still
                    # failed to place: the wasted-eviction case), slack
                    # left next to a placed preemptor. Without a retry
                    # here, evicted pods whose cancelled departures are
                    # the only remaining heap events starve forever on a
                    # free fleet
                    pending = [q for q in pending if not try_place(q)]
        else:          # departure frees chips, retry pending FIFO
            if seq_id in cancelled:
                # this placement was evicted earlier; its chips were
                # already freed at eviction time
                cancelled.discard(seq_id)
                continue
            active.pop(seq_id, None)
            ni, chip_ids, demand = payload
            node = fleet.nodes[ni]
            for cid in chip_ids:
                node.used[cid] -= demand
            if stalled:
                continue  # capacity freed, but nothing can bind now
            still = []
            for pod in pending:
                if not try_place(pod):
                    still.append(pod)
            pending = still

    span = max(last_t - (busy_start or 0.0), 1e-9)
    return SimReport(
        policy=policy,
        pods=len(trace),
        placed=placed,
        never_placed=len(pending),
        mean_wait=sum(waits) / len(waits) if waits else 0.0,
        p99_wait=_p99(waits),
        util_pct=util_integral / (fleet.total_hbm * span) * 100.0,
        peak_util_pct=peak,
        frag_time_weighted=frag_integral / span,
        makespan=span,
        contig_violations=violations,
        preempt_mode=preempt,
        evictions=evictions,
        wasted_evictions=wasted_evictions,
        noop_preemptions=noop_preemptions,
        hp_mean_wait=sum(hp_waits) / len(hp_waits) if hp_waits else 0.0,
        hp_p99_wait=_p99(hp_waits),
        faults_applied=faults_applied,
        fault_lost_pods=fault_lost,
        waits=waits,
    )


# -- sharded scheduling (active-active scale-out, ISSUE 10) ------------------

def run_sim_sharded(fleet: Fleet, trace: list[SimPod],
                    policy: str = "binpack", shards: int = 2,
                    vnodes: int | None = None
                    ) -> tuple[SimReport, dict]:
    """Replay ``trace`` with ``shards`` simulated shard owners and prove
    placement quality is UNCHANGED by sharding.

    The model mirrors the live design exactly: sharding never alters a
    scheduling verdict — every replica scores the whole fleet (owned
    nodes from its resident views, foreign nodes via a transient scan),
    so the chosen (node, chips) is identical to the unsharded run. What
    sharding changes is the BIND mechanics: a verdict landing on the
    handling replica's own shard binds lock-free, a foreign verdict
    pays the claim-CAS spillover path. This wrapper attributes each
    placement to a round-robin handling replica and a consistent-hash
    ring over the node names (the real ring code), returning the
    unchanged :class:`SimReport` plus the owned/spillover split — the
    expected spillover share is (N-1)/N, which the live
    ``tpushare_shard_conflicts_total`` metric should track.
    """
    from tpushare.ha.ring import DEFAULT_VNODES, HashRing

    members = [f"replica-{i}" for i in range(max(1, shards))]
    ring = HashRing(members, vnodes=vnodes or DEFAULT_VNODES)
    base = POLICIES[policy]
    counts = {"owned": 0, "spillover": 0}
    cursor = itertools.count()

    def sharded(fleet_: Fleet, req: PlacementRequest):
        decision = base(fleet_, req)
        if decision is not None:
            replica = members[next(cursor) % len(members)]
            node_name = fleet_.nodes[decision[0]].name
            if ring.owner(node_name) == replica:
                counts["owned"] += 1
            else:
                counts["spillover"] += 1
        return decision

    sharded.policy_name = policy
    report = run_sim(fleet, trace, policy=sharded)
    total = counts["owned"] + counts["spillover"]
    stats = {
        "shards": len(members),
        "vnodes": ring.vnodes,
        "shard_sizes": ring.shard_sizes(n.name for n in fleet.nodes),
        "owned_binds": counts["owned"],
        "spillover_binds": counts["spillover"],
        "spillover_rate": round(counts["spillover"] / total, 4)
        if total else None,
    }
    return report, stats


# -- multi-host slice (gang) simulation -------------------------------------

def synth_slice_trace(n_pods: int = 120, seed: int = 0,
                      gang_fraction: float = 0.3,
                      arrival_rate: float = 1.0,
                      mean_duration: float = 40.0) -> list[SimPod]:
    """Mixed slice workload: single-chip sharing tenants plus 2x2 and
    2x4 exclusive gangs (2x4 cannot fit any single v5e host — it EXISTS
    only if placement is slice-aware)."""
    rng = random.Random(seed)
    t = 0.0
    out = []
    for _ in range(n_pods):
        t += rng.expovariate(arrival_rate)
        dur = rng.expovariate(1.0 / mean_duration)
        if rng.random() < gang_fraction:
            shape = rng.choice(((2, 2), (2, 4)))
            n = shape[0] * shape[1]
            out.append(SimPod(t, dur, hbm_mib=0, chip_count=n,
                              topology=shape))
        else:
            out.append(SimPod(t, dur, hbm_mib=rng.choice((4096, 8192)),
                              chip_count=1))
    return out


def run_slice_sim(trace: list[SimPod], singles_policy: str = "pack",
                  host_grid=(2, 2), host_box=(2, 2),
                  engine: str = "sequential") -> dict:
    """Discrete-event sim over ONE slice (v5e-16 default: 2x2 hosts of
    2x2 chips) through the gang kernel (core/slice.select_gang).

    ``singles_policy`` sets how single-chip tenants land, the knob the
    policy duel measures:

    - ``"pack"``   — min-free-that-fits, same-host-first (tpushare's
                     binpack extended with slice awareness);
    - ``"spread"`` — least-allocated with host-rotating ties (what the
                     default scheduler's scoring does to a slice).

    Gangs go through the gang kernel picked by ``engine``:
    ``"sequential"`` runs :func:`select_gang` (the Python behavioral
    spec); ``"oneshot"`` runs the ABI v5 one-shot native solve
    (:func:`tpushare.core.native.solve_gang`) and falls back to the
    sequential kernel when the native engine is unavailable — by the
    parity contract the scorecard is IDENTICAL either way, which the
    ``--gangs`` leg demonstrates by emitting both. Returns admission
    and utilization stats. Reference ceiling for context: its allocator
    is single-node, so every cross-host gang (2x4 here) is unplaceable
    by construction — this sim quantifies what slice-awareness buys
    BEYOND that structural gap.
    """
    from tpushare.core.slice import SliceTopology, select_gang

    assert singles_policy in ("pack", "spread")
    assert engine in ("sequential", "oneshot")
    n_hosts = 1
    for d in host_grid:
        n_hosts *= d
    names = [f"host{i}" for i in range(n_hosts)]
    st = SliceTopology.from_host_grid(tuple(host_grid), tuple(host_box),
                                      names)
    solves = {"oneshot": 0, "sequential": 0}
    if engine == "oneshot":
        from tpushare.core import native
        from tpushare.core.topology import HostMesh
        hmesh = HostMesh(grid=tuple(host_grid), hbox=tuple(host_box),
                         hosts=tuple(names))

        def solve(views_, req):
            gp = native.solve_gang(st, hmesh, views_, req)
            if gp == "fallback":
                solves["sequential"] += 1
                return select_gang(st, views_, req)
            solves["oneshot"] += 1
            return gp
    else:
        def solve(views_, req):
            solves["sequential"] += 1
            return select_gang(st, views_, req)
    local = MeshTopology(tuple(host_box))
    hbm = 16384
    used: dict[str, list[int]] = {h: [0] * local.num_chips
                                  for h in names}

    def views():
        return {h: [ChipView(i, local.coords(i), hbm, used[h][i])
                    for i in range(local.num_chips)] for h in names}

    heap: list[tuple] = []
    for seq, pod in enumerate(sorted(trace, key=lambda p: p.arrival)):
        heapq.heappush(heap, (pod.arrival, 1, seq, pod))
    pending: list[SimPod] = []
    placed = gangs_placed = gangs_total = singles_placed = 0
    gang_waits: list[float] = []
    seq2 = len(trace)
    now = last_t = 0.0
    util_integral = 0.0
    busy_start = min((p.arrival for p in trace), default=0.0)
    total_hbm = hbm * local.num_chips * n_hosts

    def advance(to):
        nonlocal util_integral, last_t
        dt = to - last_t
        if dt > 0:
            util_integral += sum(sum(u) for u in used.values()) * dt
        last_t = to

    def try_place(pod: SimPod) -> bool:
        nonlocal placed, gangs_placed, singles_placed, seq2
        if pod.chip_count > 1:
            req = PlacementRequest(hbm_mib=pod.hbm_mib,
                                   chip_count=pod.chip_count,
                                   topology=pod.topology)
            gp = solve(views(), req)
            if gp is None:
                return False
            demand = req.chip_demand_mib(hbm)  # full chip iff exclusive
            holds = []
            for host, p in gp.per_host.items():
                for cid in p.chip_ids:
                    used[host][cid] += demand
                    holds.append((host, cid, demand))
            gangs_placed += 1
            gang_waits.append(now - pod.arrival)
        else:
            cands = [(host, i) for host in names
                     for i in range(local.num_chips)
                     if hbm - used[host][i] >= pod.hbm_mib]
            if not cands:
                return False
            if singles_policy == "spread":
                host, i = max(cands, key=lambda hc: (
                    hbm - used[hc[0]][hc[1]], -hc[1]))
            else:
                host, i = min(cands, key=lambda hc: (
                    hbm - used[hc[0]][hc[1]], names.index(hc[0]), hc[1]))
            used[host][i] += pod.hbm_mib
            holds = [(host, i, pod.hbm_mib)]
            singles_placed += 1
        placed += 1
        heapq.heappush(heap, (now + pod.duration, 0, seq2, holds))
        seq2 += 1
        return True

    while heap:
        now, kind, _seq, payload = heapq.heappop(heap)
        advance(now)
        if kind == 1:
            if payload.chip_count > 1:
                gangs_total += 1
            if not try_place(payload):
                pending.append(payload)
        else:
            for host, cid, amount in payload:
                used[host][cid] -= amount
            still = []
            for pod in pending:
                if not try_place(pod):
                    still.append(pod)
            pending = still

    # busy-interval denominator, same definition as run_sim's
    span = max(last_t - busy_start, 1e-9)
    return {
        "singles_policy": singles_policy,
        "gang_engine": engine,
        "gang_solves": dict(solves),
        "pods": len(trace),
        "placed": placed,
        "never_placed": len(pending),
        "gangs_total": gangs_total,
        "gangs_placed": gangs_placed,
        "gang_admission_pct": round(
            gangs_placed / gangs_total * 100.0, 2) if gangs_total else 100.0,
        "gang_mean_wait": round(sum(gang_waits) / len(gang_waits), 2)
        if gang_waits else 0.0,
        "gang_p99_wait": round(_p99(gang_waits), 2),
        "util_pct": round(util_integral / (total_hbm * span) * 100.0, 2),
    }
