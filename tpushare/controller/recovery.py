"""Crash-restart reconciliation: adopt-or-GC what a dead replica left.

The allocate path is two apiserver writes with a gap between them —
patch the placement annotations (chip ids, HBM split, assume-time
stamp), then bind. A replica that crashes inside that gap leaves a
**half-bound** pod: placement annotations patched by an incarnation
that no longer exists, ``spec.nodeName`` never set. Nothing in the
normal event flow heals it — the default scheduler retries the pod
through Filter, but the stale annotations sit there forever, and a
careless replay would double-account the chips.

The node-local analogue already exists (deviceplugin
``gc_stale_assignments`` reclaims placements whose container start
never reached Allocate). This module is the scheduler-side,
cross-replica version, run by every replica on the controller's
anti-entropy heartbeat and once at startup right after ``build_cache``:

- **adopt**: a pod with nodeName + chip-ids the cache does not know
  (bound by a dead incarnation after our replay, or a bind that landed
  mid-reconcile) is accounted via ``add_or_update_pod`` —
  ``tpushare_recovery_adopted_total{kind="bound"|"late_bind"}``.
- **GC**: a half-bound pod older than ``stale_after_s`` (by its
  assume-time stamp) has its placement annotations stripped with the
  same resourceVersion-CAS PUT the stale-placement reclaim uses
  (contract.strip_placement) — a concurrent live allocate that
  re-stamped or bound wins the CAS and the placement stands.
  ``tpushare_recovery_gc_total{kind="half_bound"|"unstamped"}``.

The recovery window is bounded by construction: startup runs one pass
immediately, and the resync heartbeat (30 s default) re-runs it, so a
half-bound orphan lives at most ``stale_after_s`` + one heartbeat.
"""

from __future__ import annotations

import logging
import time
from typing import Any

from tpushare import contract
from tpushare.contract import pod as podlib
from tpushare.k8s.client import ApiError
from tpushare.metrics import LabeledCounter

log = logging.getLogger("tpushare.recovery")

# Adoption grace: comfortably past one allocate round-trip (the
# patch->bind gap is two apiserver writes plus the claim CAS), far
# under the resync heartbeat — a LIVE in-flight bind is never raced.
DEFAULT_STALE_AFTER_S = 15.0

RECOVERY_ADOPTED = LabeledCounter(
    "tpushare_recovery_adopted_total",
    "Pods adopted into the cache by crash-restart reconciliation "
    '(kind="bound": placed+bound pod the cache did not know; '
    '"late_bind": a half-bound pod whose bind landed mid-reconcile)',
    ("kind",))
RECOVERY_GC = LabeledCounter(
    "tpushare_recovery_gc_total",
    "Half-bound placements reclaimed by crash-restart reconciliation "
    '(kind="half_bound": annotations stamped by a dead incarnation, '
    'never bound; "unstamped": malformed — chip ids without an '
    "assume-time stamp)",
    ("kind",))


def reconcile_once(cluster, cache, *, now_ns: int | None = None,
                   stale_after_s: float = DEFAULT_STALE_AFTER_S
                   ) -> dict[str, int]:
    """One adopt-or-GC pass over the apiserver's pod truth.

    Returns ``{"adopted": n, "gc": n}`` for the pass. Safe to run
    concurrently with live scheduling on any replica: adoption is the
    same idempotent accounting the informer path uses, and GC is a
    resourceVersion CAS that loses (409) to any concurrent mutation.
    """
    if now_ns is None:
        now_ns = time.time_ns()
    adopted = reclaimed = 0
    try:
        pods = cluster.list_pods()
    except ApiError as e:
        log.warning("recovery: pod list failed, skipping pass: %s", e)
        return {"adopted": 0, "gc": 0}
    for pod in pods:
        if not contract.is_tpushare_pod(pod) \
                or contract.is_complete_pod(pod):
            continue
        if contract.chip_ids_from_annotations(pod) is None:
            continue
        if podlib.pod_node_name(pod):
            # bound + placed: the normal replay shape. build_cache
            # already accounted everything it listed; this covers pods
            # bound by a DEAD incarnation after our replay ran.
            if not cache.known_pod(podlib.pod_cache_key(pod)):
                cache.add_or_update_pod(pod)
                adopted += 1
                RECOVERY_ADOPTED.inc("bound")
                log.info("recovery: adopted bound pod %s",
                         podlib.pod_key(pod))
            continue
        # half-bound: placement annotations, no nodeName. Age by the
        # assume-time stamp the allocate path wrote per attempt.
        t = contract.assume_time_from_annotations(pod)
        if t and (now_ns - t) / 1e9 <= stale_after_s:
            continue  # inside a live allocate's window — leave it
        adp, rec = _adopt_or_gc(cluster, cache, pod, t)
        adopted += adp
        reclaimed += rec
    return {"adopted": adopted, "gc": reclaimed}


def _adopt_or_gc(cluster, cache, pod: dict[str, Any], t: int
                 ) -> tuple[int, int]:
    """Re-read one stale half-bound pod and adopt (the bind landed
    after our LIST) or GC it (CAS-strip the placement annotations)."""
    ns, name = podlib.pod_namespace(pod), podlib.pod_name(pod)
    try:
        fresh = cluster.get_pod(ns, name)
    except ApiError:
        return 0, 0  # vanished; termination frees everything
    if podlib.pod_node_name(fresh):
        # the bind landed between LIST and now: adopt, don't reclaim
        if contract.chip_ids_from_annotations(fresh) is not None and \
                not cache.known_pod(podlib.pod_cache_key(fresh)):
            cache.add_or_update_pod(fresh)
            RECOVERY_ADOPTED.inc("late_bind")
            log.info("recovery: adopted late-bound pod %s",
                     podlib.pod_key(fresh))
            return 1, 0
        return 0, 0
    if contract.is_assigned(fresh) or \
            contract.assume_time_from_annotations(fresh) != t:
        return 0, 0  # runtime granted chips / a live re-placement
    try:
        cluster.replace_pod(ns, name, contract.strip_placement(fresh))
    except ApiError as e:
        if e.is_conflict:
            log.info("recovery: reclaim of %s/%s lost a CAS race "
                     "(placement stands)", ns, name)
        else:
            log.warning("recovery: reclaim of %s/%s failed: %s",
                        ns, name, e)
        return 0, 0
    RECOVERY_GC.inc("half_bound" if t else "unstamped")
    log.warning("recovery: reclaimed half-bound placement of %s/%s",
                ns, name)
    return 0, 1
