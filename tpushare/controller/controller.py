"""The sync controller: watch streams -> workqueue -> cache reconciliation.

Reference: /root/reference/pkg/gpushare/controller.go. Same structure —
a pod watch filtered to tpushare pods feeding a rate-limited workqueue
(controller.go:77-100), worker loops running syncPod (controller.go:185-216),
plus node and configmap watches (controller.go:106-113) — without client-go:
watches come from the ClusterClient protocol and run on daemon threads.

The reconciliation rules match the reference exactly:
- deleted pod        -> remove from cache via the stashed last-seen copy
                        (controller.go:194-200, removePodCache:342)
- completed pod      -> remove (frees chips; controller.go:204-206)
- assigned+annotated -> add_or_update (controller.go:208-215)
- update events only enqueue when the pod became complete or an unknown pod
  gained a chip-ids annotation (controller.go:283-290)
- configmap ``unhealthy-tpu-<node>`` key ``chips`` (CSV ids) marks chips
  unschedulable (nodeinfo.go:406-431)
"""

from __future__ import annotations

import logging
import threading
from typing import Any

from tpushare import contract
from tpushare.cache import SchedulerCache
from tpushare.contract.constants import (
    UNHEALTHY_CM_KEY,
    UNHEALTHY_CM_NAMESPACE,
    UNHEALTHY_CM_PREFIX,
)
from tpushare.contract import node as nodelib
from tpushare.contract import pod as podlib
from tpushare.controller.workqueue import WorkQueue
from tpushare.k8s.client import ApiError

log = logging.getLogger("tpushare.controller")


def parse_unhealthy(data: dict[str, str] | None) -> set[int]:
    """CSV chip ids -> set (reference getUnhealthyGPUs parses the same
    format from the configmap, nodeinfo.go:414-429)."""
    if not data:
        return set()
    raw = data.get(UNHEALTHY_CM_KEY, "")
    out: set[int] = set()
    for part in raw.split(","):
        part = part.strip()
        if part.isdigit():
            out.add(int(part))
    return out


class Controller:
    def __init__(self, cluster, cache: SchedulerCache,
                 workers: int = 1, resync_seconds: float = 30.0) -> None:
        self._cluster = cluster
        self.cache = cache
        self._queue = WorkQueue()
        self._workers = workers
        self._resync_seconds = resync_seconds
        # extra anti-entropy work ridden on the resync heartbeat (e.g.
        # the gang coordinator's abandoned-plan expiry); hooks must be
        # cheap and exception-safe burdens are on the caller side — a
        # failing hook is logged and never takes resync down
        self.resync_hooks: list = []
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # last-seen copy of every queued pod so deletes can clean the cache
        # after the object is gone from the apiserver (controller.go:342)
        self._seen_lock = threading.Lock()
        self._seen: dict[str, dict[str, Any]] = {}  # ns/name -> pod

    # -- lifecycle ------------------------------------------------------------

    def build_cache(self) -> int:
        """Initial state: replay pods, then load unhealthy-chip configmaps
        for every known node (reference BuildCache + configmap lister warm).
        A single pod LIST serves both the cache replay and the stash."""
        pods = self._cluster.list_pods()
        replayed = self.cache.build_cache(pods=pods)
        for pod in pods:
            if contract.is_tpushare_pod(pod):
                with self._seen_lock:
                    self._seen[podlib.pod_key(pod)] = pod
        for name in self.cache.node_names():
            self._load_unhealthy(name)
        return replayed

    def start(self) -> None:
        self._spawn(self._pod_watch_loop, "pod-watch")
        self._spawn(self._node_watch_loop, "node-watch")
        self._spawn(self._cm_watch_loop, "cm-watch")
        self._spawn(self._resync_loop, "resync")
        for i in range(self._workers):
            self._spawn(self._worker_loop, f"worker-{i}")

    def stop(self) -> None:
        self._stop.set()
        self._queue.shut_down()
        for t in self._threads:
            t.join(timeout=2)

    def _spawn(self, fn, name: str) -> None:
        t = threading.Thread(target=fn, name=f"tpushare-{name}", daemon=True)
        t.start()
        self._threads.append(t)

    # -- watch loops ----------------------------------------------------------

    def _watch_forever(self, fn, name: str) -> None:
        """Run a watch-consuming loop, restarting it on any unexpected
        exception: a dead watch thread would silently freeze the cache
        (only the 30 s resync would remain, and nothing at all for node
        or configmap changes)."""
        while not self._stop.is_set():
            try:
                fn()
                return  # clean exit (stop set)
            except Exception as e:  # noqa: BLE001 — watch must survive
                log.warning("controller: %s watch crashed, restarting: %s",
                            name, e)
                self._stop.wait(1.0)

    def _pod_watch_loop(self) -> None:
        self._watch_forever(self._consume_pod_events, "pod")

    def _consume_pod_events(self) -> None:
        for ev in self._cluster.watch_pods(self._stop):
            pod = ev.object
            if not contract.is_tpushare_pod(pod):
                continue
            key = podlib.pod_key(pod)
            if ev.type == "ADDED":
                with self._seen_lock:
                    self._seen[key] = pod
                self._queue.add(key)
            elif ev.type == "MODIFIED":
                relevant = self._update_relevant(pod)
                with self._seen_lock:
                    self._seen[key] = pod
                if relevant:
                    self._queue.add(key)
            elif ev.type == "DELETED":
                # remove synchronously with the event's own object: going
                # through get_pod would race a same-name recreate (e.g. a
                # StatefulSet replacing web-0 with a new UID) and leak the
                # old UID's chip reservations forever
                self.cache.remove_pod(pod)
                with self._seen_lock:
                    stashed = self._seen.get(key)
                    if stashed is not None and \
                            podlib.pod_uid(stashed) == podlib.pod_uid(pod):
                        self._seen.pop(key, None)

    def _update_relevant(self, pod: dict[str, Any]) -> bool:
        """controller.go:283-290: process updates only when the pod became
        complete, or when a pod we don't track gained a placement — plus
        one tpushare extension: a pod we DO track that lost its placement
        (the device plugin's stale-placement reclaim cleared the
        annotations; its chips must free now, not at pod termination)."""
        if contract.is_complete_pod(pod):
            return True
        known = self.cache.known_pod(podlib.pod_cache_key(pod))
        has_placement = contract.chip_ids_from_annotations(pod) is not None
        if not known and has_placement:
            return True
        if known and not has_placement:
            return True
        return False

    def _node_watch_loop(self) -> None:
        self._watch_forever(self._consume_node_events, "node")

    def _consume_node_events(self) -> None:
        for ev in self._cluster.watch_nodes(self._stop):
            node = ev.object
            name = nodelib.node_name(node)
            if ev.type == "DELETED":
                self.cache.remove_node(name)
            elif contract.is_tpushare_node(node):
                self.cache.update_node(node)

    def _cm_watch_loop(self) -> None:
        self._watch_forever(self._consume_cm_events, "configmap")

    def _consume_cm_events(self) -> None:
        for ev in self._cluster.watch_configmaps(self._stop):
            cm = ev.object
            meta = cm.get("metadata") or {}
            name = meta.get("name", "")
            if meta.get("namespace") != UNHEALTHY_CM_NAMESPACE:
                continue
            if not name.startswith(UNHEALTHY_CM_PREFIX):
                continue
            node_name = name[len(UNHEALTHY_CM_PREFIX):]
            chips = set() if ev.type == "DELETED" \
                else parse_unhealthy(cm.get("data"))
            try:
                self.cache.get_node_info(node_name).set_unhealthy(chips)
                log.info("controller: node %s unhealthy chips = %s",
                         node_name, sorted(chips))
            except ApiError:
                pass  # node gone; nothing to mark

    def _resync_loop(self) -> None:
        """Periodic anti-entropy (reference: 30 s informer resync,
        cmd/main.go:28; SURVEY §5.4). Watch streams can drop events during
        reconnects — the k8s watch API does not replay a gap — so every
        resync re-lists pods, enqueues all live tpushare pods, and removes
        stashed pods that no longer exist (their DELETED event was missed)."""
        while not self._stop.wait(self._resync_seconds):
            try:
                self.resync_once()
            except Exception as e:  # noqa: BLE001 — loop must survive
                log.warning("controller: resync failed: %s", e)

    def resync_once(self) -> None:
        # Snapshot the stash BEFORE the LIST: only a pod observed before
        # the LIST and absent from it is provably gone. A pod created
        # (and bound) AFTER the LIST lands in _seen via its watch event
        # while this loop runs — judging that newer stash against the
        # older LIST flagged it "missed DELETED" and freed a LIVE bound
        # pod's chips, which the next bind then double-booked (real
        # oversubscription; caught by the chaos soak's churn storm).
        # Such a pod is simply not a candidate this round; a genuinely
        # deleted pod is caught by the NEXT resync, whose pre-snapshot
        # will contain it.
        with self._seen_lock:
            pre = dict(self._seen)
        pods = self._cluster.list_pods()
        live: dict[str, str] = {}
        for pod in pods:
            if not contract.is_tpushare_pod(pod):
                continue
            key = podlib.pod_key(pod)
            live[key] = podlib.pod_uid(pod)
            with self._seen_lock:
                self._seen[key] = pod
            self._queue.add(key)
        # uids never resurrect, so (pre-LIST stash, LIST) disagreement
        # is conclusive for THAT uid regardless of later stash updates
        stale = [(k, p) for k, p in pre.items()
                 if live.get(k) != podlib.pod_uid(p)]
        with self._seen_lock:
            for k, p in stale:
                cur = self._seen.get(k)
                if k not in live and cur is not None and \
                        podlib.pod_uid(cur) == podlib.pod_uid(p):
                    # drop the stash only if it still holds the same
                    # uid we judged (a recreate's newer stash stays)
                    self._seen.pop(k, None)
        for _, pod in stale:
            self.cache.remove_pod(pod)  # missed DELETED / replaced UID
        # nodes re-list too: the first watch connects from "now", so a
        # node update committed between build_cache's LIST and the watch
        # connecting is in a gap only anti-entropy can heal (observed:
        # slice relabels invisible forever without this — capacity
        # changes eventually repeat via the device plugin's periodic
        # report, but labels don't)
        try:
            nodes = self._cluster.list_nodes()
            live_names = set()
            for node in nodes:
                live_names.add(nodelib.node_name(node))
                if contract.is_tpushare_node(node):
                    self.cache.update_node(node)
            # and the reverse gap: a node DELETED while the watch was
            # down would otherwise haunt the cache forever (ghost hosts
            # keep receiving gang plans and unhealthy-probe traffic)
            for name in self.cache.node_names():
                if name not in live_names:
                    self.cache.remove_node(name)
        except ApiError as e:
            log.warning("controller: node resync failed: %s", e)
        for name in self.cache.node_names():
            self._load_unhealthy(name)
        for hook in list(self.resync_hooks):
            try:
                hook()
            except Exception as e:  # noqa: BLE001 — anti-entropy must
                log.warning("resync hook failed: %s", e)  # never die

    def _load_unhealthy(self, node_name: str) -> None:
        try:
            cm = self._cluster.get_configmap(
                UNHEALTHY_CM_NAMESPACE, UNHEALTHY_CM_PREFIX + node_name)
            chips = parse_unhealthy(cm.get("data"))
        except ApiError as e:
            if not e.is_not_found:
                return  # transient failure: keep the current set
            chips = set()  # configmap gone = all chips healthy again
        try:
            self.cache.get_node_info(node_name).set_unhealthy(chips)
        except ApiError:
            pass  # node disappeared meanwhile

    # -- workers --------------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            key = self._queue.get()
            if key is None:
                return
            try:
                self._sync_pod(key)
            except Exception as e:  # noqa: BLE001 — worker must survive
                if self._queue.retry(key):
                    log.warning("controller: sync %s failed, will retry: %s",
                                key, e)
                else:
                    log.error("controller: dropping %s after max retries: %s",
                              key, e)
            else:
                self._queue.forget(key)
            finally:
                self._queue.done(key)

    def _sync_pod(self, key: str) -> None:
        """Reference syncPod (controller.go:185-216)."""
        ns, _, name = key.partition("/")
        try:
            pod = self._cluster.get_pod(ns, name)
        except ApiError as e:
            if not e.is_not_found:
                raise
            with self._seen_lock:
                stashed = self._seen.pop(key, None)
            if stashed is not None:
                self.cache.remove_pod(stashed)
            return
        if contract.is_complete_pod(pod):
            self.cache.remove_pod(pod)
        elif podlib.pod_node_name(pod) and \
                contract.chip_ids_from_annotations(pod) is not None:
            self.cache.add_or_update_pod(pod)
        elif self.cache.known_pod(podlib.pod_cache_key(pod)) and \
                contract.chip_ids_from_annotations(pod) is None:
            # placement annotations were cleared (stale-placement reclaim):
            # free the chips without waiting for pod termination
            self.cache.remove_pod(pod)

    # -- test hooks -----------------------------------------------------------

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait until the queue is empty and no key is processing (tests)."""
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._queue._lock:
                idle = (not self._queue._queue and not self._queue._delayed
                        and not self._queue._processing)
            if idle:
                return True
            time.sleep(0.01)
        return False
