"""Sync layer: keeps the SchedulerCache consistent with the apiserver."""

from tpushare.controller.controller import Controller
from tpushare.controller.recovery import reconcile_once
from tpushare.controller.workqueue import WorkQueue

__all__ = ["Controller", "WorkQueue", "reconcile_once"]
