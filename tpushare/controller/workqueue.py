"""Deduplicating retry workqueue.

The reference uses client-go's rate-limited workqueue
(/root/reference/pkg/gpushare/controller.go:95-99): keys are deduplicated
while queued, failed items are re-added with backoff, and a max-retry cap
drops poison keys. This is a dependency-free equivalent with the same
contract (add / get / done / forget / retry accounting).
"""

from __future__ import annotations

import heapq
import threading
import time


class WorkQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 1.0,
                 max_retries: int = 15) -> None:
        self._lock = threading.Condition()
        self._queue: list[str] = []
        self._queued: set[str] = set()
        self._processing: set[str] = set()
        self._dirty: set[str] = set()       # re-added while processing
        self._retries: dict[str, int] = {}
        self._delayed: list[tuple[float, str]] = []  # heap of (when, key)
        self._shutdown = False
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.max_retries = max_retries

    def add(self, key: str) -> None:
        with self._lock:
            if self._shutdown:
                return
            if key in self._processing:
                self._dirty.add(key)  # reprocess after current run finishes
                return
            if key not in self._queued:
                self._queued.add(key)
                self._queue.append(key)
                self._lock.notify()

    def add_after(self, key: str, delay: float) -> None:
        with self._lock:
            if self._shutdown:
                return
            heapq.heappush(self._delayed, (time.monotonic() + delay, key))
            self._lock.notify()

    def get(self, timeout: float | None = None) -> str | None:
        """Blocking pop; returns None on shutdown or timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                now = time.monotonic()
                while self._delayed and self._delayed[0][0] <= now:
                    _, key = heapq.heappop(self._delayed)
                    if key not in self._queued and key not in self._processing:
                        self._queued.add(key)
                        self._queue.append(key)
                if self._queue:
                    key = self._queue.pop(0)
                    self._queued.discard(key)
                    self._processing.add(key)
                    return key
                if self._shutdown:
                    return None
                wait = None
                if self._delayed:
                    wait = max(self._delayed[0][0] - now, 0.001)
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._lock.wait(wait)

    def done(self, key: str) -> None:
        with self._lock:
            self._processing.discard(key)
            if key in self._dirty:
                self._dirty.discard(key)
                if key not in self._queued:
                    self._queued.add(key)
                    self._queue.append(key)
                    self._lock.notify()

    def forget(self, key: str) -> None:
        with self._lock:
            self._retries.pop(key, None)

    def retry(self, key: str) -> bool:
        """Schedule a failed key for retry with exponential backoff.
        Returns False (and forgets the key) once max_retries is exhausted."""
        with self._lock:
            n = self._retries.get(key, 0) + 1
            if n > self.max_retries:
                self._retries.pop(key, None)
                return False
            self._retries[key] = n
        self.add_after(key, min(self.base_delay * (2 ** (n - 1)),
                                self.max_delay))
        return True

    def shut_down(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._delayed)
