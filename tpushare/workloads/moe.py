"""Mixture-of-experts FFN with expert parallelism — GShard/Switch style.

The reference framework has no model code at all (SURVEY.md §2: parallelism
components ABSENT — /root/reference contains only the Go scheduler); this
module is part of tpushare's workload family, the JAX programs that the
scheduler's samples/ suite places onto shared TPU chips. It exists so the
framework's "ep" (expert-parallel) sharding axis is a real, exercised code
path rather than a label.

TPU-first design choices:

- **Static-shape capacity routing** (top-k with per-expert capacity C):
  every tensor shape is known at trace time, so the whole layer jits into
  one XLA program — no ragged dispatch, no host round-trips. Tokens over
  capacity are *dropped* (contribute zero; the transformer's residual path
  carries them), the standard Switch/GShard behavior.
- **Dispatch/combine as einsums**: routing becomes two big matmuls
  ([T,E,C] one-hot against [T,d] activations), which is exactly what the
  MXU wants, and which XLA turns into an ``all_to_all`` over the "ep" mesh
  axis when the expert axis is sharded — ICI does the token shuffle.
- **Per-expert SwiGLU** evaluated as batched einsums over the expert axis
  ([E,C,d] x [E,d,f]); with ``w1/w3/w2`` sharded ``P("ep", ...)`` each
  device computes only its local experts.
- **fp32 router** (softmax + cumsum bookkeeping), bf16 expert compute.

The pure-Python/dense reference (`moe_ffn_reference`) loops over experts and
is the behavioral spec for the packed implementation; parity is covered by
tests/test_moe.py.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int            # per-expert hidden width
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: object = jnp.bfloat16

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token slots for a batch of ``n_tokens`` (static)."""
        cap = math.ceil(self.top_k * n_tokens / self.n_experts
                        * self.capacity_factor)
        return max(cap, 1)


def init_moe_params(cfg: MoEConfig, key: jax.Array) -> dict:
    """Router + stacked expert weights (leading axis = expert)."""
    kg, k1, k3, k2 = jax.random.split(key, 4)
    E, d, f = cfg.n_experts, cfg.d_model, cfg.d_ff

    def w(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    return {
        # router stays fp32: tiny, and argmax stability matters more than HBM
        "wg": jax.random.normal(kg, (d, E), jnp.float32) * (d ** -0.5),
        "w1": w(k1, E, d, f, fan_in=d),
        "w3": w(k3, E, d, f, fan_in=d),
        "w2": w(k2, E, f, d, fan_in=f),
    }


def moe_param_specs() -> dict:
    """PartitionSpec tree: experts shard over the "ep" mesh axis."""
    return {
        "wg": P(None, None),
        "w1": P("ep", None, None),
        "w3": P("ep", None, None),
        "w2": P("ep", None, None),
    }


def _topk_gates(probs: jax.Array, top_k: int):
    """Shared top-k selection: probs [T, E] -> (masks, gates), each a
    length-``top_k`` list of [T, E] one-hots / [T] normalized gate values.
    Single source of truth for the routing contract (tie-break = argmax
    order, gates renormalized to sum to 1 over the kept experts)."""
    E = probs.shape[-1]
    masks, gates = [], []
    p = probs
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)                       # [T]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [T, E]
        gates.append(jnp.sum(probs * onehot, axis=-1))
        masks.append(onehot)
        p = p * (1.0 - onehot)
    denom = sum(gates)
    gates = [g / jnp.maximum(denom, 1e-9) for g in gates]
    return masks, gates


def _route(logits: jax.Array, top_k: int, capacity: int):
    """fp32 top-k capacity routing.

    logits [T, E] -> (dispatch [T, E, C] 0/1, combine [T, E, C] gates,
    aux load-balance loss). Priority: lower k first, then token order —
    deterministic and independent of expert sharding.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    masks, gates = _topk_gates(probs, top_k)

    # Switch-style aux loss on the k=0 assignment: E * sum_e f_e * P_e,
    # minimized (=1) at a uniform expert load.
    f_e = jnp.mean(masks[0], axis=0)        # fraction routed to e
    p_e = jnp.mean(probs, axis=0)           # mean router prob for e
    aux = E * jnp.sum(f_e * p_e)

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    prior = jnp.zeros((E,), jnp.float32)    # slots already taken per expert
    for mask, gate in zip(masks, gates):
        pos = jnp.cumsum(mask, axis=0) - mask + prior       # [T, E]
        prior = prior + jnp.sum(mask, axis=0)
        pos_tok = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)  # [T]
        keep = (pos_tok < capacity).astype(jnp.float32)
        slot = jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)  # [T, C]
        d_k = mask[:, :, None] * slot[:, None, :] * keep[:, None, None]
        dispatch = dispatch + d_k
        combine = combine + gate[:, None, None] * d_k
    return dispatch, combine, aux


def moe_ffn(params: dict, x: jax.Array, cfg: MoEConfig):
    """x [..., d_model] -> (y [..., d_model], aux_loss scalar).

    Dropped tokens produce y == 0 for that token (callers add the residual).
    Under pjit with ``moe_param_specs`` and tokens sharded over "dp"/"ep",
    the two dispatch einsums lower to ICI all_to_all collectives.
    """
    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    T = xt.shape[0]
    C = cfg.capacity(T)

    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32), params["wg"])
    dispatch, combine, aux = _route(logits, cfg.top_k, C)

    # token shuffle in: [T,E,C] x [T,d] -> [E,C,d]
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
    # per-expert SwiGLU, batched over the (sharded) expert axis
    h = (jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"]))
         * jnp.einsum("ecd,edf->ecf", expert_in, params["w3"]))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"])
    # token shuffle out, gate-weighted
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return y.reshape(*lead, d), aux


def moe_ffn_reference(params: dict, x: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Dense behavioral spec: every expert computed for every token, output =
    gate-weighted sum over the token's top-k experts, no capacity drops.
    Matches :func:`moe_ffn` exactly when ``capacity_factor`` is large enough
    that nothing drops."""
    lead = x.shape[:-1]
    xt = x.reshape(-1, x.shape[-1])
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32), params["wg"])
    probs = jax.nn.softmax(logits, axis=-1)
    masks, gates = _topk_gates(probs, cfg.top_k)

    # all experts on all tokens: [E, T, d]
    h = (jax.nn.silu(jnp.einsum("td,edf->etf", xt, params["w1"]))
         * jnp.einsum("td,edf->etf", xt, params["w3"]))
    all_out = jnp.einsum("etf,efd->etd", h, params["w2"])

    y = jnp.zeros_like(xt)
    for mask, gate in zip(masks, gates):
        w = (mask * gate[:, None]).astype(x.dtype)          # [T, E]
        y = y + jnp.einsum("te,etd->td", w, all_out)
    return y.reshape(*lead, x.shape[-1])


def expert_load(params: dict, x: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Tokens routed to each expert at k=0 (observability helper)."""
    xt = x.reshape(-1, x.shape[-1])
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32), params["wg"])
    idx = jnp.argmax(logits, axis=-1)
    return jnp.sum(jax.nn.one_hot(idx, cfg.n_experts, dtype=jnp.int32), axis=0)
