"""Make the device plugin's HBM grant effective inside a JAX process.

The scheduler guarantees non-oversubscription at placement time; runtime
enforcement is delegated to XLA's allocator (the same division of labor as
the reference: scheduling-level guarantee, runtime isolation out of scope —
designs.md "Non Goals", with the TF fraction knob as the practical fence,
userguide.md:67-77).

Call :func:`apply_hbm_gating` BEFORE the first ``import jax``:

    from tpushare.workloads.hbm import apply_hbm_gating
    apply_hbm_gating()
    import jax
"""

from __future__ import annotations

import logging
import os

from tpushare.contract.constants import (
    ENV_HBM_CHIP_TOTAL,
    ENV_HBM_LIMIT,
    ENV_MEM_FRACTION,
    ENV_VISIBLE_CHIPS,
)

log = logging.getLogger("tpushare.workloads.hbm")


def apply_hbm_gating(environ: dict[str, str] | None = None) -> dict[str, str]:
    """Derive XLA memory settings from the tpushare grant env.

    - ``XLA_PYTHON_CLIENT_MEM_FRACTION`` <- grant/chip-total (if the device
      plugin didn't already inject it),
    - disables preallocation for fractional grants so co-tenants don't race
      to grab the whole fraction at import time,
    - maps ``TPU_VISIBLE_CHIPS`` to libtpu's visible-devices setting.

    Returns the settings applied (for logging/tests). Mutates os.environ
    (or the supplied dict) only where the operator hasn't set values.
    """
    env = os.environ if environ is None else environ
    applied: dict[str, str] = {}

    limit = _to_int(env.get(ENV_HBM_LIMIT))
    total = _to_int(env.get(ENV_HBM_CHIP_TOTAL))
    if limit and total and 0 < limit < total:
        if ENV_MEM_FRACTION not in env:
            applied[ENV_MEM_FRACTION] = f"{limit / total:.4f}"
        # fractional tenants must not preallocate the whole fraction up
        # front: leave headroom allocation to demand
        applied.setdefault("XLA_PYTHON_CLIENT_PREALLOCATE", "false")

    chips = env.get(ENV_VISIBLE_CHIPS)
    if chips and "TPU_PROCESS_BOUNDS" not in env:
        # libtpu honors TPU_VISIBLE_CHIPS directly (the device plugin
        # injects it); a fractional tenant is a single-process job, so pin
        # the process bounds accordingly unless the operator set their own
        applied["TPU_PROCESS_BOUNDS"] = "1,1,1"

    for k, v in applied.items():
        env.setdefault(k, v)
    if applied:
        log.info("hbm gating applied: %s", applied)
    return applied


def _to_int(raw: str | None) -> int:
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0
