"""All-to-all (Ulysses-style) sequence parallelism for attention.

The second of tpushare's two sequence-parallel schemes (the first is
:mod:`tpushare.workloads.ringattention`). Where ring attention keeps heads
whole and rotates K/V chunks around the "sp" ring (n-1 ppermute hops,
O(S/n) residency), the all-to-all scheme re-shards in one collective:

    [B, H, S/n, D]  --all_to_all-->  [B, H/n, S, D]

each device then runs ordinary full-sequence attention over its head
subset, and a second all_to_all restores the sequence sharding. Two ICI
collectives total, no per-step pipeline — the better trade when heads are
plentiful and sequence chunks are small enough that overlapping the ring
doesn't pay; ring wins when S/n is large or H < n (the scheme requires
``H % n == 0``).

TPU notes: ``lax.all_to_all(tiled=True)`` lowers to a single ICI
all-to-all; attention inside runs on the unsharded sequence, so the
flash/pallas kernel applies unchanged per head subset.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _ulysses_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, causal: bool, attn: str,
                   interpret: bool, window: int | None) -> jax.Array:
    """Per-shard body under shard_map: q is local [B, H, S/n, D], k/v
    are [B, H_kv, S/n, D] (GQA-native — the kv all_to_all moves 1/G of
    the expanded bytes, and head-block alignment works out exactly:
    device d's query-head block [d*H/n, (d+1)*H/n) needs kv heads
    [d*Hkv/n, (d+1)*Hkv/n), which is precisely the block its kv
    all_to_all delivers, because (H/n)/G == Hkv/n)."""
    # heads scatter, sequence gathers: [B, H, S/n, D] -> [B, H/n, S, D]
    def seq_to_head(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)

    if attn == "flash":
        # the sequence is FULL per device after the all_to_all, so the
        # fused Pallas kernel applies unchanged to the local head subset
        # (GQA streamed natively) — O(block) residency instead of this
        # path's [S, S] fp32 score matrix (Mosaic on TPU, interpret
        # elsewhere)
        from tpushare.workloads.attention import flash_attention
        o = flash_attention(qh, kh, vh, causal=causal,
                            interpret=interpret, window=window)
    else:
        # the einsum spec path IS attention_reference (per-device plain
        # arrays under shard_map) — no re-implementation to drift from,
        # and its causal/window validation comes along for free. The
        # reference wants equal heads, so GQA expands LOCALLY (the wire
        # already moved only the small heads)
        from tpushare.workloads.attention import attention_reference
        g = qh.shape[1] // kh.shape[1]
        if g > 1:
            kh, vh = jnp.repeat(kh, g, 1), jnp.repeat(vh, g, 1)
        o = attention_reference(qh, kh, vh, causal=causal,
                                window=window).astype(q.dtype)

    # restore sequence sharding: [B, H/n, S, D] -> [B, H, S/n, D]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      mesh: jax.sharding.Mesh, axis: str = "sp",
                      causal: bool = True,
                      attn: str = "einsum",
                      window: int | None = None) -> jax.Array:
    """Exact attention over [B, H, S, D] with the sequence sharded on
    ``axis`` via head/sequence all_to_all re-sharding. Requires ``S``,
    ``H``, and ``H_kv`` divisible by the axis size. GQA-NATIVE: pass the
    SMALL kv heads — their all_to_all moves 1/G of the pre-expanded
    bytes, and device d's query-head block aligns exactly with the kv
    block its all_to_all delivers. Jit-compatible; composes with
    outer dp/tp shardings.

    ``attn="flash"`` runs the fused Pallas kernel on each device's full-
    sequence head subset (O(block) residency; the TPU serving path) —
    the einsum default keeps CPU test meshes fast and is the numerics
    spec.
    """
    if attn not in ("einsum", "flash"):
        raise ValueError(f"attn must be 'einsum' or 'flash', got {attn!r}")
    if window is not None:
        # fail HERE with a usable message, not with NaNs from an all
        # -masked softmax row inside shard_map
        if not causal:
            raise ValueError("window attention requires causal=True")
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
    # Mosaic vs interpret must follow the MESH's platform, not the process
    # default backend: a CPU test mesh in a process whose default backend
    # is TPU (entry() ran on the chip first) would otherwise try to lower
    # the Mosaic kernel for CPU devices inside shard_map
    interpret = mesh.devices.flat[0].platform != "tpu"
    B, H, S, D = q.shape
    n = mesh.shape[axis]
    if S % n:
        raise ValueError(f"seq len {S} not divisible by {axis} size {n}")
    if H % n:
        raise ValueError(
            f"{H} heads not divisible by {axis} size {n}; use ring "
            "attention when heads are scarcer than shards")
    from tpushare.workloads.attention import validate_gqa_qkv
    Hkv = validate_gqa_qkv(q, k, v)
    if k.shape[2] != S:
        raise ValueError(
            f"ulysses attention needs equal q/kv lengths, got {S} vs "
            f"{k.shape[2]}")
    if Hkv % n:
        raise ValueError(
            f"{Hkv} kv heads not divisible by {axis} size {n}; expand "
            "K/V heads first (or use ring attention) when kv heads are "
            "scarcer than shards")
    spec = P(None, None, axis, None)
    fn = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=axis, causal=causal,
                          attn=attn, interpret=interpret, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
