"""JAX workloads that run under tpushare HBM grants.

The reference ships sample "gpu-player" workloads that echo their injected
device env (samples/docker/run.sh) and TF fraction guidance for respecting
the memory grant (userguide.md:67-77). The tpushare equivalents are real
JAX programs:

- :mod:`tpushare.workloads.hbm` — turns the device plugin's injected env
  (``TPU_VISIBLE_CHIPS``, ``TPUSHARE_HBM_LIMIT_MIB``) into effective XLA
  settings. Import and call ``apply_hbm_gating()`` BEFORE importing jax.
- :mod:`tpushare.workloads.model` — a llama-style decoder (bf16 + optional
  int8 weight quantization) with dp/tp mesh shardings, sized by presets.
- :mod:`tpushare.workloads.player` — binpack-demo tenant (samples/1-4).
- :mod:`tpushare.workloads.serve` — the BASELINE config #5 co-located
  int8 serving replica.
"""
