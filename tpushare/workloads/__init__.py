"""JAX workloads that run under tpushare HBM grants.

The reference ships sample "gpu-player" workloads that echo their injected
device env (samples/docker/run.sh) and TF fraction guidance for respecting
the memory grant (userguide.md:67-77). The tpushare equivalents are real
JAX programs:

- :mod:`tpushare.workloads.hbm` — turns the device plugin's injected env
  (``TPU_VISIBLE_CHIPS``, ``TPUSHARE_HBM_LIMIT_MIB``) into effective XLA
  settings. Import and call ``apply_hbm_gating()`` BEFORE importing jax.
- :mod:`tpushare.workloads.model` — a llama-style decoder (bf16 + optional
  int8 weight quantization) with dp/tp mesh shardings, sized by presets.
- :mod:`tpushare.workloads.player` — binpack-demo tenant (samples/1-4).
- :mod:`tpushare.workloads.serve` — the BASELINE config #5 co-located
  int8 serving replica.
"""


def honor_cpu_request() -> None:
    """Flip jax's platform config to CPU when the ENV explicitly asks
    for it (JAX_PLATFORMS=cpu) but a site hook pinned the config to a
    hardware platform before user code ran. One definition for every
    entry point (graft entry, multichip dryrun, tpushare-serve): a
    wedged TPU tunnel otherwise hangs backend init for runs that never
    wanted the chip. No-op when the env makes no explicit CPU request,
    so hardware-targeted runs are unaffected."""
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu" \
            and jax.config.jax_platforms != "cpu":
        jax.config.update("jax_platforms", "cpu")
