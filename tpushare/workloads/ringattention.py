"""Ring attention: sequence-parallel exact attention over an ICI ring.

Long-context serving on a shared slice needs attention over sequences whose
K/V don't fit one chip's HBM grant. Ring attention shards the sequence over
an ``sp`` mesh axis — each chip holds a contiguous [B, H, S/n, D] chunk of
q, k, v — and rotates the K/V chunks around the ring with
``lax.ppermute`` while folding each visiting chunk into a flash-style
online-softmax accumulator. Per-chip residency is O(S/n); the collective
pattern is n-1 neighbor-to-neighbor hops that XLA maps onto ICI (no
all-gather of the full sequence ever exists).

The reference framework (mengwanguc/gpushare-scheduler-extender) has no
model/attention code — SURVEY.md §5.7 marks sequence parallelism ABSENT —
so this module is part of the TPU build's workload family (the programs the
scheduler places), exercised by the driver's multi-chip dry run.

Numerics contract: matches :func:`tpushare.workloads.attention.
attention_reference` on the gathered sequence to bf16 tolerance. The
online-softmax recurrence is the same one the Pallas kernel uses, so the
two compose: intra-chip attention could itself run the fused kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _chunk_positions(rank, per: int, n: int, zigzag: bool):
    """Global sequence positions of the rows rank ``rank`` holds.

    Contiguous: rows [rank*per, (rank+1)*per). Zigzag: the sequence is
    cut into 2n half-chunks and rank r holds halves r and 2n-1-r — so
    every rank owns one early and one late stretch of the sequence and
    causal work balances (contiguous sharding makes rank n-1 fold n
    chunks of visible keys while rank 0 folds one: the slowest rank sets
    the SPMD critical path).
    """
    if not zigzag:
        return rank * per + jnp.arange(per)
    h = per // 2
    return jnp.concatenate([rank * h + jnp.arange(h),
                            (2 * n - 1 - rank) * h + jnp.arange(h)])


def _ring_body(carry, step, *, axis_name: str, n: int, my: jax.Array,
               qs: jax.Array, q_pos: jax.Array, causal: bool,
               zigzag: bool):
    """Fold the currently-held K/V chunk into the online-softmax state,
    then pass the chunk to the next rank (skip the send on the last step).

    The fold keeps inputs in their storage dtype through the MXU
    (fp32 accumulation via preferred_element_type — pre-casting to fp32
    halves MXU throughput, the same lesson as the Pallas kernel), and a
    causally fully-masked chunk skips the fold entirely instead of
    computing an all--inf score block.
    """
    m, l, acc, kb, vb = carry
    sk = kb.shape[2]
    src = (my - step) % n                     # rank this chunk started at
    k_pos = _chunk_positions(src, sk, n, zigzag)   # global key positions

    def fold(operand, masked: bool):
        m, l, acc = operand
        # GQA-native: qs is [B, Hkv, G, Sq, D] while the ring-resident
        # kb/vb stay [B, Hkv, Sk, D] — each kv head's chunk serves its
        # whole query group, so ppermute moves 1/G of the pre-expanded
        # bytes per hop (the entire ICI win of GQA at the ring level)
        s = jax.lax.dot_general(
            qs, kb, (((4,), (3,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)     # [B, Hkv, G, Sq, Sk]
        if masked:
            mask = k_pos[None, :] <= q_pos[:, None]    # [Sq, Sk]
            s = jnp.where(mask[None, None, None], s, -jnp.inf)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # rows with no visible key yet carry m = -inf; clamp the shift so
        # exp(-inf - -inf) never produces NaN
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        # no p re-mask: masked scores are -inf and exp(-inf - shift) is
        # exactly 0 for the clamped-finite shift (the same redundant
        # [Sq, Sk] VPU pass the Pallas kernel dropped in r3)
        p = jnp.exp(s - shift)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((4,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # three mask classes per chunk: fully masked (skip everything),
        # fully visible (contiguous layout: every src < my chunk — skip
        # the mask build and both where passes over [Sq, Sk], mirroring
        # the Pallas kernel's unmasked fast path), diagonal (masked fold)
        any_visible = jnp.min(k_pos) <= jnp.max(q_pos)
        fully_visible = jnp.max(k_pos) <= jnp.min(q_pos)
        branch = jnp.where(any_visible,
                           jnp.where(fully_visible, 2, 1), 0)
        m, l, acc = lax.switch(
            branch,
            [lambda op: op,
             functools.partial(fold, masked=True),
             functools.partial(fold, masked=False)],
            (m, l, acc))
    else:
        m, l, acc = fold((m, l, acc), masked=False)

    def rotate(kv):
        perm = [(i, (i + 1) % n) for i in range(n)]
        return (lax.ppermute(kv[0], axis_name, perm),
                lax.ppermute(kv[1], axis_name, perm))

    kb, vb = lax.cond(step < n - 1, rotate, lambda kv: kv, (kb, vb))
    return (m, l, acc, kb, vb), None


def _ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          axis_name: str, causal: bool,
                          zigzag: bool) -> jax.Array:
    """Per-shard body (runs under shard_map): q is the local
    [B, H, S/n, D] chunk, k/v are [B, H_kv, S/n, D] (H_kv dividing H —
    GQA-native, never expanded), in ring order (contiguous or zigzag)."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, sq, d = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    # scale folded into q off the [Sq, Sk] score path, storage dtype
    # kept; grouped view so kv heads batch against their query groups
    qs = (q.astype(jnp.float32) * (d ** -0.5)).astype(q.dtype)
    qs = qs.reshape(B, Hkv, G, sq, d)
    q_pos = _chunk_positions(my, sq, n, zigzag)

    m = jnp.full((B, Hkv, G, sq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, Hkv, G, sq, 1), jnp.float32)
    acc = jnp.zeros((B, Hkv, G, sq, d), jnp.float32)

    body = functools.partial(_ring_body, axis_name=axis_name, n=n, my=my,
                             qs=qs, q_pos=q_pos, causal=causal,
                             zigzag=zigzag)
    (m, l, acc, _, _), _ = lax.scan(body, (m, l, acc, k, v),
                                    jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(B, H, sq, d).astype(q.dtype)


def zigzag_order(S: int, n: int):
    """Index permutation taking a [.., S, ..] sequence from natural order
    to zigzag ring order: the sequence is cut into 2n half-chunks and
    rank r's shard becomes halves (r, 2n-1-r). Apply along the sequence
    axis BEFORE sharding with ``zigzag=True``; invert with
    :func:`zigzag_inverse`."""
    if S % (2 * n):
        raise ValueError(f"seq len {S} not divisible by 2*{n}")
    h = S // (2 * n)
    idx = []
    for r in range(n):
        idx.extend(range(r * h, (r + 1) * h))
        idx.extend(range((2 * n - 1 - r) * h, (2 * n - r) * h))
    return jnp.asarray(idx)


def zigzag_inverse(S: int, n: int):
    """Inverse permutation of :func:`zigzag_order`."""
    fwd = zigzag_order(S, n)
    inv = jnp.zeros(S, jnp.int32).at[fwd].set(jnp.arange(S, dtype=jnp.int32))
    return inv


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: jax.sharding.Mesh, axis: str = "sp",
                   causal: bool = True, zigzag: bool = False) -> jax.Array:
    """Exact attention over [B, H, S, D] with the sequence sharded on
    ``axis``. S must divide evenly by the axis size. Jit-compatible; under
    jit the shard_map composes with outer dp/tp shardings.

    ``zigzag=True`` expects the sequence axis pre-permuted with
    :func:`zigzag_order` (output comes back in the same permuted order):
    every rank then owns one early and one late stretch, so causal work
    is balanced across the ring instead of rank n-1 folding n visible
    chunks while rank 0 folds one (the llama3-style layout; the SPMD
    critical path is the slowest rank).
    """
    B, H, S, D = q.shape
    n = mesh.shape[axis]
    if S % n:
        raise ValueError(f"seq len {S} not divisible by {axis} size {n}")
    if zigzag and (S // n) % 2:
        raise ValueError(
            f"zigzag needs an even per-rank chunk (S/n = {S // n})")
    from tpushare.workloads.attention import validate_gqa_qkv
    validate_gqa_qkv(q, k, v, extra="the ring moves 1/G of the bytes "
                                    "per hop with the small kv heads")
    if k.shape[2] != S:
        raise ValueError(
            f"ring attention needs equal q/kv lengths, got {S} vs "
            f"{k.shape[2]}")
    spec = P(None, None, axis, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, axis_name=axis,
                          causal=causal, zigzag=zigzag),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
