"""Ring attention: sequence-parallel exact attention over an ICI ring.

Long-context serving on a shared slice needs attention over sequences whose
K/V don't fit one chip's HBM grant. Ring attention shards the sequence over
an ``sp`` mesh axis — each chip holds a contiguous [B, H, S/n, D] chunk of
q, k, v — and rotates the K/V chunks around the ring with
``lax.ppermute`` while folding each visiting chunk into a flash-style
online-softmax accumulator. Per-chip residency is O(S/n); the collective
pattern is n-1 neighbor-to-neighbor hops that XLA maps onto ICI (no
all-gather of the full sequence ever exists).

The reference framework (mengwanguc/gpushare-scheduler-extender) has no
model/attention code — SURVEY.md §5.7 marks sequence parallelism ABSENT —
so this module is part of the TPU build's workload family (the programs the
scheduler places), exercised by the driver's multi-chip dry run.

Numerics contract: matches :func:`tpushare.workloads.attention.
attention_reference` on the gathered sequence to bf16 tolerance. The
online-softmax recurrence is the same one the Pallas kernel uses, so the
two compose: intra-chip attention could itself run the fused kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _ring_body(carry, step, *, axis_name: str, n: int, my: jax.Array,
               q32: jax.Array, q_pos: jax.Array, causal: bool):
    """Fold the currently-held K/V chunk into the online-softmax state,
    then pass the chunk to the next rank (skip the send on the last step)."""
    m, l, acc, kb, vb = carry
    sk = kb.shape[2]
    src = (my - step) % n                     # rank this chunk started at
    k_pos = src * sk + jnp.arange(sk)         # global key positions

    s = jnp.einsum("bhqd,bhkd->bhqk", q32, kb.astype(jnp.float32))
    if causal:
        mask = k_pos[None, :] <= q_pos[:, None]        # [Sq, Sk]
        s = jnp.where(mask[None, None], s, -jnp.inf)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # rows with no visible key yet carry m = -inf; clamp the shift so
    # exp(-inf - -inf) never produces NaN
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - shift)
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                   vb.astype(jnp.float32))

    def rotate(kv):
        perm = [(i, (i + 1) % n) for i in range(n)]
        return (lax.ppermute(kv[0], axis_name, perm),
                lax.ppermute(kv[1], axis_name, perm))

    kb, vb = lax.cond(step < n - 1, rotate, lambda kv: kv, (kb, vb))
    return (m_new, l, acc, kb, vb), None


def _ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          axis_name: str, causal: bool) -> jax.Array:
    """Per-shard body (runs under shard_map): q, k, v are the local
    [B, H, S/n, D] chunks, contiguous in ring order."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, sq, d = q.shape
    q32 = q.astype(jnp.float32) * (d ** -0.5)
    q_pos = my * sq + jnp.arange(sq)

    m = jnp.full((B, H, sq, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, sq, 1), jnp.float32)
    acc = jnp.zeros((B, H, sq, d), jnp.float32)

    body = functools.partial(_ring_body, axis_name=axis_name, n=n, my=my,
                             q32=q32, q_pos=q_pos, causal=causal)
    (m, l, acc, _, _), _ = lax.scan(body, (m, l, acc, k, v),
                                    jnp.arange(n))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: jax.sharding.Mesh, axis: str = "sp",
                   causal: bool = True) -> jax.Array:
    """Exact attention over [B, H, S, D] with the sequence sharded on
    ``axis``. S must divide evenly by the axis size. Jit-compatible; under
    jit the shard_map composes with outer dp/tp shardings.
    """
    B, H, S, D = q.shape
    n = mesh.shape[axis]
    if S % n:
        raise ValueError(f"seq len {S} not divisible by {axis} size {n}")
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"q {q.shape} / k {k.shape} / v {v.shape} must match "
            "(GQA heads pre-expanded; causal ring needs equal q/kv lengths)")
    spec = P(None, None, axis, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, axis_name=axis,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
