"""Pipeline parallelism — GPipe schedule over a "pp" mesh axis.

The reference framework has no model-parallel code (SURVEY.md §2: DP/TP/PP
ABSENT); this module gives tpushare's workload family a real "pp" sharding
axis: the llama layer stack is split into ``pp`` contiguous stages (layer
axis sharded over the mesh), microbatches stream through the stages, and
stage-to-stage activation handoff is a ``ppermute`` hop between ICI
neighbors.

TPU-first design:

- **shard_map + lax.scan schedule**: the whole pipeline — M microbatches
  through P stages in M+P-1 ticks — is one compiled XLA program. Every
  device runs the identical scan body (SPMD); "which stage am I" is
  ``lax.axis_index``, and bubble ticks compute on don't-care data that the
  output masking discards (predication instead of control flow, which is
  what the compiler wants).
- **ppermute activation handoff**: stage i sends its activation to stage
  i+1 along the ring each tick; on a TPU slice the pp axis lays out on ICI
  neighbors so each hop is one link. ``ppermute`` is differentiable (its
  transpose is the reversed permutation), so ``jax.grad`` through the
  pipeline yields the standard GPipe backward schedule for free.
- **embed/unembed outside the pipelined stack**: token embedding and the
  lm_head run replicated outside shard_map, keeping the stage body a pure
  [mb, S, d] -> [mb, S, d] layer stack (and composable with tp sharding of
  those matmuls).

Scaling note: this implementation keeps microbatch inputs and the output
buffer replicated across stages — right for validating schedules and
for the driver's virtual-mesh dry run; a production variant would keep
activations stage-local. Parity with the sequential model is exact
(same layer body: model.decoder_layer) and covered by tests/test_pipeline.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpushare.workloads.model import (
    ModelConfig, _matmul, _rmsnorm, decoder_layer)


def stage_layer_specs(params: dict) -> dict:
    """in_specs pytree for ``params["layers"]``: layer axis over "pp"."""
    return jax.tree.map(lambda _: P("pp"), params["layers"])


def pipelined_forward_with_aux(params: dict, tokens: jax.Array,
                               cfg: ModelConfig, mesh: jax.sharding.Mesh,
                               microbatches: int | None = None,
                               axis: str = "pp"):
    """tokens [B, S] -> (logits [B, S, vocab], aux) via a GPipe pipeline.

    ``cfg.n_layers`` must divide evenly into ``mesh.shape[axis]`` stages and
    the batch into ``microbatches`` (default: one per stage). The stages
    run the same ``decoder_layer`` body in the same order as
    :func:`tpushare.workloads.model.forward_with_aux`, so dense logits are
    numerically identical. MoE caveat: routing operates per forward call,
    so microbatching changes the token population an expert sees — logits
    match only while routing is dropless (capacity never binds per
    microbatch; the shipped presets guarantee this), and the aux
    load-balance term is a mean of per-microbatch values, which is close
    to but not equal to the full-batch aux.
    """
    n_stages = mesh.shape[axis]
    L = cfg.n_layers
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")
    B, S = tokens.shape
    M = microbatches or n_stages
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    mb = B // M

    x = jnp.take(params["embed"], tokens, axis=0)        # [B, S, d]
    xmb = x.reshape(M, mb, S, x.shape[-1])
    positions = jnp.broadcast_to(jnp.arange(S), (mb, S))

    def stage_apply(local_layers, x):
        """Run this stage's contiguous slice of the layer stack."""
        def body(x, lp):
            return decoder_layer(x, lp, positions, cfg)
        x, auxs = lax.scan(body, x, local_layers)
        return x, jnp.mean(auxs)

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(stage_layer_specs(params), P()),
        out_specs=(P(), P()), check_vma=False)
    def run(local_layers, xmb):
        stage = lax.axis_index(axis)
        last = n_stages - 1
        state = jnp.zeros_like(xmb[0])
        outbuf = jnp.zeros_like(xmb)
        ticks = M + n_stages - 1

        def tick(carry, t):
            state, outbuf = carry
            # stage i hands last tick's activation to stage i+1
            recv = lax.ppermute(state, axis, fwd_perm)
            x0 = lax.dynamic_index_in_dim(xmb, jnp.clip(t, 0, M - 1),
                                          axis=0, keepdims=False)
            inp = jnp.where(stage == 0, x0, recv)
            y, aux = stage_apply(local_layers, inp)
            # last stage finished microbatch t-(P-1) this tick
            j = jnp.clip(t - last, 0, M - 1)
            upd = lax.dynamic_update_index_in_dim(outbuf, y, j, axis=0)
            outbuf = jnp.where((t >= last) & (stage == last), upd, outbuf)
            # this stage computed real data only for ticks in [stage, stage+M)
            aux = jnp.where((t >= stage) & (t < stage + M), aux, 0.0)
            return (y, outbuf), aux

        (_, outbuf), auxs = lax.scan(tick, (state, outbuf),
                                     jnp.arange(ticks))
        # only the last stage holds real outputs; make them uniform so the
        # out_spec can be replicated
        out = lax.psum(jnp.where(stage == last, outbuf, 0.0), axis)
        aux = lax.psum(jnp.sum(auxs), axis) / (n_stages * M)
        return out, aux

    y, aux = run(params["layers"], xmb)
    x = y.reshape(B, S, y.shape[-1])
    x = _rmsnorm(x, params["final_norm"])
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, aux


def pipelined_forward(params: dict, tokens: jax.Array, cfg: ModelConfig,
                      mesh: jax.sharding.Mesh,
                      microbatches: int | None = None) -> jax.Array:
    """Logits-only wrapper over :func:`pipelined_forward_with_aux`."""
    return pipelined_forward_with_aux(params, tokens, cfg, mesh,
                                      microbatches)[0]


def make_pipelined_train_step(cfg: ModelConfig, mesh: jax.sharding.Mesh,
                              microbatches: int | None = None,
                              learning_rate: float = 3e-4):
    """(params, opt_state, tokens) -> (params, opt_state, loss) with the
    forward (and therefore the GPipe backward) pipelined over "pp".

    The objective is model.make_train_step's, with the pipelined forward
    substituted (see the MoE-aux caveat on
    :func:`pipelined_forward_with_aux`)."""
    from tpushare.workloads.model import make_train_step

    def fwd(params, tokens, cfg):
        return pipelined_forward_with_aux(params, tokens, cfg, mesh,
                                          microbatches)

    return make_train_step(cfg, learning_rate, forward_fn=fwd)
