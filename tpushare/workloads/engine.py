"""Continuous-batching decode engine (slot-based, static shapes).

The serve path so far decodes one fixed batch start-to-finish; real
serving traffic is ragged — requests arrive mid-flight with different
prompt lengths and generation budgets. The GPU-world answer (vLLM-style
continuous batching) leans on dynamic batch reshaping; on TPU that would
mean recompilation per batch shape. This engine is the TPU-first
formulation, built so EVERY compiled program has a static shape:

- **Slots, not batches**: the KV cache is pre-allocated once as
  ``max_slots`` rows (`init_kv_cache(cfg, S, max_len)`); a request
  occupies a free slot, decodes in lock-step with whatever else is
  resident, and frees its slot on completion. No shape ever changes.
- **Per-slot positions via vmap**: one compiled step advances all S
  slots one token, each at its OWN position — ``jax.vmap`` of the
  tested single-stream :func:`forward_cached` over the slot axis, so
  numerics are the cached path's (parity-tested), and the per-slot
  cache write lowers to one scatter.
- **Decode quantum**: host sync once per ``quantum`` steps, not per
  token — ``lax.scan`` runs k masked steps on device and returns the
  [k, S] token block. Arrivals join at quantum boundaries; inactive
  slots compute-and-discard (the standard static-shape trade: HBM-bound
  decode makes the wasted lanes cheap, and XLA never re-specializes).
- **Bucketed prefill**: prompts pad to the next power-of-two bucket and
  run one B=1 ``forward_cached`` prefill; pad positions land BEYOND the
  slot's position watermark, so they are invisible to the position mask
  and later overwritten in place as decode advances. One compile per
  bucket, ~log2(max_len) compiles total.

Works with the bf16 and int8 KV caches, prompt-bounded or ROLLING:

- **Rolling (ring) slots** (``rolling=True``, requires
  ``cfg.attn_window`` and ``max_len >= 2*attn_window`` — the same
  retention sizing as ``greedy_decode_kv(rolling=True)``): each slot's
  KV buffer is a ring over ``position % max_len`` with its OWN
  wraparound watermark (``pos`` [S, max_len], threaded through the
  vmapped step with its own vmap axis), so continuous-batching serving
  holds O(window) HBM per slot no matter how long any request runs —
  the resource bound the scheduler's HBM accounting assumes.
- Rolling prefill chunks the prompt by ``attn_window`` (static chunk
  count per prompt, ~plen/W compiles worst case, shared across equal
  lengths): pads are confined to the FINAL chunk, whose positions are
  < plen + W and therefore can never wrap far enough
  (>= plen + (M - W) + 1) to clobber a ring key still inside a live
  query's window.
- Parity scoping for rolling: co-tenant invariance is BITWISE at any
  scale (fixed S, varying traffic — per-slot watermark rows never
  bleed), and S=1 matches solo ``greedy_decode_kv(rolling=True)``
  bitwise at matched ring geometry. S>1 vs the UNBATCHED solo stream
  is bitwise at llama-tiny scale (tests/test_engine.py) but can drift
  ~2e-5 at d_model 256: XLA reassociates an fp32 reduction in the
  vmapped rolling lane body that it happens not to touch in the
  non-rolling one. Claims are tested at the scopes that hold.

MoE presets stay excluded: capacity routing couples tokens across
slots (the same caveat as greedy_decode_kv).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from tpushare.workloads.model import (
    ModelConfig, forward_cached, init_kv_cache)


@dataclasses.dataclass
class _Request:
    rid: int
    slot: int
    tokens: list  # generated so far (host copy)
    budget: int   # max new tokens


def _bucket(n: int, lo: int = 8) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


class DecodeEngine:
    """Continuous-batching decoder over a fixed slot pool (greedy by
    default; per-engine or per-request sampling optional).

    >>> eng = DecodeEngine(params, cfg, max_slots=8, max_len=256)
    >>> rid = eng.submit([1, 17, 23], max_new=32)   # joins mid-flight
    >>> finished = eng.run_quantum()                 # {rid: [tokens...]}

    ``submit`` raises RuntimeError when no slot is free (callers queue;
    tpushare.workloads.serve does). Completion = budget exhausted or
    ``eos_id`` emitted. Deterministic: a request's tokens equal a solo
    :func:`greedy_decode_kv` run of the same prompt regardless of which
    co-tenants share the quantum (tests/test_engine.py asserts this).

    ``temperature > 0`` switches selection to sampling (optionally
    top-k- and/or nucleus/top-p-masked), still fully reproducible AND
    residency-independent:
    the sample key is ``fold_in(fold_in(seed, request_id), position)``,
    a function of the request and the query position only — never of
    the slot index, the co-tenants, or where quantum boundaries fall.
    """

    def __init__(self, params: dict, cfg: ModelConfig, max_slots: int,
                 max_len: int, quantum: int = 8,
                 eos_id: int | None = None, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 per_request_sampling: bool = False,
                 rolling: bool = False):
        cfg.validate()
        if cfg.moe_experts:
            raise ValueError("continuous batching excludes MoE presets "
                             "(capacity routing couples slots)")
        if rolling:
            if cfg.attn_window is None:
                raise ValueError("rolling slots require cfg.attn_window")
            if max_len < 2 * cfg.attn_window:
                # greedy_decode_kv's retention sizing: 2W keeps every
                # in-chunk query's W-1 older keys alive through the
                # chunk's own ring writes during chunked prefill
                raise ValueError(
                    f"rolling max_len {max_len} < 2*attn_window "
                    f"{2 * cfg.attn_window} (chunked-prefill retention)")
        if temperature < 0:
            raise ValueError(f"temperature {temperature} must be >= 0")
        if top_k < 0 or top_k > cfg.vocab:
            raise ValueError(f"top_k {top_k} outside [0, vocab]")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p {top_p} outside (0, 1]")
        if (top_k > 0 or top_p < 1.0) and temperature == 0.0 \
                and not per_request_sampling:
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature 0 is "
                "greedy argmax and would silently ignore them)")
        # per-request mode trades a per-step sort for runtime control:
        # temperature/top_p become per-slot traced state so one compiled
        # program serves mixed greedy and sampled traffic; the default
        # static mode keeps the pure-argmax program for greedy engines
        self._per_request = bool(per_request_sampling)
        self._rolling = bool(rolling)
        self._params = params
        self._cfg = cfg
        self._S = int(max_slots)
        self._M = int(max_len)
        self._quantum = int(quantum)
        self._eos = -1 if eos_id is None else int(eos_id)
        # sampling is static per engine (baked into the compiled step);
        # temperature 0 = greedy argmax, the deterministic default.
        # Randomness is keyed per (request, position): each request gets
        # fold_in(seed, rid) at submit and every emitted token folds in
        # its query position — so a request's sample stream is identical
        # no matter which slot it lands in or where quanta fall
        self._temperature = float(temperature)
        self._top_k = int(top_k)
        self._top_p = float(top_p)
        self._seed = int(seed)
        # key buffer shaped for the ACTIVE prng impl (threefry keys are
        # uint32[2], rbg uint32[4] — hardcoding one breaks the other)
        proto = jax.random.PRNGKey(0)
        self._slot_keys = jnp.zeros((self._S,) + proto.shape,
                                    proto.dtype)
        self._cache = init_kv_cache(cfg, self._S, self._M)
        if rolling:
            # per-SLOT ring watermark [S, M] (init_kv_cache's rolling
            # "pos" is one [M] row shared across a lockstep batch; engine
            # slots advance independently, so each carries its own)
            self._cache["pos"] = jnp.full((self._S, self._M), -1,
                                          jnp.int32)
        self._pos = jnp.zeros((self._S,), jnp.int32)
        self._last = jnp.zeros((self._S,), jnp.int32)
        self._active = jnp.zeros((self._S,), bool)
        self._remaining = jnp.zeros((self._S,), jnp.int32)
        self._slot_temp = jnp.zeros((self._S,), jnp.float32)
        self._slot_topp = jnp.ones((self._S,), jnp.float32)
        # per-slot eos: requests may carry their own stop token (both
        # modes — the compare target is a carried array either way)
        self._slot_eos = jnp.full((self._S,), self._eos, jnp.int32)
        self._free = list(range(self._S))
        self._by_slot: dict[int, _Request] = {}
        self._by_rid: dict[int, _Request] = {}
        self._next_rid = 0
        # requests completed by their own prefill (budget 1 / instant
        # eos), surfaced by the next run_quantum/drain
        self._done_now: dict[int, list[int]] = {}
        # tokens emitted per rid by the MOST RECENT run_quantum (incl.
        # a finishing request's final chunk) — the streaming hook;
        # valid until the next call, same-thread use only
        self.last_quantum_tokens: dict[int, list[int]] = {}

    # -- compiled programs (cached per engine: shapes are fixed) -------------

    def _pick_fn(self):
        """Token selection from final-position 1-D logits, keyed by
        (request key, query position). Returned signature is always
        ``pick(logits, key, temp, top_p)``:

        - static mode (default): temp/top_p args are ignored; the
          engine-level temperature bakes in greedy argmax (pure, no
          sort) or fixed-knob sampling at trace time.
        - per-request mode: temp/top_p are traced per-slot scalars —
          temp 0 selects the argmax via ``where`` (one program serves
          mixed greedy + sampled traffic), and top_p 1.0 naturally
          keeps the whole vocab (the cumulative mass before the last
          finite token is always < 1).
        """
        temperature, top_k, top_p = (self._temperature, self._top_k,
                                     self._top_p)

        def topk_mask(scaled):
            if top_k > 0:  # engine-static: lax.top_k needs a static k
                vals, _ = lax.top_k(scaled, top_k)
                return jnp.where(scaled >= vals[..., -1:], scaled,
                                 -jnp.inf)
            return scaled

        def nucleus_mask(scaled, p):
            # keep the smallest descending-prob prefix whose mass
            # reaches p (crossing token INCLUDED, so one always
            # survives). Value-floor form — sort + cumsum only, no
            # index gather/scatter in the vmapped decode hot loop;
            # boundary TIES share the floor and all survive
            svals = -jnp.sort(-scaled)
            probs = jax.nn.softmax(svals)
            cum = jnp.cumsum(probs)
            kth = jnp.sum((cum - probs) < p)  # mass BEFORE token < p
            floor = svals[kth - 1]
            return jnp.where(scaled >= floor, scaled, -jnp.inf)

        if self._per_request:
            def pick(logits, key, temp, p):
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                scaled = logits.astype(jnp.float32) / jnp.maximum(
                    temp, 1e-6)
                scaled = nucleus_mask(topk_mask(scaled), p)
                sampled = jax.random.categorical(
                    key, scaled, axis=-1).astype(jnp.int32)
                return jnp.where(temp > 0.0, sampled, greedy)

            return pick

        def pick(logits, key, temp, p):  # noqa: ARG001 — static knobs
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            scaled = topk_mask((logits / temperature).astype(
                jnp.float32))
            if top_p < 1.0:
                scaled = nucleus_mask(scaled, top_p)
            return jax.random.categorical(key, scaled,
                                          axis=-1).astype(jnp.int32)

        return pick

    @functools.cached_property
    def _quantum_fn(self):
        params, cfg = self._params, self._cfg
        pick = self._pick_fn()

        def slot_step(cache, last, pos):
            def one(cache_slot, tok, p):
                # kv leaves arrive [L, M, nkv, hd] and need a B=1 axis;
                # a rolling "pos" leaf arrives [M] and forward_cached
                # takes it batch-free (one watermark per B=1 stream)
                cb = {n: (b if n == "pos" else b[:, None])
                      for n, b in cache_slot.items()}
                logits, nc = forward_cached(params, tok[None, None], cb,
                                            p, cfg)
                out = {n: (b if n == "pos" else b[:, 0])
                       for n, b in nc.items()}
                return logits[0, -1], out

            axes = {n: (0 if n == "pos" else 1) for n in cache}
            return jax.vmap(one, in_axes=(axes, 0, 0),
                            out_axes=(0, axes))(cache, last, pos)

        def step(carry, _):
            (cache, pos, last, active, remaining, keys, temp,
             topp, eos) = carry
            logits, new_cache = slot_step(cache, last, pos)
            # per-(request, position) sample keys: quantum boundaries
            # and slot placement can't shift a request's stream
            step_keys = jax.vmap(jax.random.fold_in)(keys, pos)
            nxt = jax.vmap(pick)(logits, step_keys, temp, topp)
            # inactive slots keep their cache/position/token untouched
            sel = active.reshape(1, -1, *([1] * 3))
            cache = {n: jnp.where(active[:, None] if n == "pos"
                                  else sel, new, cache[n])
                     for n, new in new_cache.items()}
            emitted = jnp.where(active, nxt, -1)
            pos = pos + active.astype(jnp.int32)
            remaining = remaining - active.astype(jnp.int32)
            done = active & ((nxt == eos) | (remaining <= 0))
            last = jnp.where(active, nxt, last)
            active = active & ~done
            return (cache, pos, last, active, remaining, keys, temp,
                    topp, eos), emitted

        def run(cache, pos, last, active, remaining, keys, temp, topp,
                eos, k_steps):
            carry = (cache, pos, last, active, remaining, keys, temp,
                     topp, eos)
            carry, emitted = lax.scan(step, carry, None, length=k_steps)
            return carry, emitted  # emitted [k, S]

        return jax.jit(run, static_argnums=(9,))

    @functools.cached_property
    def _prefill_fn(self):
        params, cfg, M = self._params, self._cfg, self._M
        pick = self._pick_fn()

        if self._rolling:
            W = cfg.attn_window

            @functools.partial(jax.jit, static_argnums=(1,))
            def prefill(tokens_padded, padded_len, plen, key, temp,
                        topp):
                # mirror greedy_decode_kv's chunked ring prefill: W-wide
                # chunks (each <= M - (W-1), satisfied by M >= 2W), the
                # LAST chunk alone carrying pads. The final real
                # position plen-1 lands in exactly one chunk; its
                # logits row is carried out via a where-accumulator so
                # no [padded_len, vocab] buffer is ever materialized.
                cache1 = init_kv_cache(cfg, 1, M, rolling=True)
                row = jnp.zeros((cfg.vocab,), jnp.float32)
                for off in range(0, padded_len, W):
                    chunk = tokens_padded[off:off + W]
                    logits, cache1 = forward_cached(
                        params, chunk[None], cache1, off, cfg)
                    t_c = logits.shape[1]
                    idx = jnp.clip(plen - 1 - off, 0, t_c - 1)
                    hit = (plen - 1 >= off) & (plen - 1 < off + t_c)
                    final = lax.dynamic_index_in_dim(
                        logits, idx, axis=1, keepdims=False)[0]
                    row = jnp.where(hit, final, row)
                first = pick(row, jax.random.fold_in(key, plen - 1),
                             temp, topp)
                return first.astype(jnp.int32), cache1

            return prefill

        @functools.partial(jax.jit, static_argnums=(1,))
        def prefill(tokens_padded, bucket_len, plen, key, temp, topp):
            cache1 = init_kv_cache(cfg, 1, self._M)
            logits, cache1 = forward_cached(
                params, tokens_padded.reshape(1, bucket_len), cache1,
                jnp.int32(0), cfg)
            final = lax.dynamic_index_in_dim(logits, plen - 1, axis=1,
                                             keepdims=False)[0]
            # the prefill emits for query position plen-1; decode then
            # starts folding at plen — streams never collide
            first = pick(final, jax.random.fold_in(key, plen - 1),
                         temp, topp)
            return first.astype(jnp.int32), cache1

        return prefill

    @functools.cached_property
    def _insert_fn(self):
        @jax.jit
        def insert(cache, pos, last, active, remaining, keys, temp,
                   topp, eos, cache1, slot, plen, first, budget, rkey,
                   r_temp, r_topp, r_eos):
            new = {n: lax.dynamic_update_index_in_dim(
                       cache[n], cache1[n][:, 0], slot, axis=1)
                   for n in cache if n != "pos"}
            if "pos" in cache:
                # the prefill's B=1 ring watermark [M] becomes this
                # slot's row of the per-slot watermark [S, M]
                new["pos"] = lax.dynamic_update_index_in_dim(
                    cache["pos"], cache1["pos"], slot, axis=0)
            cache = new
            pos = pos.at[slot].set(plen)
            last = last.at[slot].set(first)
            # a prefill-time eos completes the request on the host side
            # (submit frees the slot immediately); the lane must go
            # inactive on device too, or run_quantum would decode a
            # ghost lane for up to budget-1 steps until slot reuse
            active = active.at[slot].set((budget > 1) & (first != r_eos))
            remaining = remaining.at[slot].set(budget - 1)
            keys = keys.at[slot].set(rkey)
            temp = temp.at[slot].set(r_temp)
            topp = topp.at[slot].set(r_topp)
            eos = eos.at[slot].set(r_eos)
            return (cache, pos, last, active, remaining, keys, temp,
                    topp, eos)

        return insert

    # -- host API ------------------------------------------------------------

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def resident(self) -> int:
        return self._S - len(self._free)

    def submit(self, prompt: list[int], max_new: int,
               temperature: float | None = None,
               top_p: float | None = None,
               eos_id: int | None = None) -> int:
        """Prefill ``prompt`` into a free slot; returns the request id.
        The first generated token is produced by the prefill itself.

        ``temperature``/``top_p`` override the engine defaults for THIS
        request (requires ``per_request_sampling=True``); None inherits
        the engine-level knobs. top_k stays engine-static (lax.top_k
        needs a static k). ``eos_id`` overrides the stop token for this
        request in EITHER mode (the compare target is per-slot state,
        not compiled structure)."""
        if not self._free:
            raise RuntimeError("no free slot (queue upstream)")
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError("max_new must be >= 1")
        if not self._rolling and len(prompt) + max_new > self._M:
            # rolling slots have no such bound: the ring ages keys out,
            # so prompt + generation may run past the buffer length
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new} exceeds "
                f"max_len {self._M}")
        if (temperature is not None or top_p is not None) \
                and not self._per_request:
            raise ValueError(
                "per-request temperature/top_p need "
                "per_request_sampling=True (the static engine bakes "
                "its knobs into the compiled step)")
        r_temp = self._temperature if temperature is None \
            else float(temperature)
        r_topp = self._top_p if top_p is None else float(top_p)
        r_eos = self._eos if eos_id is None else int(eos_id)
        if r_temp < 0:
            raise ValueError(f"temperature {r_temp} must be >= 0")
        if not 0.0 < r_topp <= 1.0:
            raise ValueError(f"top_p {r_topp} outside (0, 1]")
        if top_p is not None and r_topp < 1.0 and r_temp == 0.0:
            # mirror of the static constructor's guard: an EXPLICIT
            # nucleus directive at temperature 0 would be silently
            # discarded by the greedy argmax branch
            raise ValueError(
                "top_p requires temperature > 0 for this request "
                "(temperature 0 is greedy argmax and would silently "
                "ignore it)")
        slot = self._free.pop()
        plen = len(prompt)
        if self._rolling:
            # pad to covering W-chunks (pow2-bucketed below one chunk):
            # pads stay inside the FINAL chunk, so their ring writes sit
            # at positions < plen + W and can never reach the wrap
            # distance (plen + (M - W) + 1, M >= 2W) that would clobber
            # a key still inside a live query's window
            W = self._cfg.attn_window
            n_chunks = -(-plen // W)
            bucket = min(_bucket(plen), n_chunks * W)
        else:
            # the bucket must stay inside the slot's KV buffer: a
            # non-pow2 max_len would otherwise round a valid prompt past
            # it (e.g. plen 17 -> bucket 32 > max_len 24) and crash the
            # cache write
            bucket = min(_bucket(plen), self._M)
        padded = jnp.zeros((bucket,), jnp.int32).at[:plen].set(
            jnp.asarray(prompt, jnp.int32))
        rid = self._next_rid
        self._next_rid += 1
        rkey = jax.random.fold_in(jax.random.PRNGKey(self._seed), rid)
        t_arr = jnp.float32(r_temp)
        p_arr = jnp.float32(r_topp)
        first, cache1 = self._prefill_fn(padded, bucket,
                                         jnp.int32(plen), rkey,
                                         t_arr, p_arr)
        (self._cache, self._pos, self._last, self._active,
         self._remaining, self._slot_keys, self._slot_temp,
         self._slot_topp, self._slot_eos) = self._insert_fn(
            self._cache, self._pos, self._last, self._active,
            self._remaining, self._slot_keys, self._slot_temp,
            self._slot_topp, self._slot_eos, cache1, jnp.int32(slot),
            jnp.int32(plen), first, jnp.int32(max_new), rkey,
            t_arr, p_arr, jnp.int32(r_eos))
        req = _Request(rid=rid, slot=slot, tokens=[int(first)],
                       budget=max_new)
        self._by_slot[slot] = req
        self._by_rid[rid] = req
        if max_new == 1 or int(first) == r_eos:
            # completed by the prefill itself; slot never decodes
            self._free.append(slot)
            del self._by_slot[slot]
            self._done_now[rid] = req.tokens
        return rid

    def peek_tokens(self, rid: int) -> list[int] | None:
        """Tokens generated so far for an unreported request (None once
        it has been reported finished, or for an unknown rid). Same
        thread as run_quantum — this is the streaming frontend's view
        of a request between quanta."""
        req = self._by_rid.get(rid)
        return list(req.tokens) if req is not None else None

    def run_quantum(self, k: int | None = None) -> dict[int, list[int]]:
        """Advance all resident requests up to ``k`` (default: the
        engine's quantum) tokens; returns {rid: full token list} for
        requests that finished during this quantum (or at submit)."""
        finished: dict[int, list[int]] = self._done_now
        self._done_now = {}
        self.last_quantum_tokens = {}
        if not self._by_slot:
            for rid in finished:
                self._by_rid.pop(rid, None)
            return finished
        k = self._quantum if k is None else int(k)
        (carry, emitted) = self._quantum_fn(
            self._cache, self._pos, self._last, self._active,
            self._remaining, self._slot_keys, self._slot_temp,
            self._slot_topp, self._slot_eos, k)
        (self._cache, self._pos, self._last, self._active,
         self._remaining, self._slot_keys, self._slot_temp,
         self._slot_topp, self._slot_eos) = carry
        emitted_host = jax.device_get(emitted)  # [k, S], -1 = idle lane
        active_host = jax.device_get(self._active)
        for slot, req in list(self._by_slot.items()):
            toks = [int(t) for t in emitted_host[:, slot] if t >= 0]
            req.tokens.extend(toks)
            if toks:
                self.last_quantum_tokens[req.rid] = toks
            if not active_host[slot]:
                finished[req.rid] = req.tokens
                del self._by_slot[slot]
                self._free.append(slot)
        for rid in finished:
            self._by_rid.pop(rid, None)
        return finished

    def drain(self) -> dict[int, list[int]]:
        """Run quanta until every resident request completes."""
        out: dict[int, list[int]] = {}
        while self._by_slot or self._done_now:
            out.update(self.run_quantum())
        return out
