"""Sharding-aware training checkpoint/resume (orbax-backed).

The control plane already survives restarts (cache replay /
``build_cache``, the analogue of the reference's sync loop) and can
PREEMPT a gang member mid-run (extender preempt verb); this module is the
workload-side half of that story: a gang member that gets preempted and
re-placed resumes training from the latest durable step instead of from
scratch. The reference has no training loop at all, so there is nothing
to port — this is TPU-first by construction:

- **Sharded save/restore, no host gather**: checkpoints are written from
  and restored onto ``jax.sharding`` meshes directly (orbax handles
  per-shard IO); an 8B-parameter state never has to fit one host.
- **Cross-mesh restore**: the target mesh may differ from the one that
  saved (e.g. dp=4 x tp=2 -> dp=2 x tp=4 after a re-placement grants a
  different slice shape). The restore target is described abstractly —
  shapes + NamedShardings — so orbax reshards on read.
- **Optimizer state gets real shardings too**: optax's adamw state
  (``mu``/``nu``) mirrors the params pytree, so every leaf's
  PartitionSpec is derived by path-suffix match against
  :func:`tpushare.workloads.model.param_specs` (scalars like ``count``
  fall back to replicated). No sharding-propagation compile needed —
  the mapping is deterministic and testable.
- **Geometry guard**: the model geometry is stored next to the state and
  checked at restore; resuming a d_model=512 run from a d_model=4096
  checkpoint fails loudly, not with a shape error 40 frames deep.

Retention (``keep``) and atomicity (tmp-dir rename, partial writes never
visible as a step) come from ``ocp.CheckpointManager`` — the same
discipline the scheduler cache gets from CAS + rollback.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpushare.workloads.model import (
    ModelConfig, init_params, make_train_step, param_specs)

# geometry fields that must match between the checkpoint and the resuming
# process; dtype is deliberately absent (a bf16 run may resume an fp32
# experiment) and attn/attn_window too (serving knobs, not state shape)
_GEOMETRY_FIELDS = ("vocab", "d_model", "n_layers", "n_heads",
                    "n_kv_heads", "d_ff", "moe_experts", "moe_top_k")
_VIT_GEOMETRY_FIELDS = ("image", "patch", "channels", "d_model",
                        "n_layers", "n_heads", "d_ff", "classes")


def _family(cfg):
    """(family_name, init_fn, specs_fn, geometry_fields, make_train) —
    ONE dispatch point for every call site (state shapes, shardings,
    geometry meta, and the train-step factory must all agree on the
    family). The vit import stays lazy so llama-only runs never load
    it; an unrecognized config type fails loudly here instead of as an
    AttributeError deep inside init."""
    if isinstance(cfg, ModelConfig):
        return ("llama", init_params, param_specs, _GEOMETRY_FIELDS,
                make_train_step)
    if type(cfg).__name__ == "ViTConfig":
        from tpushare.workloads.vit import (
            init_vit_params, make_vit_train_step, vit_param_specs)
        return ("vit", init_vit_params, vit_param_specs,
                _VIT_GEOMETRY_FIELDS, make_vit_train_step)
    raise TypeError(
        f"unknown workload family for config type "
        f"{type(cfg).__qualname__} — teach _family() about it")


def _geometry(cfg) -> dict:
    name, _, _, fields, _ = _family(cfg)
    geo = {f: getattr(cfg, f) for f in fields}
    geo["family"] = name
    return geo


def _key_str(entry: Any) -> str:
    """One tree-path entry as its plain key string (dict key, namedtuple
    field, or sequence index)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _path_spec_index(cfg) -> dict:
    """Map each params tree path (tuple of key strings) to its spec."""
    specs = _family(cfg)[2](cfg)
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    return {tuple(_key_str(e) for e in path): spec for path, spec in flat}


def opt_specs_like(cfg, abstract_opt: Any) -> Any:
    """PartitionSpec tree for an optimizer-state pytree.

    adamw's ``mu``/``nu`` embed the params pytree whole, so a leaf at
    ``(0, 'mu', 'layers', 'wq')`` takes the spec of params leaf
    ``('layers', 'wq')`` — the longest path SUFFIX that names a param.
    Leaves with no matching suffix (step counters, empty states) are
    replicated. Works for any optax chain that stores param-shaped
    moments under param-named paths, which is optax's convention.
    """
    index = _path_spec_index(cfg)
    suffix_lens = sorted({len(k) for k in index}, reverse=True)

    def spec_for(path, leaf):
        names = tuple(_key_str(e) for e in path)
        for n in suffix_lens:
            spec = index.get(names[-n:]) if n <= len(names) else None
            if spec is not None and leaf.ndim == len(spec):
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, abstract_opt)


def abstract_train_state(cfg, tx: Any,
                         mesh: jax.sharding.Mesh | None = None) -> dict:
    """The restore target: {"params", "opt_state"} as ShapeDtypeStructs,
    carrying NamedShardings for ``mesh`` (or no shardings when None —
    single-device runs). This is what makes restore cross-mesh: orbax
    reads each shard straight onto the TARGET layout."""
    cfg.validate()
    _, init_fn, specs_fn, _, _ = _family(cfg)
    a_params = jax.eval_shape(lambda k: init_fn(cfg, k),
                              jax.random.key(0))
    a_opt = jax.eval_shape(tx.init, a_params)
    if mesh is None:
        return {"params": a_params, "opt_state": a_opt}

    def with_sharding(a, spec):
        return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                    sharding=NamedSharding(mesh, spec))

    p_specs = specs_fn(cfg)
    return {
        "params": jax.tree.map(with_sharding, a_params, p_specs),
        "opt_state": jax.tree.map(with_sharding, a_opt,
                                  opt_specs_like(cfg, a_opt)),
    }


class TrainCheckpointer:
    """Checkpoint/resume for ``make_train_step`` state.

    >>> ckpt = TrainCheckpointer(dir, keep=3)
    >>> params, opt_state, start = ckpt.resume_or_init(cfg, tx, key)
    >>> for step in range(start, total):
    ...     params, opt_state, loss = train_step(params, opt_state, toks)
    ...     ckpt.maybe_save(step + 1, params, opt_state, cfg, every=50)
    >>> ckpt.close()

    Saves are atomic (orbax writes to a tmp dir and renames) and pruned
    to the newest ``keep`` steps. ``save`` blocks until durable — a gang
    member acking a preempt AFTER save() returns cannot lose that step.
    """

    def __init__(self, directory: str, keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True))

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def steps(self) -> list[int]:
        """All retained checkpoint steps, ascending (at most ``keep``)."""
        return sorted(self._mgr.all_steps())

    def save(self, step: int, params: Any, opt_state: Any,
             cfg) -> None:
        ocp = self._ocp
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(
                    {"params": params, "opt_state": opt_state}),
                meta=ocp.args.JsonSave(_geometry(cfg))))
        self._mgr.wait_until_finished()

    def maybe_save(self, step: int, params: Any, opt_state: Any,
                   cfg, every: int) -> bool:
        if every <= 0 or step % every:
            return False
        self.save(step, params, opt_state, cfg)
        return True

    def restore(self, cfg, tx: Any,
                mesh: jax.sharding.Mesh | None = None,
                step: int | None = None) -> tuple[Any, Any, int]:
        """Returns (params, opt_state, step) at ``step`` (default latest),
        laid out for ``mesh``. Raises FileNotFoundError when the
        directory holds no checkpoint and ValueError on geometry
        mismatch."""
        ocp = self._ocp
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError("no checkpoint to restore")
        # geometry first, state second: the guard must fire BEFORE
        # StandardRestore's own strict shape check (whose error names a
        # tensor, not the mistake) — and a wrong-geometry state never
        # gets read off disk at all
        saved_geo = dict(self._mgr.restore(
            step, args=ocp.args.Composite(
                meta=ocp.args.JsonRestore()))["meta"])
        # checkpoints written before the family tag existed are llama
        # (the only family then) — an upgrade mid-run must not strand a
        # preempted trainer's own valid checkpoint
        saved_geo.setdefault("family", "llama")
        want_geo = _geometry(cfg)
        if saved_geo != want_geo:
            raise ValueError(
                f"checkpoint geometry {saved_geo} != resuming config "
                f"{want_geo} — refusing to load mismatched state")
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(
                    abstract_train_state(cfg, tx, mesh))))
        state = restored["state"]
        return state["params"], state["opt_state"], step

    def resume_or_init(self, cfg, tx: Any, key: jax.Array,
                       mesh: jax.sharding.Mesh | None = None,
                       ) -> tuple[Any, Any, int]:
        """Latest checkpoint if one exists, else a fresh init — the one
        call a preemptable trainer makes at startup. Returns
        (params, opt_state, start_step); start_step 0 means fresh."""
        step = self.latest_step()
        if step is not None:
            params, opt_state, step = self.restore(cfg, tx, mesh=mesh)
            return params, opt_state, step
        _, init_fn, specs_fn, _, _ = _family(cfg)
        if mesh is None:
            params = init_fn(cfg, key)
        else:
            # init INSIDE jit with out_shardings: the params materialize
            # directly as global sharded arrays — correct in multi-process
            # meshes too, where device_put of a host-local array onto a
            # sharding spanning non-addressable devices is not
            p_sh = jax.tree.map(lambda s: NamedSharding(mesh, s),
                                specs_fn(cfg),
                                is_leaf=lambda x: isinstance(x, P))
            params = jax.jit(lambda k: init_fn(cfg, k),
                             out_shardings=p_sh)(key)
        opt_state = tx.init(params)
        return params, opt_state, 0

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_resumable_trainer(cfg, directory: str,
                           keep: int = 3, learning_rate: float = 3e-4):
    """Convenience wiring: (ckpt, tx, train_step) ready for the player's
    train mode or any custom loop. Dispatches the train step by family
    (llama LM loss / ViT classification loss)."""
    cfg = dataclasses.replace(cfg).validate()
    make_train = _family(cfg)[4]
    tx, train_step = make_train(cfg, learning_rate=learning_rate)
    return TrainCheckpointer(directory, keep=keep), tx, train_step
