"""Llama-style decoder-only transformer in pure JAX, TPU-first.

This is the workload family behind samples/5-serving.yaml (BASELINE config
#5: co-located int8 JAX-serving replicas) and the flagship model for the
driver's `__graft_entry__` compile checks. Design choices are TPU-idiomatic
rather than a port of any torch code:

- **Stacked layers + ``lax.scan``**: one compiled layer body regardless of
  depth; no Python-loop unrolling, fast compiles, XLA-friendly.
- **bf16 params/activations, fp32 softmax + RMSNorm accumulations**: MXU
  feeds on bf16; numerics that need range run in fp32.
- **GQA attention with RoPE**, SwiGLU MLP — the llama recipe.
- **int8 weight quantization** (per-output-channel scales): weights live as
  int8 in HBM (the point of an 8 GiB-per-chip serving grant), dequantized
  on the fly into the bf16 matmul.
- **dp x tp mesh shardings** as PartitionSpec trees: attention heads and
  FFN hidden shard over "tp" (all-reduce over ICI inserted by XLA at wo/w2),
  batch shards over "dp". Specs live next to the params they describe.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    # attention implementation: "einsum" (XLA-fused) or "flash" (Pallas
    # online-softmax kernel, differentiable via its blockwise custom VJP;
    # see tpushare/workloads/attention.py). Both train and serve; the
    # KV-cached decode STEPS always use the einsum core (single-token
    # queries don't amortize a fused kernel), but a prefill-from-zero
    # with attn="flash" runs the Pallas kernel over the chunk itself —
    # T x T causal instead of einsum over the full T x M buffer — which
    # is where serving's time-to-first-token goes (forward_cached).
    attn: str = "einsum"
    # sliding-window (local) attention span: None = full causal. Applies
    # to every path — the flash kernel skips blocks below the window
    # floor (O(window) per query), einsum and the KV-cached decode mask
    # (the cache stays prompt-bounded; a rolling buffer would only add
    # the O(window) MEMORY saving, not change outputs) — Mistral-style
    # long-context serving.
    attn_window: int | None = None
    # KV-cache storage dtype for the serving decode path: "model" keeps
    # cfg.dtype (bf16); "int8" stores per-(token, kv-head) symmetric
    # int8 + an fp32 scale — the decode step is HBM-bandwidth-bound on
    # cache reads, so int8 halves the traffic (and the residency that
    # competes with co-tenants on a shared chip), completing the int8
    # serving story that quantize_int8 starts for the weights.
    kv_cache_dtype: str = "model"
    # mixture-of-experts FFN (tpushare/workloads/moe.py): 0 = dense SwiGLU;
    # >0 replaces every layer's FFN with moe_experts experts of width d_ff,
    # expert weights sharded over the "ep" mesh axis.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def moe(self) -> "Any":
        """MoEConfig for the FFN, or None when dense."""
        if self.moe_experts <= 0:
            return None
        from tpushare.workloads.moe import MoEConfig
        return MoEConfig(d_model=self.d_model, d_ff=self.d_ff,
                         n_experts=self.moe_experts, top_k=self.moe_top_k,
                         capacity_factor=self.moe_capacity_factor,
                         dtype=self.dtype)

    def validate(self) -> "ModelConfig":
        assert self.d_model % self.n_heads == 0
        assert self.n_heads % self.n_kv_heads == 0
        assert self.attn_window is None or self.attn_window >= 1
        assert self.kv_cache_dtype in ("model", "int8")
        return self


PRESETS = {
    # ~Llama-3-8B geometry (the BASELINE config #5 serving model)
    "llama-8b": ModelConfig(),
    # small config for single-host smoke runs on a shared chip
    "llama-mini": ModelConfig(vocab=2048, d_model=512, n_layers=4,
                              n_heads=8, n_kv_heads=4, d_ff=1408),
    # tiny config for compile checks and CPU-mesh dry runs
    "llama-tiny": ModelConfig(vocab=256, d_model=64, n_layers=2,
                              n_heads=4, n_kv_heads=2, d_ff=128),
    # tiny mixtral-style MoE variant: 4 experts, top-2 routing, for the
    # expert-parallel ("ep") sharding dry run and tests
    "llama-moe-tiny": ModelConfig(vocab=256, d_model=64, n_layers=2,
                                  n_heads=4, n_kv_heads=2, d_ff=128,
                                  moe_experts=4),
}


# -- init ---------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array) -> dict:
    """Stacked-layer parameter pytree (leading axis = layer)."""
    cfg.validate()
    k = iter(jax.random.split(key, 12))
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    L, d, f, v = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab

    def w(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    # key draw order is part of the reproducibility contract: embed, then
    # attention weights, then FFN weights, then lm_head — identical to the
    # pre-MoE layout for dense configs (same seed => same dense params)
    embed = w(next(k), v, d, fan_in=d)  # scaled like output layers
    layers = {
        "attn_norm": jnp.ones((L, d), cfg.dtype),
        "wq": w(next(k), L, d, nh * hd, fan_in=d),
        "wk": w(next(k), L, d, nkv * hd, fan_in=d),
        "wv": w(next(k), L, d, nkv * hd, fan_in=d),
        "wo": w(next(k), L, nh * hd, d, fan_in=nh * hd),
        "ffn_norm": jnp.ones((L, d), cfg.dtype),
    }
    if cfg.moe_experts > 0:
        # moe.py owns the expert layout; vmap stacks it to [L, ...]
        from tpushare.workloads.moe import init_moe_params
        moe_keys = jax.random.split(next(k), L)
        layers.update(jax.vmap(
            lambda kk: init_moe_params(cfg.moe, kk))(moe_keys))
    else:
        layers.update({
            "w1": w(next(k), L, d, f, fan_in=d),
            "w3": w(next(k), L, d, f, fan_in=d),
            "w2": w(next(k), L, f, d, fan_in=f),
        })
    return {
        "embed": embed,
        "layers": layers,
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": w(next(k), d, v, fan_in=d),
    }


def param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpec tree matching init_params: tensor-parallel over "tp".

    Heads/hidden shard on the output dim of the in-projections and the
    input dim of the out-projections, so XLA inserts exactly one
    ICI all-reduce per block (after wo, after w2) — the megatron layout.
    MoE variants shard the expert axis over "ep" instead (the token
    dispatch/combine einsums then lower to ICI all_to_all).
    """
    layers = {
        "attn_norm": P(None, None),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "ffn_norm": P(None, None),
    }
    if cfg.moe_experts > 0:
        # derive from moe.py's single-layer specs: prepend the layer axis
        from tpushare.workloads.moe import moe_param_specs
        layers.update({name: P(None, *spec)
                       for name, spec in moe_param_specs().items()})
    else:
        layers.update({
            "w1": P(None, None, "tp"),
            "w3": P(None, None, "tp"),
            "w2": P(None, "tp", None),
        })
    return {
        "embed": P(None, None),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P(None, "tp"),
    }


def batch_spec() -> P:
    return P("dp", None)


# -- int8 weight quantization -------------------------------------------------

QUANT_KEYS = ("wq", "wk", "wv", "wo", "w1", "w3", "w2")


def quantize_int8(params: dict) -> dict:
    """Per-output-channel symmetric int8 for the big matmul weights.

    HBM footprint drops ~2x vs bf16 (the reason a llama-8b replica fits an
    8 GiB grant). Norms/embeddings stay bf16.
    """
    out = {"embed": params["embed"], "final_norm": params["final_norm"],
           "lm_head": _q(params["lm_head"]), "layers": {}}
    for name, w in params["layers"].items():
        # MoE expert weights ([L, E, d, f]) stay bf16: moe_ffn's batched
        # expert einsums take plain arrays (router fp32 regardless)
        quant = name in QUANT_KEYS and w.ndim == 3
        out["layers"][name] = _q(w) if quant else w
    return out


def _sym_int8(x: jax.Array, axis: int):
    """Symmetric int8 along ``axis``: (int8 values, fp32 scales with the
    reduced axis kept). Shared by weight quantization (per output
    channel, axis=-2) and KV-cache quantization (per token-and-head over
    head_dim, axis=-1) so the floor/rounding conventions cannot drift."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis,
                    keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def _q(w: jax.Array) -> dict:
    q, scale = _sym_int8(w, axis=-2)
    return {"int8": q, "scale": scale}


def _matmul(x: jax.Array, w) -> jax.Array:
    """bf16 matmul for plain weights; on-the-fly dequant for int8 weights.

    The dequant multiplies AFTER the int8->bf16 cast but BEFORE the matmul
    contraction would lose the scale, i.e. (x @ q) * scale — one fused
    elementwise epilogue on the MXU output.
    """
    if isinstance(w, dict):
        y = jnp.einsum("...k,kn->...n", x, w["int8"].astype(x.dtype))
        return y * jnp.squeeze(w["scale"], axis=-2).astype(x.dtype)
    return jnp.einsum("...k,kn->...n", x, w)


def quant_specs(specs: dict) -> dict:
    """PartitionSpec tree for quantized params: int8 shards like the weight,
    the per-channel scale shards like the weight's last dim."""
    out = {"embed": specs["embed"], "final_norm": specs["final_norm"],
           "lm_head": _qspec(specs["lm_head"]), "layers": {}}
    for name, spec in specs["layers"].items():
        quant = name in QUANT_KEYS and len(spec) == 3
        out["layers"][name] = _qspec(spec) if quant else spec
    return out


def _qspec(spec: P) -> dict:
    return {"int8": spec, "scale": P(*spec[:-2], None, spec[-1])}


# -- forward ------------------------------------------------------------------

def _rmsnorm(x: jax.Array, g: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * rms).astype(x.dtype) * g


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [B, S, H, D]; rotate pairs (fp32 trig, bf16 result)."""
    half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def decoder_layer(x: jax.Array, lp: dict, positions: jax.Array,
                  cfg: ModelConfig, mask: jax.Array | None = None):
    """One transformer block: x [B, S, d] -> (x, aux).

    Shared by :func:`forward`'s layer scan and the pipeline-parallel stage
    bodies (tpushare/workloads/pipeline.py). ``positions`` [B, S] feeds
    RoPE; ``mask`` [S, S] overrides the default causal attention mask
    (einsum backend only — the flash kernel bakes causality in, so a
    custom mask with ``cfg.attn == "flash"`` raises rather than being
    silently ignored); ``aux`` is the MoE load-balance term (0 for
    dense)."""
    B, S = x.shape[:2]
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    h = _rmsnorm(x, lp["attn_norm"])
    q, k, v = _qkv(h, lp, positions, cfg)
    if cfg.attn == "flash":
        if mask is not None:
            raise ValueError(
                "the flash backend supports only the default causal mask; "
                "use attn='einsum' for custom masks")
        # GQA-native: the kernel streams the SMALL kv heads (no repeat —
        # the whole HBM point of grouped-query attention at serve time)
        from tpushare.workloads.attention import flash_attention
        attn = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=True,
            window=cfg.attn_window,
        ).transpose(0, 2, 1, 3).reshape(B, S, nh * hd)
    else:
        # GQA: repeat kv heads up to query heads for the einsum spec path
        reps = nh // nkv
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
        if mask is None:
            mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        if cfg.attn_window is not None:
            # composed into CUSTOM masks too — silently running full
            # attention on one path while the flash/decode paths window
            # would break the same-model-everywhere invariant
            from tpushare.workloads.attention import sliding_window_mask
            mask = jnp.logical_and(mask, sliding_window_mask(
                jnp.arange(S)[:, None], jnp.arange(S)[None, :],
                cfg.attn_window))
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
        scores = scores * (hd ** -0.5)
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(
            B, S, nh * hd)
    x = x + _matmul(attn, lp["wo"])
    return _ffn_block(x, lp, cfg)


def forward(params: dict, tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab]."""
    return forward_with_aux(params, tokens, cfg)[0]


def forward_with_aux(params: dict, tokens: jax.Array, cfg: ModelConfig):
    """tokens [B, S] int32 -> (logits [B, S, vocab], aux loss scalar).

    ``aux`` is the mean per-layer MoE load-balance loss (0 for dense
    models); training adds it with weight ``cfg.moe_aux_weight``.

    Layer stack runs under ``lax.scan``; the whole function is jit/pjit
    compatible (static shapes, no data-dependent Python control flow).
    """
    B, S = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)  # [B,S,d]
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))

    def layer(x, lp):
        return decoder_layer(x, lp, positions, cfg)

    x, auxs = lax.scan(layer, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"])
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, jnp.mean(auxs)


# -- loss / train step --------------------------------------------------------

def next_token_loss(logits: jax.Array, aux: jax.Array, targets: jax.Array,
                    cfg: ModelConfig) -> jax.Array:
    """Cross-entropy of shifted logits against targets + weighted MoE aux.

    The single definition of the training objective, shared by the
    sequential trainer here and the pipeline-parallel trainer
    (tpushare/workloads/pipeline.py) so the two cannot drift."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll) + cfg.moe_aux_weight * aux


def loss_fn(params: dict, tokens: jax.Array, cfg: ModelConfig,
            forward_fn=None) -> jax.Array:
    """Next-token cross-entropy over the shifted sequence (+ MoE aux).

    ``forward_fn(params, tokens, cfg) -> (logits, aux)`` defaults to
    :func:`forward_with_aux`; trainers with a different execution plan for
    the same model (e.g. the GPipe pipeline) substitute theirs."""
    logits, aux = (forward_fn or forward_with_aux)(params, tokens[:, :-1],
                                                   cfg)
    return next_token_loss(logits, aux, tokens[:, 1:], cfg)


def make_train_step(cfg: ModelConfig, learning_rate: float = 3e-4,
                    forward_fn=None):
    """(params, opt_state, tokens) -> (params, opt_state, loss), pure."""
    import optax

    tx = optax.adamw(learning_rate)

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            functools.partial(loss_fn, cfg=cfg,
                              forward_fn=forward_fn))(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return tx, train_step


# -- KV-cache forward (serving path) ------------------------------------------

def init_kv_cache(cfg: ModelConfig, batch: int, max_len: int,
                  rolling: bool = False) -> dict:
    """Zeroed per-layer K/V buffers: [L, B, max_len, n_kv, head_dim].

    With ``cfg.kv_cache_dtype == "int8"`` the buffers store int8 values
    plus per-(token, kv-head) fp32 scales ("ks"/"vs",
    [L, B, max_len, n_kv, 1]) — ~2x less HBM traffic per decode step.

    ``rolling=True`` (requires ``cfg.attn_window`` and ``max_len >=
    attn_window``) makes the buffer a RING over slots ``pos % max_len``:
    cache memory and per-step attention cost become O(window) no matter
    how long generation runs — the rolling-buffer cache of
    sliding-window serving (Mistral-style). A "pos" array tracks each
    slot's global position for masking; a slot is only ever overwritten
    by a key at least ``max_len >= window`` positions newer, which the
    window mask had already aged out.
    """
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    if rolling:
        assert cfg.attn_window is not None, \
            "rolling cache requires cfg.attn_window"
        assert max_len >= cfg.attn_window, \
            f"rolling buffer {max_len} < window {cfg.attn_window}: " \
            "overwritten slots would still be visible"
    if cfg.kv_cache_dtype == "int8":
        sshape = shape[:-1] + (1,)
        cache = {"k": jnp.zeros(shape, jnp.int8),
                 "v": jnp.zeros(shape, jnp.int8),
                 "ks": jnp.zeros(sshape, jnp.float32),
                 "vs": jnp.zeros(sshape, jnp.float32)}
    else:
        cache = {"k": jnp.zeros(shape, cfg.dtype),
                 "v": jnp.zeros(shape, cfg.dtype)}
    if rolling:
        # slot -> global position of the key it holds (-1 = never written)
        cache["pos"] = jnp.full((max_len,), -1, jnp.int32)
    return cache


def _kv_quant(x: jax.Array):
    """Per-(token, kv-head) symmetric int8 over the head_dim axis:
    [B, T, n_kv, hd] -> (int8 values, fp32 scales [B, T, n_kv, 1])."""
    return _sym_int8(x, axis=-1)


def _qkv(h: jax.Array, lp: dict, positions: jax.Array, cfg: ModelConfig):
    """Projections + RoPE shared by the cached and uncached layer bodies."""
    B, T = h.shape[:2]
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    q = _matmul(h, lp["wq"]).reshape(B, T, nh, hd)
    k = _matmul(h, lp["wk"]).reshape(B, T, nkv, hd)
    v = _matmul(h, lp["wv"]).reshape(B, T, nkv, hd)
    return (_rope(q, positions, cfg.rope_theta),
            _rope(k, positions, cfg.rope_theta), v)


def _ffn_block(x: jax.Array, lp: dict, cfg: ModelConfig):
    """Post-attention half of a layer: residual + RMSNorm + FFN.

    Returns ``(x, aux)``: aux is the MoE load-balance loss for this layer
    (0 for the dense SwiGLU path)."""
    h = _rmsnorm(x, lp["ffn_norm"])
    if cfg.moe_experts > 0:
        from tpushare.workloads.moe import moe_ffn
        y, aux = moe_ffn({"wg": lp["wg"], "w1": lp["w1"],
                          "w3": lp["w3"], "w2": lp["w2"]}, h, cfg.moe)
        return x + y, aux
    gated = jax.nn.silu(_matmul(h, lp["w1"])) * _matmul(h, lp["w3"])
    return x + _matmul(gated, lp["w2"]), jnp.zeros((), jnp.float32)


def forward_cached(params: dict, tokens: jax.Array, cache: dict,
                   pos_offset: jax.Array, cfg: ModelConfig,
                   prefill_from_zero: bool | None = None):
    """Incremental forward: attend the T new tokens against the KV cache.

    tokens [B, T] occupy global positions pos_offset..pos_offset+T-1; their
    K/V are written into the cache in place (functionally), and attention
    runs over the full fixed-size buffer with a causal position mask — so
    one compiled program serves both prefill (T = prompt len) and decode
    (T = 1). Returns (logits [B, T, vocab], updated cache). Cost per decode
    step is O(max_len) instead of greedy_decode's O(max_len^2) recompute.

    A cache carrying "pos" (``init_kv_cache(rolling=True)``) is a RING:
    writes land at slot ``pos % M`` and the mask derives from each slot's
    recorded global position instead of its index, so an O(window)-sized
    buffer bounds memory and step cost for arbitrarily long generation.
    Chunk-size contract: T <= M always (a longer chunk overwrites its
    own keys), and for windowed correctness mid-stream the buffer must
    retain each query's W-1 older keys across the chunk's writes —
    i.e. T <= M - (attn_window - 1) once positions >= window exist
    (greedy_decode_kv's rolling mode sizes M = 2W and chunks by W).
    """
    B, T = tokens.shape
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    reps = nh // nkv
    M = cache["k"].shape[2]
    rolling = "pos" in cache
    if rolling:
        assert T <= M, f"rolling cache: chunk {T} > buffer {M}"
        # Mid-stream windowed correctness needs each in-chunk query's
        # W-1 older keys to survive the chunk's own ring writes, i.e.
        # T <= M - (W-1). The over-wide exception is a prefill from
        # GLOBAL position 0 (nothing older is live), only checkable when
        # pos_offset is a concrete (untraced) zero. greedy_decode_kv's
        # long-run sizing (M = 2W, chunks of W) satisfies the strict
        # bound, but its short runs cap M at the total sequence length
        # (see its `max(min(2*W, total), W)`) and then the first prefill
        # chunk legitimately takes this concrete-zero branch — it is
        # load-bearing, not merely an escape hatch.
        W = cfg.attn_window
        if (W is not None and T > M - (W - 1)
                and not isinstance(pos_offset, jax.core.Tracer)):
            # Enforceable only for a CONCRETE pos_offset: an over-wide
            # chunk is legal exactly when it prefills from global 0,
            # and a traced offset could be that 0 — asserting on it
            # would reject previously-valid jitted prefills, so traced
            # callers keep the documented contract on trust.
            assert int(pos_offset) == 0, (
                f"rolling cache: chunk T={T} > M-(W-1)={M - (W - 1)} "
                f"overwrites keys still inside an in-chunk query's "
                f"window mid-stream; chunk by <= {M - (W - 1)} (or "
                f"prefill from pos_offset=0 with T <= M)")
    x = jnp.take(params["embed"], tokens, axis=0)
    q_pos = pos_offset + jnp.arange(T)                       # [T] global
    positions = jnp.broadcast_to(q_pos, (B, T))
    from tpushare.workloads.attention import sliding_window_mask
    if rolling:
        slots = q_pos % M                                    # [T] write ring
        new_pos = cache["pos"].at[slots].set(q_pos)
        key_global = new_pos[None, :]                        # [1, M]
        mask = jnp.logical_and(key_global >= 0,
                               key_global <= q_pos[:, None])
        # attn_window is asserted present for rolling caches at init;
        # masking by the slot's GLOBAL position makes wrap-around safe
        mask = jnp.logical_and(mask, sliding_window_mask(
            q_pos[:, None], key_global, cfg.attn_window))
    else:
        slots = None
        new_pos = None
        key_pos = jnp.arange(M)
        mask = key_pos[None, :] <= q_pos[:, None]            # [T, M]
        if cfg.attn_window is not None:
            # the prompt-bounded cache honors the window by masking (the
            # O(window) MEMORY saving is what rolling=True adds)
            mask = jnp.logical_and(mask, sliding_window_mask(
                q_pos[:, None], key_pos[None, :], cfg.attn_window))

    int8_cache = cfg.kv_cache_dtype == "int8"

    def write(buf, new):
        """New tokens into the buffer: ring scatter (rolling) or the
        contiguous dynamic_update_slice (prompt-bounded)."""
        if rolling:
            return buf.at[:, slots].set(new.astype(buf.dtype))
        return lax.dynamic_update_slice(buf, new.astype(buf.dtype),
                                        (0, pos_offset, 0, 0))

    # flash prefill fast path: a prefill from GLOBAL position 0 attends
    # only the T tokens being written, under exactly a causal(+window)
    # mask — standard self-attention, so the fused kernel applies and
    # the T x M buffer einsum (mostly masked columns) is skipped. Decode
    # steps (T == 1) and mid-stream/ring chunks keep the einsum core.
    # With an int8 cache the prefill then attends the PRE-quantization
    # k/v (full precision, strictly less rounding than the einsum path's
    # quantized-cache read); the cache still stores int8 for later steps.
    # ``prefill_from_zero``: pass True/False to select deterministically
    # (greedy_decode_kv does); None infers from a CONCRETE pos_offset ==
    # 0, which a jit-traced pos_offset can never satisfy — an inferring
    # caller that jits pos_offset as an argument silently keeps the
    # einsum path (correct, just slower; and with int8 caches the two
    # paths round differently), so serving code should be explicit.
    if prefill_from_zero is None:
        prefill_from_zero = (not isinstance(pos_offset, jax.core.Tracer)
                             and int(pos_offset) == 0)
    flash_prefill = (cfg.attn == "flash" and T > 1 and not rolling
                     and prefill_from_zero)

    def layer(x, xs):
        lp, c = xs  # c: this layer's cache slices (dict pytree)
        h = _rmsnorm(x, lp["attn_norm"])
        q, k, v = _qkv(h, lp, positions, cfg)
        if int8_cache:
            kq8, ks = _kv_quant(k)
            vq8, vs = _kv_quant(v)
            c = dict(k=write(c["k"], kq8), v=write(c["v"], vq8),
                     ks=write(c["ks"], ks), vs=write(c["vs"], vs))
        else:
            c = dict(k=write(c["k"], k), v=write(c["v"], v))
        if flash_prefill:
            from tpushare.workloads.attention import flash_attention
            o = flash_attention(q.transpose(0, 2, 1, 3),   # [B, nh, T, hd]
                                k.transpose(0, 2, 1, 3),   # GQA-native
                                v.transpose(0, 2, 1, 3),
                                causal=True, window=cfg.attn_window)
            attn_flat = o.transpose(0, 2, 1, 3).reshape(B, T, nh * hd)
        else:
            if int8_cache:
                # scales factor OUT of both contractions (constant over
                # the contracted head_dim axis), so no dequantized
                # [B, M, n_kv, hd] buffer is ever built: the dot
                # operands are a plain int8->bf16 convert of the cache,
                # and the per-key scales apply to the [.., M]-shaped
                # scores/probs instead — hd-times less elementwise work
                # than full dequant
                kd, vd = c["k"].astype(x.dtype), c["v"].astype(x.dtype)
                ks_t = jnp.moveaxis(c["ks"][..., 0], 1, 2)  # [B, n_kv, M]
                vs_t = jnp.moveaxis(c["vs"][..., 0], 1, 2)
            else:
                kd, vd = c["k"], c["v"]
            # grouped-query attention against the buffer without
            # expanding the cache to n_heads: group axis g = kv head,
            # r = queries per group
            qg = q.reshape(B, T, nkv, reps, hd)
            scores = jnp.einsum("btgrd,bmgd->bgrtm", qg,
                                kd).astype(jnp.float32)
            if int8_cache:
                scores = scores * ks_t[:, :, None, None, :]
            scores = scores * (hd ** -0.5)
            scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1)
            if int8_cache:
                probs = probs * vs_t[:, :, None, None, :]
            probs = probs.astype(x.dtype)
            attn = jnp.einsum("bgrtm,bmgd->btgrd", probs, vd)
            attn_flat = attn.reshape(B, T, nh * hd)
        x = x + _matmul(attn_flat, lp["wo"])
        x, _aux = _ffn_block(x, lp, cfg)  # aux only matters in training
        return x, c

    cache_kv = {n: b for n, b in cache.items() if n != "pos"}
    x, new_cache = lax.scan(layer, x, (params["layers"], cache_kv))
    if rolling:
        new_cache["pos"] = new_pos
    x = _rmsnorm(x, params["final_norm"])
    logits = _matmul(x, params["lm_head"]).astype(jnp.float32)
    return logits, new_cache


def greedy_decode_kv(params: dict, prompt: jax.Array, steps: int,
                     cfg: ModelConfig, rolling: bool = False) -> jax.Array:
    """KV-cached greedy decoding: one prefill over the prompt, then one
    single-token forward_cached per generated token. Token-for-token
    equivalent to :func:`greedy_decode` at ~S x lower decode-step FLOPs —
    for the full-precision cache. ``kv_cache_dtype="int8"`` trades exact
    equivalence for ~2x less cache residency/traffic: logits move ~1% of
    their range, which can flip near-tie argmaxes (and on an UNTRAINED
    model, most argmaxes are near ties — see the int8 cache tests).

    MoE caveat: capacity routing couples tokens within a forward call (they
    compete for expert slots), and the cache-free path re-routes the whole
    zero-padded buffer each step. The two decoders are therefore only
    guaranteed identical when capacity never binds —
    ``cfg.moe_capacity_factor >= n_experts / top_k`` makes every expert big
    enough for all tokens (the shipped MoE presets satisfy this). Tightly
    capacity-bound serving should use this KV path only.

    ``rolling=True`` (requires ``cfg.attn_window``) serves from a ring
    buffer of ``2 x attn_window`` slots (capped at the sequence length):
    cache memory and per-step cost stop growing with generation length.
    The FULL prompt is prefilled in window-sized chunks — skipping early
    prompt tokens would be wrong even though the window hides them from
    the final position directly, because the attention receptive field
    grows by ``window`` per LAYER (position p's layer-2 state depends on
    layer-1 states at p-W+1.., which depend on keys back to p-2(W-1)).
    The ring discards old KEYS, never old computation; 2W slots keep
    every in-chunk query's W-1 older keys alive during the chunk's own
    writes.
    """
    B, S = prompt.shape
    total = S + steps
    buf = jnp.zeros((B, total), jnp.int32).at[:, :S].set(prompt)
    if steps <= 0:
        return buf
    if rolling:
        assert cfg.attn_window is not None, \
            "rolling decode requires cfg.attn_window"
        W = cfg.attn_window
        # ring of 2W (chunked-prefill retention), capped at total for
        # short runs — but never below W itself, which init rejects
        # (a sub-window ring would let overwrites hide visible keys)
        cache = init_kv_cache(cfg, B, max(min(2 * W, total), W),
                              rolling=True)
        logits = None
        for off in range(0, S, W):  # python loop: chunks are static
            logits, cache = forward_cached(
                params, prompt[:, off:off + W], cache, off, cfg)
    else:
        cache = init_kv_cache(cfg, B, total)
        logits, cache = forward_cached(params, prompt, cache, 0, cfg,
                                       prefill_from_zero=True)
    tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)   # [B]
    buf = buf.at[:, S].set(tok)

    # steps-1 single-token forwards: iteration i consumes the token at
    # position S+i-1 and emits the one at S+i (no trailing wasted step)
    def body(i, carry):
        buf, cache, tok = carry
        logits, cache = forward_cached(params, tok[:, None], cache,
                                       S + i - 1, cfg)
        tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        buf = lax.dynamic_update_slice(buf, tok[:, None], (0, S + i))
        return buf, cache, tok

    buf, _, _ = lax.fori_loop(jnp.int32(1), jnp.int32(steps), body,
                              (buf, cache, tok))
    return buf


# -- greedy decode (cache-free reference) -------------------------------------

def greedy_decode(params: dict, prompt: jax.Array, steps: int,
                  cfg: ModelConfig) -> jax.Array:
    """Fixed-shape greedy decoding WITHOUT a KV cache: the prompt buffer is
    extended by ``steps`` positions and filled one token per iteration via
    ``lax.fori_loop``, recomputing the prefix each step. Kept as the
    behavioral spec for :func:`greedy_decode_kv` (and for tiny smoke runs
    where the cache isn't worth its HBM).
    """
    B, S = prompt.shape
    total = S + steps
    buf = jnp.zeros((B, total), jnp.int32).at[:, :S].set(prompt)

    def body(i, buf):
        logits = forward(params, buf, cfg)  # [B, total, vocab]
        nxt = jnp.argmax(logits, axis=-1)   # [B, total]
        tok = jnp.take_along_axis(nxt, (S + i - 1)[None, None], axis=1)
        return lax.dynamic_update_slice(buf, tok.astype(jnp.int32),
                                        (0, S + i))

    return lax.fori_loop(jnp.int32(0), jnp.int32(steps), body, buf)
