"""Binpack-demo tenant (samples/1-4): the reference's "gpu-player" analogue.

The reference's player just echoes its injected env vars
(samples/docker/run.sh:3-6). This one also *runs*: it applies the HBM
gating, brings up JAX on its granted chips, and loops a small llama-mini
forward pass so co-tenants demonstrably share a chip.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tpushare-player")
    ap.add_argument("--preset", default="llama-tiny")
    ap.add_argument("--steps", type=int, default=0,
                    help="forward/train passes to run (0 = run forever)")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--mode", choices=["forward", "train"],
                    default="forward",
                    help="train = full fwd+bwd+adamw step (what a gang "
                         "member runs; samples/6-gang.yaml)")
    ap.add_argument("--attn", choices=["einsum", "flash"],
                    default="einsum")
    ap.add_argument("--sp", choices=["none", "ring"], default="none",
                    help="sequence-parallel attention over the local "
                         "devices (ring = GQA-native ring attention)")
    # Multi-host gang members: each pod is one JAX process of the gang's
    # shared mesh. jax.distributed.initialize is driven entirely by env
    # (set by the launcher/JobSet): COORDINATOR_ADDRESS, NUM_PROCESSES,
    # PROCESS_ID — absent env means single-process (every test/dev run).
    ap.add_argument("--multihost", action="store_true",
                    help="call jax.distributed.initialize() from the "
                         "standard env (COORDINATOR_ADDRESS, "
                         "NUM_PROCESSES, PROCESS_ID) before device init")
    ap.add_argument("--ckpt-dir", default=None,
                    help="train mode: checkpoint/resume directory — on "
                         "start the latest step there is restored (a "
                         "preempted-and-replaced gang member continues "
                         "instead of restarting), and every --ckpt-every "
                         "steps the state is saved durably")
    ap.add_argument("--ckpt-every", type=int, default=50)
    args = ap.parse_args(argv)

    if args.ckpt_dir is not None and args.mode != "train":
        # only train mode checkpoints; a user passing --ckpt-dir with
        # forward (or --sp ring) would silently get no durable resume
        # and discover it after an eviction
        ap.error("--ckpt-dir requires --mode train (forward and "
                 "--sp ring modes do not checkpoint)")

    from tpushare.contract import constants as c
    from tpushare.workloads.hbm import apply_hbm_gating
    applied = apply_hbm_gating()

    # echo the contract env like the reference player (run.sh:3-6)
    for var in (c.ENV_VISIBLE_CHIPS, c.ENV_HBM_LIMIT, c.ENV_HBM_CHIP_TOTAL,
                c.ENV_MEM_FRACTION):
        print(f"{var}={os.environ.get(var, '<unset>')}", flush=True)
    if applied:
        print(f"gating applied: {applied}", flush=True)

    import jax

    if args.multihost:
        # one process per gang member; the standard JAX env contract
        # (GKE/JobSet set these; jax.distributed reads them when called
        # with no arguments)
        jax.distributed.initialize()
        print(f"multihost: process {jax.process_index()} of "
              f"{jax.process_count()}", flush=True)

    import dataclasses

    import jax.numpy as jnp
    from tpushare.workloads.model import (PRESETS, forward, init_params,
                                          make_train_step)

    import numpy as np

    # preset name selects the workload family; this block is the ONE
    # family dispatch site (mirroring checkpoint._family): it fixes the
    # config, init fn, train-step factory, forward fn, and batch shape
    # together so they can never pair across families. llama presets
    # speak tokens, vit presets speak images (forward/train only — the
    # ring long-context mode is a llama-attention op); vit stays a lazy
    # import for llama-only runs.
    vit = args.preset not in PRESETS
    if vit:
        from tpushare.workloads.vit import (
            PRESETS_VIT, init_vit_params, make_vit_train_step,
            vit_forward)
        if args.preset not in PRESETS_VIT:
            ap.error(f"unknown preset {args.preset!r}")
        if args.sp == "ring":
            ap.error("--sp ring is a llama-attention mode; vit presets "
                     "run --mode forward/train")
        cfg = dataclasses.replace(PRESETS_VIT[args.preset],
                                  attn=args.attn)
        init_fn, make_train = init_vit_params, make_vit_train_step
        fwd_fn = lambda p, x: vit_forward(p, x, cfg)  # noqa: E731
        batch_np = (np.zeros((args.batch, cfg.image, cfg.image,
                              cfg.channels), np.float32),
                    np.zeros((args.batch,), np.int32))
    else:
        cfg = dataclasses.replace(PRESETS[args.preset], attn=args.attn)
        init_fn, make_train = init_params, make_train_step
        fwd_fn = lambda p, t: forward(p, t, cfg)  # noqa: E731
        batch_np = (np.zeros((args.batch, args.seq), np.int32),)

    if args.sp == "ring":
        if args.mode == "train":
            ap.error("--sp ring runs the ring-attention loop (the "
                     "long-context hot op); it does not train the "
                     "model — drop --mode train or --sp ring")
        # long-context mode: the hot op is ring attention over the
        # sequence-parallel mesh (all visible devices; across gang
        # members when --multihost made them one process group)
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        from tpushare.workloads.ringattention import ring_attention
        devs = jax.devices()
        n = len(devs)
        # ring needs S divisible by the sp size; round UP to a
        # 128-aligned per-device chunk so any --seq works
        chunk = -(-max(args.seq, 128 * n) // (128 * n)) * 128
        S = chunk * n
        hd = cfg.head_dim
        if jax.process_count() > 1:
            # multi-controller: each process holds only ITS slice of
            # the sequence axis — build the global arrays from
            # process-local shards (a host-local full array cannot be
            # fed to a jit spanning other processes' devices)
            mesh = Mesh(np.asarray(devs).reshape(n), ("sp",))
            spec = PartitionSpec(None, None, "sp", None)
            sharding = NamedSharding(mesh, spec)
            rng = np.random.default_rng(jax.process_index())
            local_S = S // jax.process_count()

            def make(heads):
                local = rng.standard_normal(
                    (args.batch, heads, local_S, hd), dtype=np.float32)
                return jax.make_array_from_process_local_data(
                    sharding, local.astype(jnp.bfloat16))

            q, k, v = (make(cfg.n_heads), make(cfg.n_kv_heads),
                       make(cfg.n_kv_heads))
        else:
            mesh = Mesh(devs, ("sp",))
            q = jax.random.normal(jax.random.key(1),
                                  (args.batch, cfg.n_heads, S, hd),
                                  jnp.bfloat16)
            k = jax.random.normal(jax.random.key(2),
                                  (args.batch, cfg.n_kv_heads, S, hd),
                                  jnp.bfloat16)
            v = jax.random.normal(jax.random.key(3), k.shape,
                                  jnp.bfloat16)
        ring_jit = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))

        def run_once():
            return ring_jit(q, k, v)

        unit = f"ring/s (S={S} over {n} devices)"
    elif args.mode == "train":
        tx, train_step = make_train(cfg)
        batch = tuple(jnp.asarray(b) for b in batch_np)
        ckpt = None
        trained = 0
        if args.ckpt_dir:
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            from tpushare.workloads.checkpoint import TrainCheckpointer
            # checkpointing needs GLOBAL arrays: under a multi-process
            # gang every member saves into the same directory, which is
            # only coherent when the state is one sharded global pytree
            # (each process then writes exactly its own shards). Dense
            # presets shard megatron-style over "tp" across the whole
            # gang; MoE shards over "ep", which this wiring doesn't
            # build — refuse rather than corrupt a shared directory.
            if getattr(cfg, "moe_experts", 0):
                raise SystemExit(
                    "--ckpt-dir train mode supports dense presets; MoE "
                    "state shards over 'ep' (use TrainCheckpointer with "
                    "your own mesh)")
            mesh = Mesh(np.array(jax.devices()).reshape(1, -1),
                        ("dp", "tp"))
            ckpt = TrainCheckpointer(args.ckpt_dir)
            params, opt_state, trained = ckpt.resume_or_init(
                cfg, tx, jax.random.key(0), mesh=mesh)
            if trained:
                print(f"resumed from step {trained} ({args.ckpt_dir})",
                      flush=True)
            if jax.process_count() > 1:
                # every process feeds the same batch; lift it to
                # replicated global arrays so the pjit accepts it
                batch = tuple(jax.make_array_from_process_local_data(
                    NamedSharding(mesh, P()), b) for b in batch_np)
        else:
            params = init_fn(cfg, jax.random.key(0))
            opt_state = tx.init(params)
        step_jit = jax.jit(train_step)

        def run_once():
            nonlocal params, opt_state, trained
            params, opt_state, loss = step_jit(params, opt_state,
                                               *batch)
            trained += 1
            if ckpt is not None:
                ckpt.maybe_save(trained, params, opt_state, cfg,
                                every=args.ckpt_every)
            return loss

        unit = "train/s"
    else:
        params = init_fn(cfg, jax.random.key(0))
        data = jnp.asarray(batch_np[0])
        fwd_jit = jax.jit(fwd_fn)

        def run_once():
            return fwd_jit(params, data)

        unit = "fwd/s"

    # --steps is a TOTAL budget: a resumed trainer finishes the REMAINDER
    # (resume at 900 of --steps 1000 runs 100 more, not 1000 — the
    # userguide's "costs at most --ckpt-every steps" promise)
    done = resumed = trained if args.mode == "train" else 0
    t0 = time.perf_counter()
    while args.steps == 0 or done < args.steps:
        jax.block_until_ready(run_once())
        done += 1
        if done % 50 == 0 or done == args.steps:
            dt = time.perf_counter() - t0
            print(f"step {done}: {(done - resumed) / dt:.1f} {unit} on "
                  f"{jax.devices()[0].platform}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
