"""Binpack-demo tenant (samples/1-4): the reference's "gpu-player" analogue.

The reference's player just echoes its injected env vars
(samples/docker/run.sh:3-6). This one also *runs*: it applies the HBM
gating, brings up JAX on its granted chips, and loops a small llama-mini
forward pass so co-tenants demonstrably share a chip.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tpushare-player")
    ap.add_argument("--preset", default="llama-tiny")
    ap.add_argument("--steps", type=int, default=0,
                    help="forward passes to run (0 = run forever)")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args(argv)

    from tpushare.contract import constants as c
    from tpushare.workloads.hbm import apply_hbm_gating
    applied = apply_hbm_gating()

    # echo the contract env like the reference player (run.sh:3-6)
    for var in (c.ENV_VISIBLE_CHIPS, c.ENV_HBM_LIMIT, c.ENV_HBM_CHIP_TOTAL,
                c.ENV_MEM_FRACTION):
        print(f"{var}={os.environ.get(var, '<unset>')}", flush=True)
    if applied:
        print(f"gating applied: {applied}", flush=True)

    import jax
    import jax.numpy as jnp
    from tpushare.workloads.model import PRESETS, forward, init_params

    cfg = PRESETS[args.preset]
    params = init_params(cfg, jax.random.key(0))
    step = jax.jit(lambda p, t: forward(p, t, cfg))
    tokens = jnp.zeros((args.batch, args.seq), jnp.int32)

    n = 0
    t0 = time.perf_counter()
    while args.steps == 0 or n < args.steps:
        step(params, tokens).block_until_ready()
        n += 1
        if n % 50 == 0 or n == args.steps:
            dt = time.perf_counter() - t0
            print(f"step {n}: {n / dt:.1f} fwd/s on "
                  f"{jax.devices()[0].platform}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
