"""Co-located int8 serving replica (samples/5-serving.yaml, BASELINE #5).

Runs a llama-style model (int8 weights by default) over the granted chips
with a dp x tp mesh, serving greedy completions over a tiny stdlib HTTP
endpoint (POST /generate {"tokens": [[...]], "steps": N}).
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import itertools


def compose_mesh_devices(devices, box_label, axes_shape):
    """Order ``devices`` into a physical-adjacency-aligned device array
    of ``axes_shape`` (e.g. ``(1, tp)`` or ``(1, tp, ep)``).

    ``devices`` is the JAX device list, which on a tpushare grant is
    ``TPU_VISIBLE_CHIPS`` order — ascending chip ids, i.e. row-major
    over the granted box the device plugin reports via
    ``TPUSHARE_PLACEMENT_BOX`` (``box_label``, \"2x2\" form). When the
    box's non-trivial dims match the non-trivial logical axes (the
    mesh-shape annotation made the extender prefer exactly such a box),
    the devices are reshaped over the box and the box axes transposed
    onto the logical axes — each logical axis then walks a physical
    mesh line, so collectives over it ride contiguous ICI links. Any
    mismatch (no label, scatter grant, incongruent shapes) degrades to
    the plain ``reshape`` order serve always used.

    Pure function of its inputs (unit-tested without a TPU); the
    returned nested list feeds ``np.array(...)`` / ``Mesh`` unchanged.
    """
    n = 1
    for d in axes_shape:
        n *= d
    devs = list(devices[:n])
    if len(devs) < n or not box_label:
        return devs if len(axes_shape) == 1 else _reshape(devs, axes_shape)
    try:
        box = tuple(int(p) for p in str(box_label).lower().split("x"))
    except ValueError:
        return _reshape(devs, axes_shape)
    vol = 1
    for d in box:
        vol *= d
    nt_box = [d for d in box if d > 1]
    nt_axes = [d for d in axes_shape if d > 1]
    if vol != n or any(d <= 0 for d in box):
        return _reshape(devs, axes_shape)
    strides = []
    acc = 1
    for d in reversed(nt_box):
        strides.append(acc)
        acc *= d
    strides = list(reversed(strides))
    if sorted(nt_box) != sorted(nt_axes):
        if len(nt_axes) == 1 and len(nt_box) > 1:
            # one logical axis over a multi-axis box (plain tp over a
            # 2x2 grant): walk the box boustrophedon — consecutive ring
            # members are then always 1 ICI hop apart, where row-major
            # pays a full edge length at every row boundary
            ordered = []
            for c in itertools.product(*[range(d) for d in nt_box]):
                eff = []
                for ax, v in enumerate(c):
                    if ax and sum(eff) % 2:
                        v = nt_box[ax] - 1 - v
                    eff.append(v)
                ordered.append(devs[sum(v * s
                                        for v, s in zip(eff, strides))])
            return _reshape(ordered, axes_shape)
        return _reshape(devs, axes_shape)
    # congruent: index the flat (row-major over box) list by box coords,
    # read it out with the box axes permuted onto the logical axes order
    for perm in itertools.permutations(range(len(nt_box))):
        if [nt_box[p] for p in perm] == nt_axes:
            ordered = [
                devs[sum(c[i] * strides[perm[i]]
                         for i in range(len(perm)))]
                for c in itertools.product(*[range(d) for d in nt_axes])]
            return _reshape(ordered, axes_shape)
    return _reshape(devs, axes_shape)


def _reshape(flat, shape):
    """Row-major nested-list reshape (np.array(out).shape == shape)."""
    if len(shape) == 1:
        return list(flat)
    sub = 1
    for d in shape[1:]:
        sub *= d
    return [_reshape(flat[i * sub:(i + 1) * sub], shape[1:])
            for i in range(shape[0])]


class _EngineFrontend:
    """Queue + single engine thread between HTTP handlers and a
    DecodeEngine. All JAX calls happen on the engine thread (the
    handlers only enqueue and wait), so slot admission, prefill, and
    quanta never race. Admission is work-conserving: every quantum
    boundary first fills free slots from the queue, then advances."""

    def __init__(self, engine, tokens_counter=None):
        self._engine = engine
        self._tokens = tokens_counter
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        # live-migration pause (defrag checkpoint->evict->restore): the
        # mover parks the loop AT A QUANTUM BOUNDARY so KV state is
        # consistent when the checkpoint reads it; requests keep queuing
        # while paused and drain on resume
        self._paused = threading.Event()
        self._quiesced = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="decode-engine")

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    @property
    def engine(self):
        return self._engine

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()

    def join(self, timeout: float | None = None):
        """Wait for the engine thread to finish its in-flight quantum
        and observe the stop flag (bounded; the thread is a daemon, so
        a stuck dispatch cannot block process exit)."""
        if self._thread.is_alive():
            self._thread.join(timeout)

    def pause(self, timeout: float = 5.0) -> bool:
        """Park the engine loop at the next quantum boundary; returns
        once it is quiescent (no quantum in flight, KV state stable —
        safe to checkpoint) or False on timeout. Idempotent; requests
        submitted while paused queue up and are admitted on resume."""
        self._paused.set()
        if not self._thread.is_alive():
            return True  # nothing running: trivially quiescent
        return self._quiesced.wait(timeout)

    def resume(self) -> None:
        """Lift a pause(); the loop re-admits and advances immediately."""
        self._paused.clear()

    @property
    def paused(self) -> bool:
        return self._paused.is_set()

    def generate(self, prompt: list[int], max_new: int,
                 timeout: float = 300.0,
                 sampling: dict | None = None) -> list[int]:
        """Called from handler threads; blocks until the request's
        generation completes. Raises ValueError for requests the engine
        cannot ever place (oversized prompt etc.)."""
        return self.generate_many([prompt], max_new, timeout,
                                  sampling)[0]

    def generate_stream(self, prompt: list[int], max_new: int,
                        timeout: float = 300.0,
                        sampling: dict | None = None):
        """Yields lists of newly generated tokens as decode quanta
        complete (the first yield is the prefill's token). Terminates
        when the request finishes; raises ValueError on rejection. The
        per-yield timeout bounds ENGINE stall, not total generation."""
        stream_q: queue.Queue = queue.Queue()
        done = threading.Event()
        box: dict = {"stream": stream_q}
        self._submit((list(prompt), max_new, sampling or {}, done, box))
        while True:
            try:
                kind, payload = stream_q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError("stream stalled") from None
            if kind == "delta":
                yield payload
            elif kind == "error":
                raise ValueError(payload)
            else:  # "done"
                return

    def generate_many(self, prompts: list[list[int]], max_new: int,
                      timeout: float = 300.0,
                      sampling: dict | None = None) -> list[list[int]]:
        """Enqueue ALL prompts before waiting on any — co-resident
        decoding is the engine's whole point; a sequential
        submit-and-wait would serialize the batch."""
        pairs = [(threading.Event(), {}) for _ in prompts]
        for p, (done, box) in zip(prompts, pairs):
            self._submit((list(p), max_new, sampling or {}, done, box))
        out = []
        for done, box in pairs:
            if not done.wait(timeout):
                raise TimeoutError("generation timed out")
            if "error" in box:
                raise ValueError(box["error"])
            out.append(box["tokens"])
        return out

    def _submit(self, item) -> None:
        """Enqueue one request, failing fast when the engine is stopping.

        Checked on BOTH sides of the put: the engine thread observes the
        stop flag, drains the queue once, and exits — a request enqueued
        after that drain would otherwise sit unanswered until the
        client's timeout. Rejecting after the put as well closes the
        check-then-enqueue race (the drain and this rejection write the
        same terminal state, so double delivery is harmless)."""
        done, box = item[3], item[4]
        if self._stop.is_set():
            self._reject(done, box)
            return
        self._q.put(item)
        if self._stop.is_set():
            self._reject(done, box)

    @staticmethod
    def _reject(done, box) -> None:
        box["error"] = "server shutting down"
        if "stream" in box:
            box["stream"].put(("error", box["error"]))
        done.set()

    def _loop(self):
        inflight: dict[int, tuple] = {}  # rid -> (done, box)
        while not self._stop.is_set():
            if self._paused.is_set():
                # quiescent: the previous quantum fully completed, so
                # the engine's KV/slot state is a consistent snapshot
                # for the duration of the pause
                self._quiesced.set()
                self._stop.wait(0.005)
                continue
            self._quiesced.clear()
            # admit as many queued requests as there are free slots;
            # park until work arrives when fully idle
            while self._engine.free_slots:
                try:
                    item = self._q.get(block=not (inflight or
                                                  self._engine.resident),
                                       timeout=0.5)
                except queue.Empty:
                    break
                prompt, max_new, sampling, done, box = item
                try:
                    rid = self._engine.submit(prompt, max_new,
                                              **sampling)
                except Exception as e:  # noqa: BLE001 — an uncaught
                    # exception would kill this daemon thread silently
                    # and hang every later request at its timeout
                    box["error"] = f"{type(e).__name__}: {e}"
                    if "stream" in box:
                        box["stream"].put(("error", box["error"]))
                    done.set()
                    continue
                if "stream" in box:
                    # the prefill already produced the first token
                    box["stream"].put(
                        ("delta", self._engine.peek_tokens(rid) or []))
                inflight[rid] = (done, box)
            if not inflight:
                continue
            try:
                finished = self._engine.run_quantum()
            except Exception as e:  # noqa: BLE001 — same thread-death
                # hazard; fail the resident requests loudly and keep
                # serving (their slots stay burned: engine state after a
                # mid-quantum fault is unknown)
                print(f"decode engine quantum failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr,
                      flush=True)
                for done, box in inflight.values():
                    box["error"] = f"engine failure: {e}"
                    if "stream" in box:
                        box["stream"].put(("error", box["error"]))
                    done.set()
                inflight.clear()
                continue
            for rid, delta in self._engine.last_quantum_tokens.items():
                done_box = inflight.get(rid)
                if done_box is not None and "stream" in done_box[1]:
                    done_box[1]["stream"].put(("delta", delta))
            for rid, tokens in finished.items():
                done, box = inflight.pop(rid)
                box["tokens"] = tokens
                if self._tokens is not None:
                    self._tokens.inc(len(tokens))
                if "stream" in box:
                    box["stream"].put(("done", tokens))
                done.set()
        # stop flag observed: wake every still-blocked client with a
        # terminal signal — without this, handlers parked in
        # generate/generate_stream would sleep to their timeout and the
        # process exit would reset their connections mid-wait
        while True:
            try:
                _p, _m, _s, done, box = self._q.get_nowait()
            except queue.Empty:
                break
            box["error"] = "server shutting down"
            if "stream" in box:
                box["stream"].put(("error", box["error"]))
            done.set()
        for done, box in inflight.values():
            box["error"] = "server shutting down (request interrupted)"
            if "stream" in box:
                box["stream"].put(("error", box["error"]))
            done.set()


# -- live-migration seam (defrag/migration.py) --------------------------------
# Process-local registry: workload name -> serve frontend. A serving
# replica registers its engine frontend at startup; a co-resident
# migrator resolves its victim's loop here to park it at a quantum
# boundary before checkpointing. Out-of-process deployments supply
# their own frontend_for seam instead (the Migrator is duck-typed).
_FRONTENDS: dict[str, _EngineFrontend] = {}
_FRONTENDS_LOCK = threading.Lock()


def register_frontend(name: str, frontend: _EngineFrontend) -> None:
    with _FRONTENDS_LOCK:
        _FRONTENDS[name] = frontend


def unregister_frontend(name: str) -> None:
    with _FRONTENDS_LOCK:
        _FRONTENDS.pop(name, None)


def frontend_for(pod) -> _EngineFrontend | None:
    """The registered serve frontend for a victim pod (dict or name),
    or None — a victim with no serve loop just checkpoints."""
    name = pod if isinstance(pod, str) else \
        ((pod.get("metadata") or {}).get("name") or "")
    with _FRONTENDS_LOCK:
        return _FRONTENDS.get(name)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tpushare-serve")
    ap.add_argument("--preset", default="llama-tiny")
    ap.add_argument("--quant", choices=["none", "int8"], default="int8")
    ap.add_argument("--attn", choices=["einsum", "flash"], default="einsum",
                    help="flash = Pallas fused-attention kernel (TPU)")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--no-kv-cache", action="store_true",
                    help="use the cache-free reference decode path")
    ap.add_argument("--kv-cache-dtype", choices=["model", "int8"],
                    default="model",
                    help="int8 = quantized KV cache (~2x less cache HBM "
                         "residency per replica on a shared chip)")
    ap.add_argument("--attn-window", type=int, default=0,
                    help="sliding-window attention span (0 = full causal)")
    ap.add_argument("--rolling-kv", action="store_true",
                    help="ring-buffer KV cache sized by --attn-window: "
                         "O(window) cache memory regardless of "
                         "generation length (requires --attn-window)")
    # (validated below once argparse has run: ap.error gives a usage
    # message instead of a bare AssertionError from ModelConfig)
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel size (0 = all local devices)")
    ap.add_argument("--engine", action="store_true",
                    help="continuous batching: requests join a fixed "
                         "slot pool mid-flight instead of decoding one "
                         "batch start-to-finish (workloads/engine.py)")
    ap.add_argument("--engine-slots", type=int, default=8)
    ap.add_argument("--engine-max-len", type=int, default=512,
                    help="per-slot KV budget: prompt + generation must "
                         "fit (static shapes — allocated once)")
    ap.add_argument("--engine-quantum", type=int, default=8,
                    help="decode steps per host sync; arrivals join at "
                         "quantum boundaries")
    ap.add_argument("--eos-id", type=int, default=-1,
                    help="engine mode: token id that ends a generation "
                         "early (-1 = generate to budget)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="engine mode: 0 = greedy (default); >0 samples "
                         "(reproducibly — keyed by request + position)")
    ap.add_argument("--top-k", type=int, default=0,
                    help="engine mode: restrict sampling to the k "
                         "highest logits (0 = full vocab)")
    ap.add_argument("--top-p", type=float, default=1.0,
                    help="engine mode: nucleus sampling — keep the "
                         "smallest probability mass >= p (1.0 = off; "
                         "composes with --top-k)")
    ap.add_argument("--sample-seed", type=int, default=0)
    ap.add_argument("--per-request-sampling", action="store_true",
                    help="engine mode: let each request override "
                         "temperature/top_p in the POST body (costs a "
                         "per-slot vocab sort every decode step, so "
                         "greedy-only replicas should leave it off)")
    args = ap.parse_args(argv)

    from tpushare.workloads.hbm import apply_hbm_gating
    apply_hbm_gating()

    import jax

    from tpushare.workloads import honor_cpu_request
    honor_cpu_request()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P
    from tpushare.workloads.model import (
        PRESETS, forward, greedy_decode, greedy_decode_kv, init_params,
        param_specs, quant_specs, quantize_int8)

    import dataclasses

    import numpy as np

    if args.attn_window < 0:
        ap.error(f"--attn-window {args.attn_window} must be >= 0")
    if args.rolling_kv and not args.attn_window:
        ap.error("--rolling-kv requires --attn-window")
    if args.rolling_kv and args.no_kv_cache:
        ap.error("--rolling-kv conflicts with --no-kv-cache")
    cfg = dataclasses.replace(
        PRESETS[args.preset], attn=args.attn,
        kv_cache_dtype=args.kv_cache_dtype,
        attn_window=args.attn_window or None).validate()
    devices = jax.devices()
    tp = args.tp or len(devices)
    # the granted box's geometry, when the device plugin injected it:
    # lets the logical mesh axes walk physical ICI lines instead of
    # trusting device enumeration order (absent = old behavior)
    import os as _os

    from tpushare import contract as _contract
    box_label = _os.environ.get(_contract.ENV_PLACEMENT_BOX)
    if cfg.moe_experts > 0:
        # MoE presets shard experts over "ep": give that axis the devices
        # (largest divisor of tp that divides n_experts) and the rest to tp.
        ep = 1
        for cand in range(min(tp, cfg.moe_experts), 0, -1):
            if tp % cand == 0 and cfg.moe_experts % cand == 0:
                ep = cand
                break
        tp //= ep
        mesh = Mesh(np.array(compose_mesh_devices(
            devices, box_label, (1, tp, ep))), ("dp", "tp", "ep"))
    else:
        mesh = Mesh(np.array(compose_mesh_devices(
            devices, box_label, (1, tp))), ("dp", "tp"))

    params = init_params(cfg, jax.random.key(0))
    specs = param_specs(cfg)
    if args.quant == "int8":
        params = quantize_int8(params)
        specs = quant_specs(specs)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    params = jax.device_put(params, shardings)

    if args.no_kv_cache and args.kv_cache_dtype == "int8":
        # same silent-conflict treatment as --attn flash below: the
        # cache-free decode allocates no KV cache, so the operator's
        # expected ~2x residency saving would silently not exist
        print("note: --kv-cache-dtype int8 has no effect with "
              "--no-kv-cache (the reference decode path allocates no KV "
              "cache)", flush=True)
    if args.attn == "flash" and not args.no_kv_cache:
        # decode STEPS attend single-token queries with the einsum core
        # either way; what flash changes on the KV-cached path is the
        # PREFILL (forward_cached's prefill-from-zero runs the fused
        # kernel over the prompt chunk — the time-to-first-token cost).
        # Rolling-ring prefills chunk mid-stream and keep einsum. The
        # engine's bucketed prefill honors the same config
        # (tests/test_engine.py::test_flash_prefill_config_parity).
        which = ("prefill only (ring chunks use einsum)"
                 if args.rolling_kv else "prefill (time-to-first-token)")
        print(f"note: --attn flash accelerates the {which}; decode "
              "steps use the einsum core on any KV-cached path",
              flush=True)
    if args.no_kv_cache:
        decode_fn = lambda p, t, n: greedy_decode(p, t, n, cfg)
    else:
        decode_fn = lambda p, t, n: greedy_decode_kv(
            p, t, n, cfg, rolling=args.rolling_kv)
    decode = jax.jit(decode_fn, static_argnums=2)

    # observability: the serving tenant exposes the same wire format the
    # extender does (tpushare/metrics.py) — replicas-per-chip decisions
    # need tokens/s and slot pressure, not just extender-side placement
    from tpushare.metrics import LATENCY_BUCKETS, Registry
    registry = Registry()
    m_requests = registry.counter(
        "tpushare_serve_requests_total",
        "generate requests received (incl. ones answered 400)")
    m_errors = registry.counter(
        "tpushare_serve_request_errors_total",
        "generate requests answered with an error")
    m_tokens = registry.counter(
        "tpushare_serve_tokens_generated_total",
        "tokens generated (excludes echoed prompt tokens)")
    m_latency = registry.histogram(
        "tpushare_serve_generate_seconds",
        "wall time per generate request",
        tuple(b * 100 for b in LATENCY_BUCKETS))  # decode >> bind scales

    engine_front = None
    if args.engine:
        if args.no_kv_cache:
            ap.error("--engine requires a KV-cached path "
                     "(conflicts with --no-kv-cache)")
        if cfg.moe_experts:
            ap.error("--engine excludes MoE presets (capacity routing "
                     "couples slots)")
        if args.rolling_kv and args.engine_max_len < 2 * args.attn_window:
            ap.error(f"--engine --rolling-kv needs --engine-max-len >= "
                     f"2*attn-window ({2 * args.attn_window}): the ring "
                     "must retain chunked-prefill keys")
        from tpushare.workloads.engine import DecodeEngine
        eos = None if args.eos_id < 0 else args.eos_id
        engine_front = _EngineFrontend(
            DecodeEngine(params, cfg, args.engine_slots,
                         args.engine_max_len,
                         quantum=args.engine_quantum, eos_id=eos,
                         temperature=args.temperature,
                         top_k=args.top_k, top_p=args.top_p,
                         seed=args.sample_seed,
                         per_request_sampling=args.per_request_sampling,
                         rolling=args.rolling_kv),
            tokens_counter=m_tokens)
        engine_front.start()
        # visible to a co-resident live-migration session (POD_NAME is
        # the downward-API name under Kubernetes; fall back to preset)
        register_frontend(os.environ.get("POD_NAME") or args.preset,
                          engine_front)
        registry.gauge_func(
            "tpushare_serve_engine_slots",
            "decode-engine slot pool occupancy",
            lambda: [('{state="free"}',
                      float(engine_front.engine.free_slots)),
                     ('{state="resident"}',
                      float(engine_front.engine.resident))])
        registry.gauge_func(
            "tpushare_serve_engine_queue_depth",
            "requests waiting for a free slot",
            lambda: [("", float(engine_front.queue_depth))])

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def do_POST(self):
            if self.path != "/generate":
                self.send_error(404)
                return
            m_requests.inc()
            t_req = time.perf_counter()
            try:
                body = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))))
                steps = int(body.get("steps", 8))
                if steps < 1:
                    # the engine path rejects this in submit(); the
                    # plain path must too (a negative value would also
                    # drive the monotonic token counter backwards)
                    raise ValueError(f"steps {steps} must be >= 1")
                if body.get("stream") and engine_front is None:
                    raise ValueError("stream requires --engine")
                # per-request overrides (engine mode): the flags set
                # the defaults, the body can override sampling (needs
                # --per-request-sampling) and the stop token (any
                # engine replica)
                sampling = {k: float(body[k])
                            for k in ("temperature", "top_p")
                            if k in body}
                if "eos_id" in body:
                    sampling["eos_id"] = int(body["eos_id"])
                if sampling and engine_front is None:
                    raise ValueError(
                        "temperature/top_p/eos_id need --engine")
                if engine_front is not None and body.get("stream"):
                    prompts = body["tokens"]
                    if not (prompts and isinstance(prompts[0], int)):
                        raise ValueError(
                            "stream mode takes ONE flat prompt")
                    self._stream(list(prompts), steps, t_req,
                                 sampling)
                    return
                if engine_front is not None:
                    prompts = body["tokens"]
                    if prompts and isinstance(prompts[0], int):
                        prompts = [prompts]  # single sequence accepted
                    # response rows = prompt + generation, the same
                    # shape contract as the batch decode below
                    gen = engine_front.generate_many(
                        [list(p) for p in prompts], steps,
                        sampling=sampling)
                    rows = [list(p) + g for p, g in zip(prompts, gen)]
                    resp = json.dumps({"tokens": rows}).encode()
                else:
                    tokens = jnp.asarray(body["tokens"], jnp.int32)
                    out = decode(params, tokens, steps)
                    m_tokens.inc(out.shape[0] * steps)
                    resp = json.dumps({"tokens": out.tolist()}).encode()
                m_latency.observe(time.perf_counter() - t_req)
            except Exception as e:  # noqa: BLE001 — serving surface
                m_errors.inc()
                msg = json.dumps({"error": str(e)}).encode()
                try:
                    self.send_response(400)
                    self.send_header("Content-Length", str(len(msg)))
                    self.end_headers()
                    self.wfile.write(msg)
                except OSError:
                    pass  # client already gone
                return
            try:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(resp)))
                self.end_headers()
                self.wfile.write(resp)
            except OSError:
                # a client that hung up after generation succeeded is
                # not a serving error: the error counter feeds the
                # replicas-per-chip signal and must not count client
                # disconnects (the request is already in the latency
                # histogram as a success)
                pass

        def _stream(self, prompt, steps, t_req, sampling=None):
            """NDJSON token streaming: one {"delta": [...]} line per
            decode quantum as it lands, closed by {"done": true,
            "tokens": [prompt + generation]}. The body is delimited by
            connection close (no Content-Length) — curl -N or any
            line-reader consumes it incrementally.

            The status line is deferred until the FIRST event: a
            submit-time rejection (oversized prompt etc.) is always the
            first event available, so invalid requests get the same
            HTTP 400 as the non-streaming path instead of an error
            object inside a 200 body."""
            gen = engine_front.generate_stream(prompt, steps,
                                               sampling=sampling)
            events = iter(gen)
            first = next(events, None)  # ValueError/TimeoutError -> 400
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Connection", "close")
            self.end_headers()
            generated: list[int] = []
            try:
                deltas = ([] if first is None else [first])
                for delta in (d for src in (deltas, events)
                              for d in src):
                    generated.extend(delta)
                    self.wfile.write(
                        json.dumps({"delta": delta}).encode() + b"\n")
                    self.wfile.flush()
                m_latency.observe(time.perf_counter() - t_req)
                self.wfile.write(json.dumps(
                    {"done": True,
                     "tokens": list(prompt) + generated}).encode()
                    + b"\n")
            except (ValueError, TimeoutError) as e:
                # mid-stream engine failure: 200 already sent, append
                # the error event and close
                m_errors.inc()
                self.wfile.write(
                    json.dumps({"error": str(e)}).encode() + b"\n")
            except OSError:
                pass  # client hung up mid-stream; not a serving error

        def do_GET(self):
            if self.path == "/healthz":
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")
            elif self.path == "/metrics":
                body = registry.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

    httpd = ThreadingHTTPServer(("0.0.0.0", args.port), Handler)
    front = (f", engine slots={args.engine_slots} "
             f"quantum={args.engine_quantum}" if engine_front else "")
    print(f"tpushare-serve ready on :{httpd.server_address[1]} "
          f"(preset={args.preset}, quant={args.quant}, "
          f"mesh {'x'.join(f'{n}={s}' for n, s in zip(mesh.axis_names, mesh.devices.shape))}"
          f"{front})",
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if engine_front is not None:
            # drain at a quantum boundary: without this, SIGINT
            # abandons an in-flight quantum mid-dispatch and waiting
            # clients see connection resets instead of a clean stop
            engine_front.stop()
            engine_front.join(timeout=10.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
