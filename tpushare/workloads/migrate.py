"""Workload-side live-migration wiring (defrag/migration.py's seams).

The defrag executor's :class:`~tpushare.defrag.migration.Migrator` is
duck-typed so the scheduler layer never imports jax; this module is the
place the REAL workloads plug in:

- ``frontend_for`` comes from :mod:`tpushare.workloads.serve`'s
  process-local registry: a serving replica registers its engine
  frontend at startup, and the migration session parks it at a quantum
  boundary before the checkpoint reads state.
- ``checkpointer`` dispatches per victim through a process-local
  handler registry. A training workload registers a
  :class:`TrainStateHandler` (orbax-backed
  :class:`~tpushare.workloads.checkpoint.TrainCheckpointer` underneath
  — sharded save, cross-mesh restore); anything registered must expose
  ``save(pod, move)`` / ``restore(pod, move)``. Victims with no handler
  still get a durable MANIFEST (who moved where, when) under
  ``TPUSHARE_MIGRATE_CKPT_DIR`` so an operator can audit every move
  even for annotation-only workloads.

Everything jax-flavored is imported lazily: constructing the default
migrator in the extender process costs nothing and pulls in nothing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

_HANDLERS: dict[str, Any] = {}
_HANDLERS_LOCK = threading.Lock()


def _pod_name(pod: Any) -> str:
    if isinstance(pod, str):
        return pod
    return ((pod or {}).get("metadata") or {}).get("name") or ""


def register_checkpointer(name: str, handler: Any) -> None:
    """Register a per-workload checkpoint handler (``save(pod, move)``/
    ``restore(pod, move)``) under the workload's pod name."""
    with _HANDLERS_LOCK:
        _HANDLERS[name] = handler


def unregister_checkpointer(name: str) -> None:
    with _HANDLERS_LOCK:
        _HANDLERS.pop(name, None)


class WorkloadCheckpointer:
    """The Migrator's ``checkpointer`` seam: dispatch to the victim's
    registered handler, and (when a directory is configured) persist a
    per-move manifest so the move sequence is auditable after the
    fact. A handler failure propagates — the session aborts and the
    executor rolls the victim back; a manifest IO failure does too,
    because 'durable before evict' is the whole contract."""

    def __init__(self, directory: str | None = None) -> None:
        self._dir = directory

    def _manifest(self, phase: str, pod: Any, move: Any) -> None:
        if not self._dir:
            return
        os.makedirs(self._dir, exist_ok=True)
        name = _pod_name(pod) or "unknown"
        path = os.path.join(self._dir, f"{name}.migration.json")
        record = {"phase": phase, "pod": name,
                  "time_unix": round(time.time(), 3),
                  "move": move.to_dict() if hasattr(move, "to_dict")
                  else str(move)}
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(record, f, sort_keys=True)
        os.replace(tmp, path)  # atomic: a partial write is never visible

    def save(self, pod: Any, move: Any) -> None:
        with _HANDLERS_LOCK:
            handler = _HANDLERS.get(_pod_name(pod))
        if handler is not None:
            handler.save(pod, move)
        self._manifest("checkpointed", pod, move)

    def restore(self, pod: Any, move: Any) -> None:
        with _HANDLERS_LOCK:
            handler = _HANDLERS.get(_pod_name(pod))
        if handler is not None:
            handler.restore(pod, move)
        self._manifest("restored", pod, move)


class TrainStateHandler:
    """Adapter from a live training loop to the migration seam: the
    loop supplies ``state_fn() -> (step, params, opt_state, cfg)`` and
    ``tx`` (its optax transform), and save/restore delegate to the
    orbax-backed :class:`TrainCheckpointer` — sharded save, cross-mesh
    restore, so a re-placed gang resumes on a DIFFERENT slice shape.
    jax/orbax load on first construction, never at import."""

    def __init__(self, directory: str, state_fn, tx, mesh=None,
                 keep: int = 3) -> None:
        from tpushare.workloads.checkpoint import TrainCheckpointer
        self._ckpt = TrainCheckpointer(directory, keep=keep)
        self._state_fn = state_fn
        self._tx = tx
        self._mesh = mesh
        self._restored: Any = None

    @property
    def restored(self) -> Any:
        """The (step, params, opt_state) the last restore produced —
        the training loop picks it up when its pod re-enters the run."""
        return self._restored

    def save(self, pod: Any, move: Any) -> None:
        step, params, opt_state, cfg = self._state_fn()
        self._ckpt.save(step, params, opt_state, cfg)  # blocks: durable

    def restore(self, pod: Any, move: Any) -> None:
        _step, _params, _opt, cfg = self._state_fn()
        self._restored = self._ckpt.restore(cfg, self._tx,
                                            mesh=self._mesh)


def default_migrator():
    """The production Migrator: serve-registry frontends + the handler
    dispatch checkpointer (manifests under ``TPUSHARE_MIGRATE_CKPT_DIR``
    when set). Costs nothing until a move actually runs."""
    from tpushare.defrag.migration import Migrator
    from tpushare.workloads import serve
    return Migrator(
        checkpointer=WorkloadCheckpointer(
            os.environ.get("TPUSHARE_MIGRATE_CKPT_DIR")),
        frontend_for=serve.frontend_for)
