"""Vision Transformer encoder — the second workload family, TPU-first.

The reference schedules opaque GPU pods and ships no models at all; this
repo's workload families exist to prove the scheduler hosts REAL tenants
under fractional HBM grants. The llama family covers autoregressive
decoding; this one covers the encoder/vision shape of traffic (dense
non-causal attention, no KV cache, classification head) with the same
TPU-first discipline:

- **Patch embedding is a matmul, not a conv op**: a stride-p pxp conv
  over non-overlapping patches IS exactly reshape-to-patches @ W — so it
  is written that way and lands on the MXU as one [B*N, p*p*C] x
  [p*p*C, d] matmul with zero im2col overhead.
- **Stacked layers + ``lax.scan``**: one compiled pre-LN block body
  regardless of depth (same pattern as model.py).
- **Attention reuses the flash kernel** (``attn="flash"``,
  ``causal=False`` — the kernel's non-causal grid visits all blocks) or
  the einsum reference; MHA is the GQA contract's H_kv == H case.
- **bf16 matmuls, fp32 LayerNorm/softmax** accumulations.
- **dp x tp sharding** via the megatron layout: in-projections shard
  the head/hidden OUTPUT dim, out-projections the INPUT dim, one ICI
  all-reduce per block (after wo, after w2); batch shards over dp.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from tpushare.workloads.attention import attention_reference, flash_attention


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image: int = 224
    patch: int = 16
    channels: int = 3
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    classes: int = 1000
    dtype: Any = jnp.bfloat16
    attn: str = "einsum"  # or "flash" (Pallas kernel, causal=False)

    @property
    def n_patches(self) -> int:
        return (self.image // self.patch) ** 2

    @property
    def seq(self) -> int:
        return self.n_patches + 1  # + [CLS]

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def validate(self) -> "ViTConfig":
        assert self.image % self.patch == 0
        assert self.d_model % self.n_heads == 0
        assert self.attn in ("einsum", "flash")
        return self


PRESETS_VIT = {
    # ViT-B/16 geometry (the standard encoder serving/finetune tenant)
    "vit-b16": ViTConfig(),
    # small config for tests and CPU meshes
    "vit-tiny": ViTConfig(image=32, patch=8, d_model=64, n_layers=2,
                          n_heads=4, d_ff=128, classes=10),
}


def init_vit_params(cfg: ViTConfig, key: jax.Array) -> dict:
    """Stacked-layer pytree (leading axis = layer), bf16 weights."""
    cfg.validate()
    k = iter(jax.random.split(key, 10))
    L, d, f = cfg.n_layers, cfg.d_model, cfg.d_ff
    pdim = cfg.patch * cfg.patch * cfg.channels

    def w(key, *shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(cfg.dtype)

    return {
        "patch_embed": w(next(k), pdim, d, fan_in=pdim),
        "cls_token": jnp.zeros((1, 1, d), cfg.dtype),
        # learned position embedding, fp32 like the norms (added once)
        "pos_embed": (jax.random.normal(next(k), (1, cfg.seq, d),
                                        jnp.float32) * 0.02),
        "layers": {
            "ln1": jnp.ones((L, d), jnp.float32),
            "ln1_b": jnp.zeros((L, d), jnp.float32),
            "wq": w(next(k), L, d, d, fan_in=d),
            "wk": w(next(k), L, d, d, fan_in=d),
            "wv": w(next(k), L, d, d, fan_in=d),
            "wo": w(next(k), L, d, d, fan_in=d),
            "ln2": jnp.ones((L, d), jnp.float32),
            "ln2_b": jnp.zeros((L, d), jnp.float32),
            "w1": w(next(k), L, d, f, fan_in=d),
            "w2": w(next(k), L, f, d, fan_in=f),
        },
        "final_ln": jnp.ones((d,), jnp.float32),
        "final_ln_b": jnp.zeros((d,), jnp.float32),
        "head": w(next(k), d, cfg.classes, fan_in=d),
    }


def vit_param_specs(cfg: ViTConfig) -> dict:
    """Megatron tp layout (cf. model.py:param_specs; one all-reduce
    after wo and after w2 per block), batch over dp at the call site."""
    return {
        "patch_embed": P(None, None),
        "cls_token": P(None, None, None),
        "pos_embed": P(None, None, None),
        "layers": {
            "ln1": P(None, None), "ln1_b": P(None, None),
            "wq": P(None, None, "tp"), "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"), "wo": P(None, "tp", None),
            "ln2": P(None, None), "ln2_b": P(None, None),
            "w1": P(None, None, "tp"), "w2": P(None, "tp", None),
        },
        "final_ln": P(None), "final_ln_b": P(None),
        "head": P(None, None),
    }


def _layernorm(x, g, b):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + 1e-6) * g + b).astype(x.dtype)


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, N, p*p*C]: the reshape a stride-p conv is."""
    B, H, W, C = images.shape
    p = cfg.patch
    x = images.reshape(B, H // p, p, W // p, p, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, gh, gw, p, p, C]
    return x.reshape(B, (H // p) * (W // p), p * p * C)


def vit_forward(params: dict, images: jax.Array,
                cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] images -> [B, classes] logits."""
    B = images.shape[0]
    nh, hd = cfg.n_heads, cfg.head_dim

    x = patchify(images.astype(cfg.dtype), cfg) @ params["patch_embed"]
    cls = jnp.broadcast_to(params["cls_token"], (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = (x.astype(jnp.float32) + params["pos_embed"]).astype(cfg.dtype)

    def block(x, layer):
        h = _layernorm(x, layer["ln1"], layer["ln1_b"])
        q = (h @ layer["wq"]).reshape(B, -1, nh, hd).transpose(0, 2, 1, 3)
        k = (h @ layer["wk"]).reshape(B, -1, nh, hd).transpose(0, 2, 1, 3)
        v = (h @ layer["wv"]).reshape(B, -1, nh, hd).transpose(0, 2, 1, 3)
        if cfg.attn == "flash":
            o = flash_attention(q, k, v, causal=False)
        else:
            o = attention_reference(q, k, v, causal=False)
        o = o.transpose(0, 2, 1, 3).reshape(B, -1, cfg.d_model)
        x = x + o @ layer["wo"]
        h = _layernorm(x, layer["ln2"], layer["ln2_b"])
        x = x + jax.nn.gelu(h @ layer["w1"]) @ layer["w2"]
        return x, None

    x, _ = lax.scan(block, x, params["layers"])
    x = _layernorm(x, params["final_ln"], params["final_ln_b"])
    return (x[:, 0] @ params["head"]).astype(jnp.float32)  # [CLS] head


def make_vit_train_step(cfg: ViTConfig, learning_rate: float = 1e-3):
    """(tx, train_step) for softmax-cross-entropy classification —
    same contract shape as model.make_train_step."""
    import optax

    tx = optax.adamw(learning_rate)

    def loss_fn(params, images, labels):
        logits = vit_forward(params, images, cfg)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()

    def train_step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return tx, train_step
