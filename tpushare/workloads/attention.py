"""Fused causal attention: a Pallas TPU kernel with online softmax.

The serving workload's attention is the HBM-bandwidth hot spot: the naive
einsum path materializes a [B, H, S, S] score tensor in fp32 through HBM.
This kernel streams ONE K/V block at a time through VMEM with the
flash-attention recurrence (running max + rescaled accumulator held in VMEM
scratch across grid steps), so residency is O(BLOCK x D) regardless of
sequence length — nothing quadratic ever exists, on chip or off. MXU does
the block matmuls, VPU the rescaling (see
/opt/skills/guides/pallas_guide.md).

Grid: (B, H, q_blocks, kv_blocks); TPU grids execute sequentially with the
last axis fastest, so the (m, l, acc) scratch carries across the kv axis of
one (b, h, i) triple and is re-initialized at kv step 0. Causal q-blocks
skip kv blocks beyond their diagonal entirely (no compute, no DMA use) —
the standard ~2x causal FLOP saving.

Forward-only by design: training uses the einsum path (XLA's fused
attention + autodiff), serving/decoding uses this kernel; make_train_step
rejects flash configs explicitly. A custom VJP is the natural next step.

Layout contract: q, k, v are [B, H, S, D] (heads already GQA-expanded),
D <= 128. Sequences are padded to the 128-block internally; padded KEY
positions are masked, padded QUERY rows are sliced off on return.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

BLOCK = 128  # q/k block edge (MXU-aligned; bf16 min tile is (16, 128))


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True) -> jax.Array:
    """Plain einsum attention (the behavioral spec the kernel must match)."""
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (d ** -0.5)
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, seq: int, n_kv: int, causal: bool):
    """One (b, h, q-block i, kv-block j) grid step.

    q_ref: [1, 1, BLOCK, D]; k_ref/v_ref: [1, 1, BLOCK, D] (current kv
    block only); o_ref: [1, 1, BLOCK, D]; m/l/acc: VMEM scratch carrying
    the online-softmax state across the kv axis.
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: kv blocks past the diagonal contribute nothing
    visible = (j <= i) if causal else (j >= 0)

    @pl.when(visible)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [BQ, D]
        bq = q.shape[0]
        kb = k_ref[0, 0].astype(jnp.float32)             # [BK, D]
        vb = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, BLOCK), 0)
        col = j * BLOCK + jax.lax.broadcasted_iota(jnp.int32, (bq, BLOCK), 1)
        mask = col < seq                                  # padded keys out
        if causal:
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, -jnp.inf)

        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # rows with no visible key yet keep m=-inf; exp(-inf - -inf) would
        # be NaN, so clamp the shift for those rows
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
        m_ref[...] = m_new
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # final kv step for this q block: normalize and emit
    last = i if causal else (n_kv - 1)

    @pl.when(j == last)
    def _emit():
        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "interpret"))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    interpret: bool | None = None) -> jax.Array:
    """Fused attention over [B, H, S, D] tensors (kv heads pre-expanded).

    Runs the Pallas TPU kernel natively on TPU backends and in interpret
    mode elsewhere (tests/CPU meshes) — same code path, same numerics.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    if k.shape != (B, H, k.shape[2], D) or v.shape != k.shape:
        raise ValueError(
            f"q {q.shape} / k {k.shape} / v {v.shape} must share batch, "
            "heads and head_dim")
    if D > BLOCK:
        raise ValueError(f"head_dim {D} > {BLOCK} unsupported")
    if causal and k.shape[2] != S:
        raise ValueError("causal attention requires matching q/k lengths")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    pad_q = (-S) % BLOCK
    kv = k.shape[2]
    pad_k = (-kv) % BLOCK
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    Sp, KVp = S + pad_q, kv + pad_k
    n_kv = KVp // BLOCK

    grid = (B, H, Sp // BLOCK, n_kv)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=D ** -0.5, seq=kv,
                          n_kv=n_kv, causal=causal),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, BLOCK, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, BLOCK, D),
                         lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, BLOCK, D),
                         lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, BLOCK, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((BLOCK, 1), jnp.float32),   # running max m
            pltpu.VMEM((BLOCK, 1), jnp.float32),   # running denom l
            pltpu.VMEM((BLOCK, D), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :S, :]
