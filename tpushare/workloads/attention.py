"""Fused causal attention: a Pallas TPU kernel with online softmax.

The serving workload's attention is the HBM-bandwidth hot spot: the naive
einsum path materializes a [B, H, S, S] score tensor in fp32 through HBM.
This kernel streams ONE K/V block at a time through VMEM with the
flash-attention recurrence (running max + rescaled accumulator held in VMEM
scratch across grid steps), so residency is O(BLOCK x D) regardless of
sequence length — nothing quadratic ever exists, on chip or off. MXU does
the block matmuls, VPU the rescaling (see
/opt/skills/guides/pallas_guide.md).

Grid: (B, H, q_blocks, kv_blocks); TPU grids execute sequentially with the
last axis fastest, so the (m, l, acc) scratch carries across the kv axis of
one (b, h, i) triple and is re-initialized at the first visible kv step.
Causal q-blocks skip kv blocks beyond their diagonal entirely (no compute,
no DMA use) — the standard ~2x causal FLOP saving — and sliding-window
mode (``window=W``) additionally skips blocks below the window floor, so
per-query cost is O(W) regardless of sequence length.

Differentiable: :func:`flash_attention` carries a custom VJP whose backward
pass regenerates each probability block from the kernel's log-sum-exp
residual and scans over K/V blocks — training configs may therefore use
``attn="flash"`` and keep O(S x BLOCK) attention residency in both passes.

Layout contract: q is [B, H, S, D]; k/v are [B, H_kv, S_kv, D] with
H_kv dividing H (GQA-native — pass the SMALL kv heads; the kernel's kv
BlockSpecs divide the head index by the group size so repeated heads are
never materialized, which is the HBM point of GQA). D <= 128. Sequences
are padded to the 128-block internally; padded KEY positions are masked,
padded QUERY rows are sliced off on return.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

BLOCK = 128  # minimum q/k block edge (MXU-aligned; bf16 min tile is (16, 128))

_FLASH_BWD_IMPLS = ("xla", "pallas")
_FLASH_FWD_IMPLS = ("step", "pipelined")


def _resolve_flash_fwd(fwd_impl: str | None) -> str:
    """Forward-kernel variant, resolved like :func:`_resolve_flash_bwd`.

    "step" — one kv block per grid step: score matmul, softmax, p@v in
    a single dependency chain (the r3 kernel; VPU softmax is its
    measured critical path, docs/perf.md ablation).
    "pipelined" — the next block's score matmul is issued in the same
    grid step as the previous block's softmax/p@v consume, with scores
    double-buffered in VMEM, giving Mosaic's scheduler a data-
    independent MXU chain to overlap the VPU passes with. Identical
    math in identical order; stays opt-in (TPUSHARE_FLASH_FWD=pipelined)
    because the captured on-chip A/B (2026-07-31, TPU v5 lite) put it at
    34.5% MFU vs the step kernel's 49.2% — the double-buffered score
    scratch halves the usable VMEM working set and costs more than the
    VPU/MXU overlap recovers at the winning 1024x1024 tile.
    """
    if fwd_impl is None:
        fwd_impl = os.environ.get("TPUSHARE_FLASH_FWD", "step")
    if fwd_impl not in _FLASH_FWD_IMPLS:
        raise ValueError(
            f"fwd_impl={fwd_impl!r} (or $TPUSHARE_FLASH_FWD) must be "
            f"one of {_FLASH_FWD_IMPLS}")
    return fwd_impl


def _resolve_flash_bwd(bwd_impl: str | None) -> str:
    """Resolve the backward implementation OUTSIDE any trace.

    ``None`` reads TPUSHARE_FLASH_BWD when ``flash_attention`` itself
    runs, and the resolved string travels into the custom_vjp as a
    nondiff argument — i.e. it is part of ``flash_attention``'s own jit
    cache key, so an eager caller that flips the env (or passes
    ``bwd_impl=``) deterministically retraces rather than silently
    reusing a previously cached backward (the hazard of reading the env
    at trace time inside ``_flash_bwd``). Inside an OUTER jit the
    resolution necessarily happens at that outer trace time and is NOT
    part of the outer cache key — callers holding a jitted train step
    across an env flip must rebuild it (or pass ``bwd_impl``
    explicitly); standard jit closure semantics, now confined to the
    caller's own jit instead of a process-global VJP cache.
    """
    if bwd_impl is None:
        bwd_impl = os.environ.get("TPUSHARE_FLASH_BWD", "pallas")
    if bwd_impl not in _FLASH_BWD_IMPLS:
        raise ValueError(
            f"bwd_impl={bwd_impl!r} (or $TPUSHARE_FLASH_BWD) must be one "
            f"of {_FLASH_BWD_IMPLS}")
    return bwd_impl

# Default tile sizes for the compiled TPU path. The grid-step count is
# (B*H*Sq/block_q*Skv/block_kv); at 128x128 a 4x8x2048 shape needs 8192
# steps of two 128^3 matmuls (~43 ns of MXU work each) and per-step
# dispatch overhead dominates — measured 2.6 ms vs XLA einsum's 1.9 ms on
# v5e. Larger tiles amortize: a 12-config on-chip sweep (r3) put 1024x1024
# strictly ahead of every neighbor (512x1024 35%, 512x512 27%, 1024x512
# 26%, 2048x1024 fails to compile — the 8 MB score block overflows VMEM).
# With the scale pre-fold and the redundant-p-remask removal, 1024x1024
# measures 0.44 ms = 40% MFU at B4 H8 S2048 D128 bf16 causal (3.7x XLA
# einsum's 1.65 ms, same harness). Tiles shrink automatically for short
# sequences.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_KV = 1024


def validate_gqa_qkv(q, k, v, extra: str = "") -> int:
    """THE GQA layout contract, shared by every attention frontend
    (flash kernel, ring, Ulysses): q [B, H, S, D]; k/v [B, H_kv, S_kv, D]
    with H_kv dividing H — pass the SMALL kv heads, never pre-expanded.
    Returns H_kv. One definition so the predicate algebra cannot drift
    across modules."""
    B, H, S, D = q.shape
    Hkv = k.shape[1] if k.ndim == 4 else -1
    if (k.ndim != 4 or v.shape != k.shape or Hkv <= 0 or H % Hkv
            or k.shape != (B, Hkv, k.shape[2], D)):
        raise ValueError(
            f"q {q.shape} / k {k.shape} / v {v.shape} must share batch "
            "and head_dim, with kv heads dividing query heads "
            "(GQA-native: pass the SMALL kv heads, do not pre-expand)"
            + (f"; {extra}" if extra else ""))
    return Hkv


def sliding_window_mask(row_pos, col_pos, window: int):
    """THE window-visibility predicate: key ``col_pos`` is visible from
    query ``row_pos`` iff ``col_pos >= row_pos - (window - 1)`` (W keys
    incl. the diagonal). Single definition of the inclusive convention —
    every path (reference, kernel, model einsum, KV-cached decode)
    composes this, so an off-by-one fix lands everywhere at once.
    Broadcasts over any compatible position-array shapes."""
    return col_pos >= row_pos - (window - 1)


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        window: int | None = None) -> jax.Array:
    """Plain einsum attention (the behavioral spec the kernel must match).

    ``window=W`` (causal only) is sliding-window attention: query i sees
    keys [max(0, i-W+1), i] — the Mistral-style local mask for
    long-context serving.
    """
    if window is not None and not causal:
        # match flash_attention: silently returning full bidirectional
        # attention would let the spec validate the wrong computation
        raise ValueError("window attention requires causal=True")
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (d ** -0.5)
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        if window is not None:
            mask = jnp.logical_and(mask, sliding_window_mask(
                jnp.arange(S)[:, None], jnp.arange(S)[None, :], window))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _causal_class_dispatch(pl, step, gate, i, j, block_q: int,
                           block_kv: int, window: int | None):
    """THE causal/window mask-class split, shared by the forward kernel
    and both backward kernels (three hand-synced copies of this predicate
    algebra is how off-by-ones are born). ``step(mask_causal,
    mask_window)`` runs under ``gate`` for each class:

    - clean: entirely below the diagonal and above the window floor — no
      compares at all (the common case; each saved compare+where is a
      VPU pass over the score matrix);
    - diag-only / floor-only / both: pay exactly the compare(s) the
      block straddles.
    """
    below_diag = (j + 1) * block_kv - 1 <= i * block_q
    if window is not None:
        above_floor = j * block_kv >= (i + 1) * block_q - window

        @pl.when(jnp.logical_and(gate, jnp.logical_and(
            below_diag, above_floor)))
        def _clean():
            step(False, False)

        @pl.when(jnp.logical_and(gate, jnp.logical_and(
            jnp.logical_not(below_diag), above_floor)))
        def _diag_only():
            step(True, False)

        @pl.when(jnp.logical_and(gate, jnp.logical_and(
            below_diag, jnp.logical_not(above_floor))))
        def _floor_only():
            step(False, True)

        @pl.when(jnp.logical_and(gate, jnp.logical_and(
            jnp.logical_not(below_diag), jnp.logical_not(above_floor))))
        def _both():
            step(True, True)
    else:
        @pl.when(jnp.logical_and(gate, below_diag))
        def _clean():
            step(False, False)

        @pl.when(jnp.logical_and(gate, jnp.logical_not(below_diag)))
        def _diag():
            step(True, False)


def _mask_scores(s, i, j, block_kv, seq, window,
                 mask_causal: bool, mask_pad: bool, mask_window: bool):
    """Apply the selected mask classes to a [BQ, BK] score block for
    kv block ``j``. Shared by the step and pipelined forward kernels —
    hand-synced copies of this predicate algebra is how off-by-ones are
    born (same policy as _causal_class_dispatch)."""
    if not (mask_causal or mask_pad or mask_window):
        return s
    bq = s.shape[0]
    col = j * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, (bq, block_kv), 1)
    mask = None
    if mask_pad:
        mask = col < seq                              # padded keys out
    if mask_causal or mask_window:
        row = i * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_kv), 0)
        if mask_causal:
            c = col <= row
            mask = c if mask is None else jnp.logical_and(mask, c)
        if mask_window:
            w = sliding_window_mask(row, col, window)
            mask = w if mask is None else jnp.logical_and(mask, w)
    return jnp.where(mask, s, -jnp.inf)


def _online_softmax_accum(s, vb, m_ref, l_ref, acc_ref):
    """One online-softmax update of the (m, l, acc) scratch state from a
    masked [BQ, BK] score block and its [BK, D] value block. Shared by
    both forward kernels — the bit-identity contract between them IS
    this function being the single copy."""
    m = m_ref[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # rows with no visible key yet keep m=-inf; exp(-inf - -inf) would
    # be NaN, so clamp the shift for those rows
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    # masked score entries are already -inf and exp(-inf - shift) is
    # exactly 0.0 for any finite shift, so p needs NO re-mask — that
    # redundant where() pass over [BQ, BK] cost ~10% of kernel time
    p = jnp.exp(s - shift)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
    m_ref[...] = m_new
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    # p is cast to the value dtype for the second matmul (standard
    # flash practice: probabilities are in [0,1] so bf16 truncation
    # costs ~3 decimal digits, matching the einsum reference's p cast)
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _emit_block(o_ref, lse_ref, m_ref, l_ref, acc_ref):
    """Normalize and write the output + log-sum-exp residual for one
    q block. Shared by both forward kernels."""
    l = l_ref[...]
    out = acc_ref[...] / jnp.maximum(l, 1e-30)
    o_ref[0, 0] = out.astype(o_ref.dtype)
    # log-sum-exp of the scaled scores per query row (the residual the
    # backward pass needs to regenerate p without storing it); rows
    # with no visible key (query padding) emit -inf
    lse = jnp.where(l > 0, m_ref[...] + jnp.log(jnp.maximum(l, 1e-30)),
                    -jnp.inf)
    # lse block is [1, 1, 8, block_q]: the sublane dim is padding that
    # exists purely to satisfy Mosaic's (8, 128) min-tile rule for
    # fp32 outputs — broadcast the row vector across it
    lse_ref[0, 0] = jnp.broadcast_to(lse[:, 0], lse_ref.shape[2:])


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                  acc_ref, *, seq: int, n_kv: int,
                  causal: bool, block_q: int, block_kv: int,
                  window: int | None):
    """One (b, h, q-block i, kv-block j) grid step.

    q_ref: [1, 1, block_q, D] (softmax scale pre-folded by the caller);
    k_ref/v_ref: [1, 1, block_kv, D] (current kv block only); o_ref:
    [1, 1, block_q, D]; m/l/acc: VMEM scratch carrying the online-softmax
    state across the kv axis.

    ``window=W`` (sliding-window/local attention, causal only): kv
    blocks entirely BELOW the q block's window floor are skipped the
    same way beyond-diagonal blocks are — per-query cost is O(W), not
    O(S), which is the whole point for long-context serving.
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(2)
    j = pl.program_id(3)

    # first visible kv block: 0 normally; with a window, blocks whose
    # LAST column is older than the q block's oldest visible key
    # ((i*bq) - W + 1) are skipped, so init moves to the window floor
    if window is None:
        j_start = 0
    else:
        floor = i * block_q - (window - 1)
        j_start = jnp.maximum(floor, 0) // block_kv

    @pl.when(j == j_start)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # causal: kv blocks whose first column is past the q block's last row
    # contribute nothing; with a window, neither do blocks whose last
    # column is below the BLOCK's lowest window floor
    visible = (j * block_kv <= (i + 1) * block_q - 1) if causal else (j >= 0)
    if window is not None:
        visible = jnp.logical_and(visible, j >= j_start)

    def _accum(mask_causal: bool, mask_pad: bool,
               mask_window: bool = False):
        # inputs stay in their storage dtype (bf16) through the MXU —
        # fp32 accumulation comes from preferred_element_type; pre-casting
        # to fp32 would halve MXU throughput. The softmax scale is folded
        # into q ONCE by _flash_call (not per kv step, and never on the
        # VPU-bound [BQ, BK] score path).
        s = jax.lax.dot_general(
            q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [BQ, BK]
        s = _mask_scores(s, i, j, block_kv, seq, window,
                         mask_causal, mask_pad, mask_window)
        _online_softmax_accum(s, v_ref[0, 0], m_ref, l_ref, acc_ref)

    # mask work is dispatched 3-way so each block class pays only for the
    # compares it needs (each saved compare/where is a VPU pass over the
    # [BQ, BK] score matrix):
    #   full     — entirely below the causal diagonal, no padded keys:
    #              no mask at all (the common case for long sequences)
    #   diagonal — straddles the causal diagonal but no padded keys:
    #              causal compare only
    #   padded   — contains padded key columns: both compares
    col_end = (j + 1) * block_kv              # exclusive last col + 1
    nopad = col_end <= seq
    if causal:
        _causal_class_dispatch(
            pl, lambda c, w: _accum(mask_causal=c, mask_pad=False,
                                    mask_window=w),
            jnp.logical_and(visible, nopad), i, j, block_q, block_kv,
            window)
    else:
        # non-causal: no diagonal class exists — lowering it anyway would
        # trace a dead duplicate of the accumulate body into every kernel
        @pl.when(jnp.logical_and(visible, nopad))
        def _step_unmasked():
            _accum(mask_causal=False, mask_pad=False)

    @pl.when(jnp.logical_and(visible, jnp.logical_not(nopad)))
    def _step_padded():
        _accum(mask_causal=causal, mask_pad=True,
               mask_window=causal and window is not None)

    # final kv step for this q block: normalize and emit. With unequal
    # block sizes and query padding the diagonal formula can point past
    # the kv grid — clamp, or the emit step never fires for the last
    # (partially padded) q blocks and their output rows are garbage.
    last = (jnp.minimum(((i + 1) * block_q - 1) // block_kv, n_kv - 1)
            if causal else (n_kv - 1))

    @pl.when(j == last)
    def _emit():
        _emit_block(o_ref, lse_ref, m_ref, l_ref, acc_ref)


def _flash_kernel_pipelined(q_ref, k_ref, v_ref, o_ref, lse_ref,
                            m_ref, l_ref, acc_ref, s_ref, *, seq: int,
                            n_kv: int, causal: bool, block_q: int,
                            block_kv: int, window: int | None):
    """Software-pipelined grid step: COMPUTE block j's scores while
    CONSUMING block j-1's.

    The r3 ablation (docs/perf.md) measured the softmax VPU passes as
    the critical path: within one step kernel the chain
    score-matmul -> max/exp/sum -> p@v is strictly serial, idling the
    MXU ~60% of each step. Here the kv grid runs ONE EXTRA step and
    each step does two data-independent halves:

      compute:  s_j = q @ k_j          (pure MXU; no masking — that is
                VPU work and belongs to the consume phase) written to
                scratch slot j % 2;
      consume:  mask/softmax/accumulate block j-1 from slot (j-1) % 2,
                with v's BlockSpec index map shifted one block BACK so
                v_{j-1} is resident.

    The two halves share no data (double-buffered scores, different
    kv blocks), so Mosaic's scheduler is free to overlap the compute
    matmul with the consume softmax. Numerics are IDENTICAL to
    _flash_kernel: same operations on the same values in the same
    online-softmax order — only issue order changes.
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(2)
    j = pl.program_id(3)

    if window is None:
        j_start = 0
    else:
        floor = i * block_q - (window - 1)
        j_start = jnp.maximum(floor, 0) // block_kv

    @pl.when(j == j_start)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # ---- compute phase: s_j (gated off for the extra step and for
    # invisible blocks; k's index map clamps j so the DMA stays legal)
    visible_j = jnp.logical_and(
        j <= n_kv - 1,
        (j * block_kv <= (i + 1) * block_q - 1) if causal else True)
    if window is not None:
        visible_j = jnp.logical_and(visible_j, j >= j_start)

    @pl.when(visible_j)
    def _compute():
        s = jax.lax.dot_general(
            q_ref[0, 0], k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # [BQ, BK]
        s_ref[j % 2] = s

    # ---- consume phase: block jj = j - 1 from the other slot
    jj = j - 1
    visible_jj = jnp.logical_and(
        jj >= j_start,
        (jj * block_kv <= (i + 1) * block_q - 1) if causal else jj >= 0)

    def _consume(mask_causal: bool, mask_pad: bool,
                 mask_window: bool = False):
        s = _mask_scores(s_ref[jj % 2], i, jj, block_kv, seq,
                         window, mask_causal, mask_pad, mask_window)
        _online_softmax_accum(s, v_ref[0, 0], m_ref, l_ref, acc_ref)

    col_end = (jj + 1) * block_kv
    nopad = col_end <= seq
    if causal:
        _causal_class_dispatch(
            pl, lambda c, w: _consume(mask_causal=c, mask_pad=False,
                                      mask_window=w),
            jnp.logical_and(visible_jj, nopad), i, jj, block_q,
            block_kv, window)
    else:
        @pl.when(jnp.logical_and(visible_jj, nopad))
        def _consume_unmasked():
            _consume(mask_causal=False, mask_pad=False)

    @pl.when(jnp.logical_and(visible_jj, jnp.logical_not(nopad)))
    def _consume_padded():
        _consume(mask_causal=causal, mask_pad=True,
                 mask_window=causal and window is not None)

    # ---- emit: one step AFTER the step kernel's last (the consume of
    # the diagonal/final block happens there)
    last = (jnp.minimum(((i + 1) * block_q - 1) // block_kv, n_kv - 1)
            if causal else (n_kv - 1))

    @pl.when(j == last + 1)
    def _emit():
        _emit_block(o_ref, lse_ref, m_ref, l_ref, acc_ref)


def _flash_call(q: jax.Array, k: jax.Array, v: jax.Array,
                causal: bool, interpret: bool,
                block_q: int | None = None, block_kv: int | None = None,
                window: int | None = None, pipelined: bool = False):
    """Run the kernel; returns (out [B,H,S,D], lse [B,H,S] fp32).

    GQA-native: k/v may carry fewer heads (H_kv dividing H); the kv
    BlockSpec index maps divide the head index by the group size, so each
    query-head group streams the SAME kv blocks — the kernel never
    materializes the repeated heads, which is the whole HBM point of GQA
    (a pre-expanded call would move group-size x more K/V per step).

    ``pipelined=True`` selects :func:`_flash_kernel_pipelined`: the kv
    grid runs one extra step, v's index map trails k's by one block, and
    scores double-buffer through a [2, BQ, BK] VMEM scratch.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    Hkv = k.shape[1]
    g = H // Hkv  # query heads per kv head (validated by the caller)
    kv = k.shape[2]
    # fold the softmax scale into q once, outside the kernel (numerically
    # identical to the former per-step fold — same f32-multiply-then-
    # storage-dtype rounding — but paid once instead of every kv step)
    q = (q.astype(jnp.float32) * (D ** -0.5)).astype(q.dtype)
    # shrink tiles to the 128-aligned sequence so short shapes don't pad
    # out to a full default tile
    bq = min(block_q or DEFAULT_BLOCK_Q, -(-S // BLOCK) * BLOCK)
    bk = min(block_kv or DEFAULT_BLOCK_KV, -(-kv // BLOCK) * BLOCK)
    pad_q = (-S) % bq
    pad_k = (-kv) % bk
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    Sp, KVp = S + pad_q, kv + pad_k
    n_kv = KVp // bk

    # b/h/q-block steps are independent; only the kv axis carries the
    # online-softmax scratch state and must stay sequential. The
    # pipelined variant's [2, BQ, BK] fp32 score scratch puts the kernel
    # ~80 KiB over Mosaic's conservative 16 MiB scoped-VMEM default at
    # the shipping 1024x1024 tiles (measured on-chip: 16.08M vs 16.00M),
    # so it declares a 32 MiB budget — still a fraction of physical VMEM
    # on v4/v5 hardware, and only the actual ~16.1M gets allocated.
    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"),
        vmem_limit_bytes=(32 * 1024 * 1024 if pipelined else None))
    scratch = [
        pltpu.VMEM((bq, 1), jnp.float32),   # running max m
        pltpu.VMEM((bq, 1), jnp.float32),   # running denom l
        pltpu.VMEM((bq, D), jnp.float32),   # output accumulator
    ]
    if pipelined:
        # one extra kv step (the final consume); k is clamped to the
        # last real block there, v trails one block behind k
        grid = (B, H, Sp // bq, n_kv + 1)
        kernel = functools.partial(
            _flash_kernel_pipelined, seq=kv, n_kv=n_kv, causal=causal,
            block_q=bq, block_kv=bk, window=window)
        k_map = (lambda b, h, i, j, g=g, n=n_kv:
                 (b, h // g, jnp.minimum(j, n - 1), 0))
        v_map = (lambda b, h, i, j, g=g:
                 (b, h // g, jnp.maximum(j - 1, 0), 0))
        scratch = scratch + [pltpu.VMEM((2, bq, bk), jnp.float32)]
    else:
        grid = (B, H, Sp // bq, n_kv)
        kernel = functools.partial(
            _flash_kernel, seq=kv, n_kv=n_kv, causal=causal,
            block_q=bq, block_kv=bk, window=window)
        k_map = lambda b, h, i, j, g=g: (b, h // g, j, 0)  # noqa: E731
        v_map = k_map
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(qp.shape, q.dtype),
                   jax.ShapeDtypeStruct((B, H, 8, Sp), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D),
                         lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), k_map),
            pl.BlockSpec((1, 1, bk, D), v_map),
        ],
        out_specs=(pl.BlockSpec((1, 1, bq, D),
                                lambda b, h, i, j: (b, h, i, 0)),
                   pl.BlockSpec((1, 1, 8, bq),
                                lambda b, h, i, j: (b, h, 0, i))),
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :, :S, :], lse[:, :, 0, :S]


# Backward tile sizes. The backward kernels keep three [BKV, BQ] fp32
# intermediates (s, dp, ds) live at once, so tiles are one notch smaller
# than the forward's 1024x1024 to fit VMEM with double buffering.
DEFAULT_BWD_BLOCK_Q = 512
DEFAULT_BWD_BLOCK_KV = 512


def _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *,
                i, j, seq: int, block_q: int, block_kv: int,
                mask_causal: bool, mask_pad: bool,
                mask_window: bool = False, window: int | None = None):
    """Shared backward block math, in TRANSPOSED score space.

    Everything is [BKV, BQ] (kv positions on sublanes, q positions on
    lanes) so the per-q-row lse and delta broadcast as [1, BQ] ROW
    vectors — a [BQ, 1] column layout would need an in-kernel transpose
    of the [8, BQ] residual block, which Mosaic lowers poorly.

    Returns (p_T, ds_T) as [BKV, BQ]; p_T fp32, ds_T cast to the k/v
    storage dtype ready for the MXU.
    """
    q = q_ref[0, 0]                                   # [BQ, D] pre-scaled
    kb = k_ref[0, 0]                                  # [BK, D]
    vb = v_ref[0, 0]
    dob = do_ref[0, 0]                                # [BQ, D]
    lse_row = lse_ref[0, 0][0:1, :]                   # [1, BQ]
    delta_row = delta_ref[0, 0][0:1, :]               # [1, BQ]

    s_t = jax.lax.dot_general(
        kb, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # [BK, BQ]
    if mask_causal or mask_pad or mask_window:
        kpos = j * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (block_kv, block_q), 0)
        mask = None
        if mask_pad:
            mask = kpos < seq                         # padded keys out
        if mask_causal or mask_window:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 1)
            if mask_causal:
                c = kpos <= qpos
                mask = c if mask is None else jnp.logical_and(mask, c)
            if mask_window:
                w = sliding_window_mask(qpos, kpos, window)
                mask = w if mask is None else jnp.logical_and(mask, w)
        # exp(-inf - lse) == 0, so p needs no re-mask (forward's trick)
        s_t = jnp.where(mask, s_t, -jnp.inf)

    p_t = jnp.exp(s_t - lse_row)                      # [BK, BQ]
    dp_t = jax.lax.dot_general(
        vb, dob, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # [BK, BQ]
    ds_t = (p_t * (dp_t - delta_row)).astype(kb.dtype)
    return p_t, ds_t, kb, vb, dob, q


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, seq: int, n_kv: int,
                         causal: bool, block_q: int, block_kv: int,
                         window: int | None):
    """dq pass: grid (B, H, i, j), j innermost carrying the dq accumulator.

    dq[i] = scale * sum_j ds[i,j] @ k[j]; computed transposed as
    dot_general(ds_T, k, contract over the kv sublane axis) — an MXU
    contraction over dim 0 on both sides, no transposes materialized.
    The caller applies the scale factor (q arrives pre-scaled, so the
    in-kernel gradient is w.r.t. scaled q).
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(2)
    j = pl.program_id(3)

    # mirror of the forward's window-floor logic: init relocates to the
    # first visible kv block and below-floor blocks are skipped entirely
    if window is None:
        j_start = 0
    else:
        j_start = jnp.maximum(i * block_q - (window - 1), 0) // block_kv

    @pl.when(j == j_start)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    visible = (j * block_kv <= (i + 1) * block_q - 1) if causal else (j >= 0)
    if window is not None:
        visible = jnp.logical_and(visible, j >= j_start)

    def _step(mask_causal: bool, mask_pad: bool, mask_window: bool = False):
        _, ds_t, kb, _, _, _ = _bwd_common(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i=i, j=j,
            seq=seq, block_q=block_q, block_kv=block_kv,
            mask_causal=mask_causal, mask_pad=mask_pad,
            mask_window=mask_window, window=window)
        dq_acc[...] += jax.lax.dot_general(
            ds_t, kb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [BQ, D]

    col_end = (j + 1) * block_kv
    nopad = col_end <= seq
    if causal:
        _causal_class_dispatch(
            pl, lambda c, w: _step(mask_causal=c, mask_pad=False,
                                   mask_window=w),
            jnp.logical_and(visible, nopad), i, j, block_q, block_kv,
            window)
    else:
        @pl.when(jnp.logical_and(visible, nopad))
        def _step_unmasked():
            _step(mask_causal=False, mask_pad=False)

    @pl.when(jnp.logical_and(visible, jnp.logical_not(nopad)))
    def _step_padded():
        _step(mask_causal=causal, mask_pad=True,
              mask_window=causal and window is not None)

    last = (jnp.minimum(((i + 1) * block_q - 1) // block_kv, n_kv - 1)
            if causal else (n_kv - 1))

    @pl.when(j == last)
    def _emit():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkdv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *, seq: int,
                           n_q: int, n_g: int, causal: bool, block_q: int,
                           block_kv: int, window: int | None):
    """dk/dv pass: grid (B, H_kv, j, i, g) with the (i, g) pair innermost
    carrying both accumulators. dv[j] = sum_{i,g} p_T[j,i,g] @ do[i,g];
    dk[j] = sum_{i,g} ds_T[j,i,g] @ q_s[i,g] (already transposed — plain
    matmuls). The g axis is the query-head group (GQA): each kv head's
    gradients sum over its n_g query heads IN the grid, which is what
    lets the kernel serve grouped-query attention without expanding K/V
    the way the XLA fallback does (the output block (b, h_kv, j) stays
    resident across the whole consecutive (i, g) sweep, so the revisit
    pattern remains legal). n_g == 1 is plain MHA.
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(2)
    i = pl.program_id(3)
    g = pl.program_id(4)

    # first visible q block for this kv block: rows below j*block_kv see
    # nothing of it under causal masking
    i_start = (j * block_kv) // block_q if causal else 0

    @pl.when(jnp.logical_and(i == i_start, g == 0))
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    visible = (i * block_q + block_q - 1 >= j * block_kv) if causal \
        else (i >= 0)
    if window is not None:
        # q blocks whose lowest window floor is past this kv block's last
        # column contribute nothing to its dk/dv
        visible = jnp.logical_and(
            visible, i * block_q <= (j + 1) * block_kv + window - 2)

    def _step(mask_causal: bool, mask_pad: bool, mask_window: bool = False):
        p_t, ds_t, _, _, dob, q = _bwd_common(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, i=i, j=j,
            seq=seq, block_q=block_q, block_kv=block_kv,
            mask_causal=mask_causal, mask_pad=mask_pad,
            mask_window=mask_window, window=window)
        dv_acc[...] += jax.lax.dot_general(
            p_t.astype(dob.dtype), dob, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [BK, D]
        dk_acc[...] += jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [BK, D]

    # Mask dispatch is 2-way here (vs the forward's 3): padded KEY rows
    # need no mask (their dk/dv rows are sliced off by the caller) and
    # padded QUERY lanes self-zero (do/delta are zero-padded so ds == 0,
    # and the +1e30 lse clamp makes p == 0 exactly); only beyond-causal
    # entries of diagonal blocks would contribute garbage to the q-lane
    # contraction, so the causal compare is the one mask required.
    if causal:
        # no pad class here — padded KEY rows are sliced by the caller
        # and padded QUERY lanes self-zero (see the note above)
        _causal_class_dispatch(
            pl, lambda c, w: _step(mask_causal=c, mask_pad=False,
                                   mask_window=w),
            visible, i, j, block_q, block_kv, window)
    else:
        @pl.when(visible)
        def _step_all():
            _step(mask_causal=False, mask_pad=False)

    @pl.when(jnp.logical_and(i == n_q - 1, g == n_g - 1))
    def _emit():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, do, causal: bool, interpret: bool,
                      block_q: int | None = None,
                      block_kv: int | None = None,
                      window: int | None = None):
    """Pallas backward: two kernels over the same recomputed scores,
    with the forward's causal block skip (the XLA backward cannot skip,
    costing ~2x FLOPs) and bf16 matmuls (the XLA backward runs fp32 at
    half MXU rate). GQA-native like the forward: q/do carry H query
    heads while k/v carry H_kv — the dq kernel streams shared kv blocks
    via h // G index maps, and the dkdv kernel sums each group IN its
    grid (see its docstring) instead of expanding K/V in HBM the way the
    XLA fallback must.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    kvlen = k.shape[2]
    scale = D ** -0.5
    # identical pre-scale to the forward: gradients through the matmul
    # are then w.r.t. scaled q, fixed up by one multiply at the end
    qs = (q.astype(jnp.float32) * scale).astype(q.dtype)
    do = do.astype(q.dtype)

    bq = min(block_q or DEFAULT_BWD_BLOCK_Q, -(-S // BLOCK) * BLOCK)
    bk = min(block_kv or DEFAULT_BWD_BLOCK_KV, -(-kvlen // BLOCK) * BLOCK)
    pad_q = (-S) % bq
    pad_k = (-kvlen) % bk
    qp = jnp.pad(qs, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    dop = jnp.pad(do, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    Sp, KVp = S + pad_q, kvlen + pad_k
    n_q, n_kv = Sp // bq, KVp // bk

    # residuals in the kernels' [.., 8, Sp] row-vector layout. lse of a
    # fully-masked (padding) row is -inf; clamp it to +1e30 so those
    # lanes get p = exp(s - 1e30) = exactly 0 — clamping to 0 (the XLA
    # path's choice) would leave p = exp(s), and an adversarially large
    # finite s could overflow p to inf, turning ds = p * 0 into NaN and
    # poisoning whole dk rows through the contraction
    lse_c = jnp.where(jnp.isfinite(lse), lse, 1e30)
    lse_p = jnp.pad(lse_c, ((0, 0), (0, 0), (0, pad_q)),
                    constant_values=1e30)  # padded q rows: p == 0 too
    lse_b = jnp.broadcast_to(lse_p[:, :, None, :], (B, H, 8, Sp))
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    delta_p = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q)))
    delta_b = jnp.broadcast_to(delta_p[:, :, None, :], (B, H, 8, Sp))

    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    kspec = pl.BlockSpec((1, 1, bk, D),
                         lambda b, h, i, j, G=G: (b, h // G, j, 0))
    rowspec = pl.BlockSpec((1, 1, 8, bq), lambda b, h, i, j: (b, h, 0, i))
    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary"))

    dqs = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, seq=kvlen, n_kv=n_kv,
                          causal=causal, block_q=bq, block_kv=bk,
                          window=window),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        grid=(B, H, n_q, n_kv),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=params,
        interpret=interpret,
    )(qp, kp, vp, dop, lse_b, delta_b)

    # dkdv grid: (B, H_kv, j, i, g) — kv-side blocks indexed by the kv
    # head, q-side blocks by the group member h = h_kv * G + g
    kspec_t = pl.BlockSpec((1, 1, bk, D),
                           lambda b, hk, j, i, g: (b, hk, j, 0))
    qspec_t = pl.BlockSpec((1, 1, bq, D),
                           lambda b, hk, j, i, g, G=G: (b, hk * G + g, i, 0))
    rowspec_t = pl.BlockSpec((1, 1, 8, bq),
                             lambda b, hk, j, i, g, G=G:
                             (b, hk * G + g, 0, i))
    params_t = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "parallel",
                             "arbitrary", "arbitrary"))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, seq=kvlen, n_q=n_q,
                          n_g=G, causal=causal, block_q=bq, block_kv=bk,
                          window=window),
        out_shape=(jax.ShapeDtypeStruct(kp.shape, k.dtype),
                   jax.ShapeDtypeStruct(vp.shape, v.dtype)),
        grid=(B, Hkv, n_kv, n_q, G),
        in_specs=[kspec_t, kspec_t, qspec_t, qspec_t, rowspec_t, rowspec_t],
        out_specs=(kspec_t, kspec_t),
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        compiler_params=params_t,
        interpret=interpret,
    )(kp, vp, qp, dop, lse_b, delta_b)

    dq = (dqs[:, :, :S].astype(jnp.float32) * scale).astype(q.dtype)
    return dq, dk[:, :, :kvlen], dv[:, :, :kvlen]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, interpret, block_q, block_kv, window, bwd_impl,
           fwd_impl):
    out, _ = _flash_call(q, k, v, causal, interpret, block_q, block_kv,
                         window, pipelined=fwd_impl == "pipelined")
    return out


def _flash_fwd(q, k, v, causal, interpret, block_q, block_kv, window,
               bwd_impl, fwd_impl):
    out, lse = _flash_call(q, k, v, causal, interpret, block_q, block_kv,
                           window, pipelined=fwd_impl == "pipelined")
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, interpret, block_q, block_kv, window, bwd_impl,
               fwd_impl, res, do):
    """Backward dispatch. ``bwd_impl`` ("xla" | "pallas") arrives as a
    nondiff argument resolved by :func:`_resolve_flash_bwd` at call time,
    so the selected backward is deterministic per trace — no cached-vjp
    hazard from reading the env here. "pallas" selects the kernel pair on
    compiled TPU paths (causal block skip + bf16 MXU + GQA-native grouped
    dkdv grid). Interpret mode always uses the XLA path (Pallas interpret
    of 4-matmul kernels is far slower than XLA on CPU test meshes).
    """
    q, k, v, out, lse = res
    if not interpret and bwd_impl == "pallas":
        # backward tiles are chosen independently of the forward's
        # (block_q/block_kv args tune the FORWARD; see DEFAULT_BWD_*).
        # GQA (grouped dkdv grid — no K/V expansion) and sliding-window
        # (floor block skip in both grid orders) are native.
        return _flash_bwd_pallas(q, k, v, out, lse, do, causal,
                                 interpret=False, window=window)
    return _flash_bwd_xla(causal, res, do, window=window)


def _flash_bwd_xla(causal, res, do, window: int | None = None):
    """Blockwise flash backward: scan over K/V blocks, regenerating each
    probability block from the saved LSE — residency stays O(S x BLOCK),
    nothing [S, S] is ever materialized (the point of training with the
    fused kernel). Runs as plain XLA ops: einsums land on the MXU and the
    scan body fuses. Known slack vs a hand-written Pallas backward: the
    causal case still multiplies the fully-masked rows above each block's
    diagonal (~2x the minimal backward matmul FLOPs), because skipping
    them would need a second blocking level over the query axis.
    """
    q, k, v, out, lse = res
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    g = H // Hkv
    if g > 1:
        # GQA: recompute with kv heads broadcast to the query heads, then
        # sum each group's dk/dv back down. This expands K/V in the
        # BACKWARD only (the forward kernel streams the small heads); a
        # grouped Pallas backward could avoid it if training memory ever
        # demands.
        k = jnp.repeat(k, g, axis=1)
        v = jnp.repeat(v, g, axis=1)
    kv = k.shape[2]
    scale = D ** -0.5

    pad_q = (-S) % BLOCK
    pad_k = (-kv) % BLOCK
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))).astype(jnp.float32)
    dop = jnp.pad(do, ((0, 0), (0, 0), (0, pad_q), (0, 0))).astype(jnp.float32)
    op = jnp.pad(out, ((0, 0), (0, 0), (0, pad_q), (0, 0))).astype(jnp.float32)
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0))).astype(jnp.float32)
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0))).astype(jnp.float32)
    # padded / no-visible-key rows carry lse = -inf; exp(s - -inf) would be
    # inf, so clamp — their do is zero, which zeroes every contribution
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)), constant_values=0.0)
    lsep = jnp.where(jnp.isfinite(lsep), lsep, 0.0)[..., None]   # [B,H,Sp,1]
    n_kv = (kv + pad_k) // BLOCK

    qs = qp * scale
    delta = jnp.sum(dop * op, axis=-1, keepdims=True)            # [B,H,Sp,1]
    row = jnp.arange(S + pad_q)

    # [n_kv, B, H, BLOCK, D] so lax.scan walks kv blocks
    kb_all = jnp.moveaxis(kp.reshape(B, H, n_kv, BLOCK, D), 2, 0)
    vb_all = jnp.moveaxis(vp.reshape(B, H, n_kv, BLOCK, D), 2, 0)

    def block(dq, xs):
        j, kb, vb = xs
        col = j * BLOCK + jnp.arange(BLOCK)
        mask = (col < kv)[None, :]
        if causal:
            mask = jnp.logical_and(mask, col[None, :] <= row[:, None])
            if window is not None:
                mask = jnp.logical_and(mask, sliding_window_mask(
                    row[:, None], col[None, :], window))
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kb)
        p = jnp.where(mask[None, None], jnp.exp(s - lsep), 0.0)
        dv_j = jnp.einsum("bhqk,bhqd->bhkd", p, dop)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dop, vb)
        ds = p * (dp - delta)
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kb) * scale
        dk_j = jnp.einsum("bhqk,bhqd->bhkd", ds, qp) * scale
        return dq, (dk_j, dv_j)

    dq, (dk_b, dv_b) = jax.lax.scan(
        block, jnp.zeros_like(qp), (jnp.arange(n_kv), kb_all, vb_all))
    dk = jnp.moveaxis(dk_b, 0, 2).reshape(B, H, kv + pad_k, D)
    dv = jnp.moveaxis(dv_b, 0, 2).reshape(B, H, kv + pad_k, D)
    dk, dv = dk[:, :, :kv], dv[:, :, :kv]
    if g > 1:
        dk = dk.reshape(B, Hkv, g, kv, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, g, kv, D).sum(axis=2)
    return (dq[:, :, :S].astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    interpret: bool | None = None,
                    block_q: int | None = None,
                    block_kv: int | None = None,
                    window: int | None = None,
                    bwd_impl: str | None = None,
                    fwd_impl: str | None = None) -> jax.Array:
    """Fused attention over [B, H, S, D] queries; k/v may carry fewer
    (GQA) heads — H_kv must divide H and is streamed, never expanded.

    Runs the Pallas TPU kernel natively on TPU backends and in interpret
    mode elsewhere (tests/CPU meshes) — same code path, same numerics.
    Differentiable: a custom VJP regenerates probabilities blockwise from
    the kernel's log-sum-exp residual, so training never materializes the
    [S, S] score matrix either.

    ``window=W`` (causal only): sliding-window/local attention — query i
    sees keys [max(0, i-W+1), i]. KV blocks entirely below the window
    floor are skipped like beyond-diagonal blocks, so per-query cost is
    O(W) regardless of sequence length (Mistral-style long-context
    serving); both backward paths (XLA scan and the default Pallas pair)
    apply the same floor skip and mask.

    ``bwd_impl``: "pallas" (kernel pair, the default on TPU — x1.72
    train fwd+bwd over the XLA scan, captured on chip 2026-07-31, 19/19
    tests_tpu green; interpret mode always runs the XLA path) or "xla"
    (blockwise scan, the escape hatch);
    ``None`` reads $TPUSHARE_FLASH_BWD when this function runs — part of
    its jit cache key for eager callers; under an outer jit the usual
    trace-time-closure caveat applies (see :func:`_resolve_flash_bwd`).
    """
    B, H, S, D = q.shape
    validate_gqa_qkv(q, k, v)
    if D > BLOCK:
        raise ValueError(f"head_dim {D} > {BLOCK} unsupported")
    if causal and k.shape[2] != S:
        raise ValueError("causal attention requires matching q/k lengths")
    for name, blk in (("block_q", block_q), ("block_kv", block_kv)):
        if blk is not None and (blk <= 0 or blk % BLOCK):
            raise ValueError(
                f"{name}={blk} must be a positive multiple of {BLOCK} "
                "(MXU tile alignment)")
    if window is not None:
        if not causal:
            raise ValueError("window attention requires causal=True")
        if window < 1:
            raise ValueError(f"window={window} must be >= 1")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # bwd_impl is resolved HERE, outside the jit boundary below, so an
    # env-default resolution happens per call in plain Python and the
    # resolved string is a static argument of the jit cache key.
    return _flash_attention_jit(q, k, v, bool(causal), bool(interpret),
                                block_q, block_kv, window,
                                _resolve_flash_bwd(bwd_impl),
                                _resolve_flash_fwd(fwd_impl))


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_attention_jit(q, k, v, causal, interpret, block_q, block_kv,
                         window, bwd_impl, fwd_impl):
    return _flash(q, k, v, causal, interpret, block_q, block_kv,
                  window, bwd_impl, fwd_impl)
