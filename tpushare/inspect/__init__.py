"""tpushare-inspect: cluster allocation CLI.

The analogue of ``kubectl inspect gpushare`` (reference sibling-repo
cmd/inspect, SURVEY §2.10; output format modeled on
/root/reference/docs/userguide.md:10-17). Reads the extender's /inspect
endpoint and renders the per-node / per-chip / per-pod allocation table.
Deployable as a kubectl plugin by dropping ``kubectl-inspect_tpushare``
(deployer/bin/) on PATH.
"""

from tpushare.inspect.cli import main, render_table

__all__ = ["main", "render_table"]
