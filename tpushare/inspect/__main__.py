import sys

from tpushare.inspect.cli import main

sys.exit(main())
