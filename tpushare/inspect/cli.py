"""Render the cluster TPU allocation tree as a terminal table."""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any


def fetch(endpoint: str, node: str | None = None) -> dict[str, Any]:
    url = endpoint.rstrip("/") + "/tpushare-scheduler/inspect"
    if node:
        url += f"/{node}"
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _fmt_row(cols: list[str], widths: list[int]) -> str:
    return "  ".join(c.ljust(w) for c, w in zip(cols, widths)).rstrip()


def render_table(tree: dict[str, Any], details: bool = False) -> str:
    """Cluster summary table (modeled on userguide.md:10-17's
    NAME/IPADDRESS/GPU-Memory table, extended with mesh/chip columns)."""
    lines: list[str] = []
    rows = [["NAME", "MESH", "CHIPS", "HEALTHY", "HBM USED/TOTAL (MiB)",
             "UTIL"]]
    for node in tree.get("nodes", []):
        healthy = node["chip_count"] - len(node.get("unhealthy_chips", []))
        total = node["total_hbm_mib"]
        used = node["used_hbm_mib"]
        util = f"{100.0 * used / total:.0f}%" if total else "-"
        rows.append([node["name"], node.get("mesh", "-"),
                     str(node["chip_count"]),
                     str(healthy), f"{used}/{total}", util])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines.extend(_fmt_row(r, widths) for r in rows)

    if details:
        for node in tree.get("nodes", []):
            lines.append("")
            lines.append(f"node {node['name']} (mesh {node.get('mesh', '-')}):")
            crows = [["  CHIP", "COORDS", "USED/TOTAL", "HEALTHY", "PODS"]]
            for chip in node.get("chips", []):
                pods = ", ".join(
                    f"{p.get('namespace', '?')}/{p.get('name', p['uid'][:8])}"
                    f"={p['hbm_mib']}"
                    + (f" [gang {p['gang']}#{p['gang_rank']}]"
                       if "gang" in p else "")
                    for p in chip.get("pods", [])) or "-"
                crows.append([
                    f"  {chip['idx']}",
                    "x".join(str(c) for c in chip.get("coords", [])),
                    f"{chip['used_hbm_mib']}/{chip['total_hbm_mib']}",
                    "yes" if chip.get("healthy", True) else "NO",
                    pods,
                ])
            cw = [max(len(r[i]) for r in crows) for i in range(len(crows[0]))]
            lines.extend(_fmt_row(r, cw) for r in crows)

    used, total = tree.get("used_hbm_mib", 0), tree.get("total_hbm_mib", 0)
    pct = f"{100.0 * used / total:.0f}%" if total else "-"
    lines.append("")
    # closing summary line matches the reference CLI's
    # "Allocated/Total GPU Memory In Cluster" footer (userguide.md:17)
    lines.append(
        f"Allocated/Total TPU HBM in Cluster: {used}/{total} MiB ({pct})")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpushare-inspect",
        description="Show per-node/per-chip TPU HBM allocation")
    ap.add_argument("-d", "--details", action="store_true",
                    help="per-chip and per-pod breakdown")
    ap.add_argument("--endpoint", default="http://127.0.0.1:39999",
                    help="extender base URL")
    ap.add_argument("node", nargs="?", default=None,
                    help="restrict to one node")
    args = ap.parse_args(argv)
    try:
        if args.node:
            tree = {"nodes": [fetch(args.endpoint, args.node)]}
            node = tree["nodes"][0]
            tree["used_hbm_mib"] = node.get("used_hbm_mib", 0)
            tree["total_hbm_mib"] = node.get("total_hbm_mib", 0)
        else:
            tree = fetch(args.endpoint)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"error: cannot reach extender at {args.endpoint}: {e}",
              file=sys.stderr)
        return 1
    print(render_table(tree, details=args.details or bool(args.node)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
