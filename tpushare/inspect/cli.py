"""Render the cluster TPU allocation tree as a terminal table, plus
the operator subcommands over the extender's diagnostic endpoints:

    tpushare-inspect                   # allocation table (default)
    tpushare-inspect <node>            # one node, per-chip detail
    tpushare-inspect fleet             # /inspect/fleet health snapshot
    tpushare-inspect defrag            # /inspect/defrag rebalancer state
    tpushare-inspect ring              # /inspect/ring shard membership
    tpushare-inspect gang              # /inspect/gang planner snapshot
    tpushare-inspect wire              # /inspect/wire serve-path caches
    tpushare-inspect qos               # /inspect/qos tier/eviction state
    tpushare-inspect explain [<pod>]   # /inspect/explain decision audit
    tpushare-inspect traces [-n N]     # /debug/traces flight recorder
    tpushare-inspect journal           # /inspect/journal black-box state
    tpushare-inspect metrics [--federated]  # /metrics[/federated] scrape

No hand-rolled curl: every JSON surface the extender serves has a CLI
verb (the fleet/explain/traces trio is rendered for terminals; raw
JSON is one `--json` away).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any


def fetch_path(endpoint: str, path: str) -> Any:
    with urllib.request.urlopen(endpoint.rstrip("/") + path,
                                timeout=10) as r:
        return json.loads(r.read())


def fetch_text(endpoint: str, path: str) -> str:
    """Raw text surface (/metrics is exposition format, not JSON)."""
    with urllib.request.urlopen(endpoint.rstrip("/") + path,
                                timeout=10) as r:
        return r.read().decode()


def fetch(endpoint: str, node: str | None = None) -> dict[str, Any]:
    path = "/tpushare-scheduler/inspect"
    if node:
        path += f"/{node}"
    return fetch_path(endpoint, path)


def _fmt_row(cols: list[str], widths: list[int]) -> str:
    return "  ".join(c.ljust(w) for c, w in zip(cols, widths)).rstrip()


def render_table(tree: dict[str, Any], details: bool = False) -> str:
    """Cluster summary table (modeled on userguide.md:10-17's
    NAME/IPADDRESS/GPU-Memory table, extended with mesh/chip columns)."""
    lines: list[str] = []
    rows = [["NAME", "MESH", "CHIPS", "HEALTHY", "HBM USED/TOTAL (MiB)",
             "UTIL"]]
    for node in tree.get("nodes", []):
        healthy = node["chip_count"] - len(node.get("unhealthy_chips", []))
        total = node["total_hbm_mib"]
        used = node["used_hbm_mib"]
        util = f"{100.0 * used / total:.0f}%" if total else "-"
        rows.append([node["name"], node.get("mesh", "-"),
                     str(node["chip_count"]),
                     str(healthy), f"{used}/{total}", util])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines.extend(_fmt_row(r, widths) for r in rows)

    if details:
        for node in tree.get("nodes", []):
            lines.append("")
            lines.append(f"node {node['name']} (mesh {node.get('mesh', '-')}):")
            crows = [["  CHIP", "COORDS", "USED/TOTAL", "HEALTHY", "PODS"]]
            for chip in node.get("chips", []):
                pods = ", ".join(
                    f"{p.get('namespace', '?')}/{p.get('name', p['uid'][:8])}"
                    f"={p['hbm_mib']}"
                    + (f" [gang {p['gang']}#{p['gang_rank']}]"
                       if "gang" in p else "")
                    for p in chip.get("pods", [])) or "-"
                crows.append([
                    f"  {chip['idx']}",
                    "x".join(str(c) for c in chip.get("coords", [])),
                    f"{chip['used_hbm_mib']}/{chip['total_hbm_mib']}",
                    "yes" if chip.get("healthy", True) else "NO",
                    pods,
                ])
            cw = [max(len(r[i]) for r in crows) for i in range(len(crows[0]))]
            lines.extend(_fmt_row(r, cw) for r in crows)

    used, total = tree.get("used_hbm_mib", 0), tree.get("total_hbm_mib", 0)
    pct = f"{100.0 * used / total:.0f}%" if total else "-"
    lines.append("")
    # closing summary line matches the reference CLI's
    # "Allocated/Total GPU Memory In Cluster" footer (userguide.md:17)
    lines.append(
        f"Allocated/Total TPU HBM in Cluster: {used}/{total} MiB ({pct})")
    return "\n".join(lines)


def render_fleet(snap: dict[str, Any]) -> str:
    """Terminal rendering of the /inspect/fleet health snapshot."""
    lines: list[str] = []
    util = snap.get("utilization_pct")
    lines.append(
        f"fleet: {snap.get('nodes_covered', 0)}/"
        f"{snap.get('nodes_total', 0)} nodes indexed, "
        f"{snap.get('used_hbm_mib', 0)}/{snap.get('total_hbm_mib', 0)} "
        f"MiB used"
        + (f" ({util}%)" if util is not None else ""))
    rows = [["TIER", "SCHEDULABLE", "CONTIGUOUS", "STRANDED MiB"]]
    for label, row in (snap.get("tiers") or {}).items():
        rows.append([label, str(row["schedulable_chips"]),
                     str(row["contiguous_chips"]),
                     str(row["stranded_hbm_mib"])])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines.extend(_fmt_row(r, widths) for r in rows)
    top = snap.get("top_fragmented") or []
    lines.append("")
    if top:
        lines.append(f"most fragmented nodes "
                     f"({snap.get('fragmented_nodes', len(top))} with a "
                     f"stranded gap):")
        for t in top:
            lines.append(
                f"  {t['node']}: {t['stranded_hbm_mib']} MiB stranded at "
                f"{t['tier']} ({t['eligible_chips']} eligible chips, "
                f"largest contiguous {t['largest_contiguous']})")
    else:
        lines.append("no stranded contiguous capacity")
    sc = snap.get("scorecard") or {}
    lines.append("")
    lines.append(
        f"scorecard: util {sc.get('time_weighted_util_pct')}% "
        f"(time-weighted), rejection rate {sc.get('rejection_rate')}, "
        f"p99 pending age {sc.get('p99_pending_age_s')} s "
        f"({sc.get('cycles', 0)} cycles, {sc.get('binds', 0)} binds, "
        f"{sc.get('pending', 0)} pending)")
    adj = snap.get("adjacency") or {}
    if adj.get("placements"):
        lines.append(
            f"adjacency: {adj['placements']} multi-chip placements, "
            f"mean quality {adj.get('mean_quality')}, "
            f"min {adj.get('min_quality')}, "
            f"{adj.get('scattered', 0)} scattered "
            "(1.0 = every placement is its chip count's best box)")
    audit = snap.get("audit") or {}
    drift = audit.get("drift_total") or {}
    total_drift = sum(drift.values())
    lines.append(
        f"drift auditor: {int(audit.get('sweeps_total', 0))} sweeps over "
        f"{int(audit.get('nodes_total', 0))} nodes, "
        + (f"DRIFT DETECTED: {drift}" if total_drift
           else "0 divergences"))
    return "\n".join(lines)


def render_defrag(snap: dict[str, Any]) -> str:
    """Terminal rendering of the /inspect/defrag rebalancer state."""
    lines: list[str] = []
    budget = snap.get("budget") or {}
    lines.append(
        f"defrag: {'running' if snap.get('running') else 'stopped'}, "
        f"{snap.get('passes', 0)} passes (period "
        f"{snap.get('period_s')} s), budget "
        f"{budget.get('used_in_window', 0)}/{budget.get('budget', 0)} "
        f"moves this {budget.get('window_s')} s window")
    for key, label in (("backoff_nodes", "backoff"),
                       ("inflight_nodes", "in flight")):
        nodes = budget.get(key) or []
        if nodes:
            lines.append(f"  {label}: {', '.join(nodes)}")
    plan = snap.get("plan")
    age = snap.get("plan_age_s")
    if plan is None:
        lines.append("no plan yet")
    else:
        n_slice = len(plan.get("slice_moves") or [])
        lines.append(
            f"last plan ({age} s ago): {plan.get('fragmented_nodes', 0)} "
            f"fragmented nodes, {plan.get('stranded_chips_before', 0)} "
            f"stranded chips, {len(plan.get('moves') or [])} moves"
            + (f" + {n_slice} slice moves" if n_slice else ""))
        for m in (plan.get("slice_moves") or []) \
                + (plan.get("moves") or []):
            if m.get("kind") == "slice":
                head = (
                    f"  gang {m.get('gang_id')}: "
                    f"{len(m.get('members') or [])} members over "
                    f"{', '.join(m.get('nodes') or [])} "
                    f"[slice, +{m.get('gain_chips')} chips at "
                    f"{m.get('tier')}]")
            else:
                head = (
                    f"  {m.get('pod_key')}: {m.get('source')}"
                    f"{list(m.get('victim_chip_ids') or [])} -> "
                    f"{m.get('target')}"
                    f"{list(m.get('target_chip_ids') or [])} "
                    f"[{m.get('mode')}, +{m.get('gain_chips')} chips at "
                    f"{m.get('tier')}]")
            # the execution outcome column: a demoted move must read
            # differently from a completed one (it moved NOTHING)
            outcome = m.get("outcome")
            if outcome:
                head += f" => {outcome}"
                if m.get("error"):
                    head += f" ({m['error']})"
            lines.append(head)
    moves = snap.get("recent_moves") or []
    lines.append("")
    if moves:
        lines.append(f"last {len(moves)} move outcomes:")
        for rec in moves:
            m = rec.get("move") or {}
            err = rec.get("error")
            if m.get("kind") == "slice":
                what = (f"gang {m.get('gang_id')} over "
                        f"{', '.join(m.get('nodes') or [])}")
            else:
                what = (f"{m.get('pod_key')} {m.get('source')} -> "
                        f"{m.get('target')}")
            lines.append(f"  {what}: {rec.get('outcome')}"
                         + (f" ({err})" if err else ""))
    else:
        lines.append("no moves executed yet")
    c = snap.get("counters") or {}
    totals = ", ".join(f"{k}={int(v)}" for k, v in sorted(
        (c.get("moves_total") or {}).items()))
    lines.append("")
    lines.append(
        f"counters: plans {c.get('plans_total') or {}}, "
        f"moves [{totals or 'none'}], "
        f"demotions {int(c.get('demotions_total', 0))}, "
        f"freed chips {int(c.get('freed_chips_total', 0))}")
    mig = ", ".join(f"{k}={int(v)}" for k, v in sorted(
        (c.get("migrations_total") or {}).items()))
    pause = snap.get("pause_s") or {}
    if mig or pause.get("count"):
        p50, p99 = pause.get("p50"), pause.get("p99")
        lines.append(
            f"migrations [{mig or 'none'}], pause p50 "
            f"{round(p50, 4) if p50 is not None else '-'} s / p99 "
            f"{round(p99, 4) if p99 is not None else '-'} s over "
            f"{pause.get('count', 0)} sessions")
    return "\n".join(lines)


def render_ring(snap: dict[str, Any]) -> str:
    """Terminal rendering of the /inspect/ring membership snapshot."""
    if snap.get("enabled") is False:
        return (f"sharding disabled "
                f"(mode: {snap.get('mode', 'single-replica')})")
    lines: list[str] = []
    members = snap.get("members") or []
    lines.append(
        f"ring: {len(members)} member(s), {snap.get('vnodes')} vnodes, "
        f"lease TTL {snap.get('lease_duration_s')} s, "
        f"{int(snap.get('rebalances_total', 0))} rebalance(s)")
    lines.append(
        f"this replica: {snap.get('identity')} "
        f"({'live' if snap.get('live') else 'NOT LIVE'}"
        + (", ring leader" if snap.get("ring_leader")
           == snap.get("identity") else "")
        + f"), {snap.get('owned_nodes', 0)} owned node(s), "
        f"{snap.get('pending_revalidation', 0)} pending revalidation")
    sizes = snap.get("shard_sizes") or {}
    peers = snap.get("peers") or {}
    rows = [["MEMBER", "SHARD NODES", "PEER URL", ""]]
    for m in members:
        tags = []
        if m == snap.get("ring_leader"):
            tags.append("leader")
        if m == snap.get("identity"):
            tags.append("self")
        rows.append([m, str(sizes.get(m, 0)), peers.get(m, "-"),
                     ",".join(tags)])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines.extend(_fmt_row(r, widths) for r in rows)
    c = snap.get("conflicts") or {}
    lines.append("")
    lines.append(
        f"bind outcomes: owned {int(c.get('owned', 0))} (lock-free), "
        f"spillover {int(c.get('spillover', 0))} (claim CAS), "
        f"cas_lost {int(c.get('cas_lost', 0))}")
    f = snap.get("forwards") or {}
    lines.append(
        f"forwards: forwarded {int(f.get('forwarded', 0))}, "
        f"served {int(f.get('served', 0))}, "
        f"loop_fallback {int(f.get('loop_fallback', 0))}, "
        f"peer_failed {int(f.get('peer_failed', 0))}")
    return "\n".join(lines)


def render_gang(snap: dict[str, Any]) -> str:
    """Terminal rendering of the /inspect/gang planner snapshot."""
    lines: list[str] = []
    plans = snap.get("plans") or []
    catalog = snap.get("catalog") or []
    lines.append(
        f"gang planner: {len(plans)} live plan(s), "
        f"{len(snap.get('provisional') or [])} provisional, "
        f"{len(catalog)} slice(s) in catalog")
    for s in catalog:
        grid = s.get("host_grid")
        lines.append(
            f"  slice {s.get('slice')}: {s.get('hosts', 0)} host(s), "
            + (f"host grid {'x'.join(str(d) for d in grid)}"
               if grid else "non-uniform tiling")
            + (", native arena" if s.get("native_arena")
               else ", sequential kernel"))
    if plans:
        lines.append("")
        rows = [["GANG", "SLICE", "SIZE", "BOUND", "DEMOTED", "ENGINE",
                 "SOURCE", "LEADER TRACE"]]
        for p in plans:
            rows.append([
                p.get("gang_id", "-"), p.get("slice", "-"),
                str(p.get("size", 0)),
                f"{len(p.get('bound') or [])}/{p.get('size', 0)}",
                str(len(p.get("demoted") or [])),
                p.get("engine") or "-", p.get("source", "-"),
                p.get("leader_trace_id") or "-"])
        widths = [max(len(r[i]) for r in rows)
                  for i in range(len(rows[0]))]
        lines.extend(_fmt_row(r, widths) for r in rows)
    else:
        lines.append("no live plans")
    solves = snap.get("solves") or {}
    members = snap.get("members") or {}
    lines.append("")
    lines.append(
        "solves: " + (", ".join(
            f"{k}={int(v)}" for k, v in sorted(solves.items()))
            or "none"))
    lines.append(
        "member binds: " + (", ".join(
            f"{k}={int(v)}" for k, v in sorted(members.items()))
            or "none"))
    return "\n".join(lines)


def render_wire(snap: dict[str, Any]) -> str:
    """Terminal rendering of the /inspect/wire serve-path snapshot:
    Python digest/response-cache occupancy, native table occupancy and
    hit rate, and the native/fallback/bypass serve split an operator
    alerts on (docs/ops.md: growing ``fallback`` means the steady state
    stopped being steady)."""
    lines: list[str] = []
    wc = snap.get("wirecache") or {}
    lines.append(
        f"wirecache: {'enabled' if wc.get('enabled') else 'DISABLED'}"
        + (", verify mode" if wc.get("verify") else "")
        + f", {wc.get('digests', 0)}/{wc.get('max_digests', 0)} digests, "
        f"{wc.get('responses', 0)} cached responses, "
        f"{int(wc.get('stale_serves', 0))} stale serves")
    dig = wc.get("digest_outcomes") or {}
    if dig:
        lines.append("  digest outcomes: " + ", ".join(
            f"{k}={int(v)}" for k, v in sorted(dig.items())))
    resp = wc.get("response_outcomes") or {}
    if resp:
        lines.append("  response outcomes: " + ", ".join(
            f"{k}={int(v)}" for k, v in sorted(resp.items())))
    nat = snap.get("native") or {}
    if not nat.get("enabled"):
        lines.append("native table: DISABLED (no ABI v6 engine, or "
                     "TPUSHARE_NO_NATIVE_WIRE=1)")
        return "\n".join(lines)
    hit_rate = nat.get("hit_rate")
    lines.append(
        f"native table: {nat.get('entries', 0)}/{nat.get('capacity', 0)} "
        f"entries, {nat.get('probes', 0)} probes, hit rate "
        + (f"{100.0 * hit_rate:.1f}%" if hit_rate is not None else "-")
        + (", verify mode" if nat.get("verify") else ""))
    lines.append(
        f"  hits {nat.get('hits', 0)}, misses {nat.get('misses', 0)} "
        f"(stamp-moved {nat.get('stamp_misses', 0)}), installs "
        f"{nat.get('installs', 0)}, evictions {nat.get('evictions', 0)}")
    outcomes = snap.get("native_outcomes") or {}
    lines.append("serve outcomes: " + (", ".join(
        f"{k}={int(v)}" for k, v in sorted(outcomes.items()))
        or "none"))
    return "\n".join(lines)


def render_qos(snap: dict[str, Any]) -> str:
    """Terminal rendering of the /inspect/qos tier-plane snapshot:
    overcommit knobs and their effective values, per-tier fleet usage,
    oversubscribed nodes, the eviction budget/backoff state, and each
    tenant's DRF dominant share (docs/ops.md runbook surface)."""
    lines: list[str] = []
    oc = snap.get("overcommit", 1.0)
    eff = snap.get("effective_overcommit", oc)
    lines.append(
        f"qos: overcommit {oc}"
        + (f" (EFFECTIVE {eff}: evictor degraded — oversubscribed "
           "admissions stopped)" if snap.get("evictor_degraded")
           else (" (active)" if oc > 1.0 else " (off)"))
        + f", DRF cap {snap.get('drf_cap', 1.0)}")
    fleet = snap.get("fleet") or {}
    by_tier = fleet.get("by_tier_hbm_mib") or {}
    rows = [["TIER", "HBM USED (MiB)"]]
    for tier in ("guaranteed", "burstable", "best-effort"):
        if tier in by_tier:
            rows.append([tier, str(by_tier[tier])])
    for tier in sorted(set(by_tier) - {"guaranteed", "burstable",
                                       "best-effort"}):
        rows.append([tier, str(by_tier[tier])])
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines.extend(_fmt_row(r, widths) for r in rows)
    lines.append(
        f"reclaimable (best-effort, evictable): "
        f"{fleet.get('reclaimable_hbm_mib', 0)} MiB of "
        f"{fleet.get('total_hbm_mib', 0)} MiB physical")
    over = snap.get("oversubscribed_nodes") or {}
    if over:
        lines.append(f"oversubscribed nodes "
                     f"({fleet.get('oversubscribed_hbm_mib', 0)} MiB "
                     "borrowed beyond physical):")
        for node, mib in sorted(over.items()):
            lines.append(f"  {node}: {mib} MiB over")
    else:
        lines.append("no node oversubscribed")
    ev = snap.get("eviction") or {}
    lines.append("")
    lines.append(
        f"evictions: {ev.get('used_in_window', 0)}/{ev.get('budget', 0)} "
        f"this {ev.get('window_s')} s window, "
        f"{int(ev.get('consecutive_failures', 0))} consecutive failure(s)")
    for key, label in (("backoff_nodes", "backoff"),
                       ("inflight_nodes", "in flight")):
        nodes = ev.get(key) or []
        if nodes:
            lines.append(f"  {label}: {', '.join(nodes)}")
    shares = snap.get("tenant_dominant_share") or {}
    lines.append("")
    if shares:
        lines.append("tenant dominant shares (DRF):")
        for ns, s in sorted(shares.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {ns}: {100.0 * s:.1f}%")
    else:
        lines.append("no tenant usage")
    return "\n".join(lines)


def render_journal(snap: dict[str, Any]) -> str:
    """Terminal rendering of the /inspect/journal black-box snapshot:
    ring pump health (the zero-Python fast path's telemetry), decision-
    journal files and recorded aggregate, federation slot state — the
    one-read answer to "is the flight data actually being recorded"."""
    lines: list[str] = []
    bb = snap.get("blackbox") or {}
    ring = bb.get("ring") or {}
    if not bb.get("supported"):
        lines.append("black box: UNSUPPORTED (pre-v8 .so or "
                     "TPUSHARE_BLACKBOX=0) — native fast-path serves "
                     "are not recorded")
    else:
        lines.append(
            f"black box: {'running' if bb.get('running') else 'STOPPED'}, "
            f"{int(bb.get('events_total', 0))} events drained "
            f"(period {bb.get('period_s')} s), "
            f"{int(ring.get('dropped_total', 0))} dropped, "
            f"{int(ring.get('pending', 0))}/"
            f"{int(ring.get('capacity', 0))} pending in ring, "
            f"{int(bb.get('digest_map_entries', 0))} digest-map entries")
    j = snap.get("journal") or {}
    if not j.get("enabled"):
        lines.append("journal: disabled (set TPUSHARE_JOURNAL_DIR to "
                     "record an incident journal)")
    else:
        rec = j.get("recorded") or {}
        lines.append(
            f"journal: {j.get('directory')} "
            f"({len(j.get('files') or [])} file(s), "
            f"{int(j.get('bytes', 0))}/{int(j.get('max_bytes', 0))} "
            f"bytes), {int(j.get('written', 0))} written, "
            f"{int(j.get('buffered', 0))} buffered, "
            f"{int(j.get('dropped', 0))} dropped")
        lines.append(
            f"  recorded: {int(rec.get('pods', 0))} pod(s) — "
            f"{int(rec.get('admitted', 0))} admitted, "
            f"{int(rec.get('rejected', 0))} rejected; "
            f"{int(rec.get('binds', 0))} bind(s), "
            f"{int(rec.get('bind_failures', 0))} failed")
        lines.append(
            f"  replay: python -m tpushare.sim --replay "
            f"{j.get('directory')}")
    f = snap.get("federation") or {}
    if not f.get("enabled"):
        lines.append("federation: disabled")
    else:
        lines.append(
            f"federation: slot {f.get('slot')} of {f.get('nslots')} "
            f"(pid {f.get('pid')}), {int(f.get('publishes', 0))} "
            f"publish(es), {int(f.get('publish_errors', 0))} error(s), "
            f"period {f.get('period_s')} s")
    return "\n".join(lines)


def render_traces(dump: dict[str, Any], limit: int | None = None) -> str:
    """Terminal rendering of the /debug/traces flight recorder."""
    lines: list[str] = []
    traces = dump.get("traces") or []
    pinned = dump.get("pinned") or []
    if limit is not None:
        traces = traces[:limit]
    lines.append(f"{len(traces)} recent traces, {len(pinned)} pinned "
                 f"slow, {dump.get('recorded_total', 0)} recorded total")
    for t in traces:
        spans = " ".join(
            f"{s.get('name')}={s.get('duration_ms', 0):.1f}ms"
            for s in t.get("spans") or [])
        lines.append(f"  {t.get('trace_id')} [{t.get('outcome')}] "
                     f"{t.get('duration_ms', 0):.1f}ms  {spans}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpushare-inspect",
        description="Show per-node/per-chip TPU HBM allocation and the "
                    "extender's diagnostic surfaces (fleet / explain / "
                    "traces subcommands)")
    ap.add_argument("-d", "--details", action="store_true",
                    help="per-chip and per-pod breakdown")
    ap.add_argument("--endpoint", default="http://127.0.0.1:39999",
                    help="extender base URL")
    ap.add_argument("--json", action="store_true",
                    help="print the raw JSON instead of a table")
    ap.add_argument("-n", "--limit", type=int, default=None,
                    help="traces: show at most N traces")
    ap.add_argument("--federated", action="store_true",
                    help="metrics: scrape /metrics/federated (counters "
                         "and histograms merged across every replica "
                         "publishing into the shared segment) instead "
                         "of this replica's /metrics")
    ap.add_argument("target", nargs="*", default=[],
                    help="node name, or a subcommand: 'fleet', 'defrag', "
                         "'ring', 'gang', 'wire', 'qos', 'explain [pod]', "
                         "'traces', 'journal', 'metrics'")
    args = ap.parse_args(argv)
    cmd = args.target[0] if args.target else None
    try:
        if cmd == "fleet":
            snap = fetch_path(args.endpoint, "/inspect/fleet")
            print(json.dumps(snap, indent=2) if args.json
                  else render_fleet(snap))
            return 0
        if cmd == "defrag":
            snap = fetch_path(args.endpoint, "/inspect/defrag")
            print(json.dumps(snap, indent=2) if args.json
                  else render_defrag(snap))
            return 0
        if cmd == "ring":
            snap = fetch_path(args.endpoint, "/inspect/ring")
            print(json.dumps(snap, indent=2) if args.json
                  else render_ring(snap))
            return 0
        if cmd == "gang":
            snap = fetch_path(args.endpoint, "/inspect/gang")
            print(json.dumps(snap, indent=2) if args.json
                  else render_gang(snap))
            return 0
        if cmd == "wire":
            snap = fetch_path(args.endpoint, "/inspect/wire")
            print(json.dumps(snap, indent=2) if args.json
                  else render_wire(snap))
            return 0
        if cmd == "qos":
            snap = fetch_path(args.endpoint, "/inspect/qos")
            print(json.dumps(snap, indent=2) if args.json
                  else render_qos(snap))
            return 0
        if cmd == "explain":
            path = "/inspect/explain"
            if len(args.target) > 1:
                path += "/" + args.target[1]
            try:
                out = fetch_path(args.endpoint, path)
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    print(f"no decision record for "
                          f"{args.target[1]!r}", file=sys.stderr)
                    return 1
                raise
            # decision records are nested per-cycle trees; JSON is the
            # honest rendering (the table would lie by omission)
            print(json.dumps(out, indent=2))
            return 0
        if cmd == "journal":
            snap = fetch_path(args.endpoint, "/inspect/journal")
            print(json.dumps(snap, indent=2) if args.json
                  else render_journal(snap))
            return 0
        if cmd == "metrics":
            path = "/metrics/federated" if args.federated else "/metrics"
            # already text exposition format: print verbatim (--json has
            # nothing to add — the scrape IS the raw surface)
            print(fetch_text(args.endpoint, path), end="")
            return 0
        if cmd == "traces":
            path = "/debug/traces"
            if args.limit is not None:
                path += f"?n={args.limit}"
            dump = fetch_path(args.endpoint, path)
            print(json.dumps(dump, indent=2) if args.json
                  else render_traces(dump, args.limit))
            return 0
        node = cmd  # plain node name (or None = whole cluster)
        if node:
            tree = {"nodes": [fetch(args.endpoint, node)]}
            n = tree["nodes"][0]
            tree["used_hbm_mib"] = n.get("used_hbm_mib", 0)
            tree["total_hbm_mib"] = n.get("total_hbm_mib", 0)
        else:
            tree = fetch(args.endpoint)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"error: cannot reach extender at {args.endpoint}: {e}",
              file=sys.stderr)
        return 1
    print(render_table(tree, details=args.details or bool(node)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
