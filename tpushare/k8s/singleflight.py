"""Singleflight: coalesce concurrent identical apiserver calls.

A gang storm (N members of one gang hitting Allocate/Bind within the same
watch-lag window) used to issue N identical LISTs/GETs — each one a full
apiserver round-trip carrying the same answer. With singleflight, the
first caller for a key becomes the *leader* and executes the upstream
call; every concurrent caller for the same key parks on the leader's
event and shares its result (or its exception). The key leaves the table
as soon as the leader finishes, so sequential calls are never served
stale data — this is request coalescing, not a cache.

Mirrors golang.org/x/sync/singleflight, which client-go-based schedulers
lean on for exactly this fan-in.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from tpushare.metrics import LabeledCounter

# process-wide: every Singleflight instance reports here so one scrape
# (and bench.py) sees the whole coalescing picture. outcome=leader is an
# upstream call that actually happened; outcome=shared is a round-trip
# that singleflight saved.
SINGLEFLIGHT_TOTAL = LabeledCounter(
    "tpushare_singleflight_total",
    "Coalesced-call outcomes: leader = upstream call executed, "
    "shared = concurrent duplicate served from the leader's result",
    ("outcome",))


class _Call:
    __slots__ = ("event", "result", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result: Any = None
        self.error: BaseException | None = None


class Singleflight:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._calls: dict[str, _Call] = {}

    def do(self, key: str, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` once per concurrent burst of callers sharing
        ``key``; every caller gets the leader's result or exception."""
        with self._lock:
            call = self._calls.get(key)
            if call is not None:
                leader = False
            else:
                call = _Call()
                self._calls[key] = call
                leader = True
        if not leader:
            SINGLEFLIGHT_TOTAL.inc("shared")
            call.event.wait()
            if call.error is not None:
                raise call.error
            return call.result
        SINGLEFLIGHT_TOTAL.inc("leader")
        try:
            call.result = fn()
        except BaseException as e:
            call.error = e
            raise
        finally:
            # remove BEFORE waking waiters: a caller arriving after the
            # leader finished must start a fresh upstream call (coalescing
            # only within a burst — never serving stale results)
            with self._lock:
                self._calls.pop(key, None)
            call.event.set()
        return call.result
