"""Circuit breaker over the cluster-client surface.

During an apiserver brownout every write pays its full retry budget
before failing — N binds in flight means N * budget doomed round-trips
against a server that is already drowning, and every Bind burns most of
the kube-scheduler's webhook timeout before reporting anything. The
breaker converts that into *fault containment*:

- **closed** — traffic flows; consecutive transport-level failures
  (network errors, 5xx, 429) are counted, any success resets the count;
- **open** — after ``failure_threshold`` consecutive failures (or the
  rolling error rate crossing ``error_rate_threshold`` with enough
  samples) calls fail fast with :class:`BreakerOpenError` and zero
  round-trips, for ``reset_timeout_s``;
- **half-open** — after the cooldown, up to ``probe_calls`` trial calls
  pass through; ``probe_successes`` consecutive successes close the
  breaker, any failure re-opens it.

What counts as a failure is deliberately narrow: 409/404/403 are
*successful communication* carrying a correctness verdict — only
status 0 (network), 5xx, and 429 indicate the apiserver itself is in
trouble.

Degraded mode while open (wired in extender/server.py + handlers.py):
Filter/Prioritize keep serving from the informer-warmed cache (their
verdicts are cache reads; the staleness bound is whatever the informer
reports), Bind fails fast with the distinct BreakerOpenError instead of
burning the webhook timeout, and the device plugin's write paths
queue-and-retry behind the same breaker on their periodic loops.

Layering: :func:`harden` composes the canonical stack
``RetryingCluster(BreakerCluster(inner))`` — the breaker sits INSIDE the
retry loop so every real attempt reports one outcome to it, and a
breaker-open fast-fail is classified non-retryable and surfaces
immediately instead of being retried against a known-bad server.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable

from tpushare.k8s.client import ApiError
from tpushare.metrics import Counter, LabeledCounter
from tpushare.obs.trace import annotate_current

BREAKER_TRANSITIONS = LabeledCounter(
    "tpushare_breaker_transitions_total",
    "Circuit-breaker state transitions (open->half_open->closed is the "
    "healthy recovery path; repeated closed->open flapping means the "
    "apiserver is oscillating)",
    ("from_state", "to_state"))
BREAKER_FASTFAIL = Counter(
    "tpushare_breaker_fastfail_total",
    "Calls refused locally (zero round-trips) while the breaker was open")

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class BreakerOpenError(ApiError):
    """Fail-fast refusal: the apiserver is considered down and this call
    was never sent. Distinct from a real apiserver error so Bind can
    answer the webhook immediately with an honest reason. Never retried
    (is_retryable special-cases it) — retrying a refusal would just spin
    on the local breaker."""

    breaker_open = True  # retry.is_retryable keys on this, not the type
    # (no import edge: breaker -> retry exists only lazily in harden())

    def __init__(self, message: str):
        super().__init__(0, message)


def _is_transport_failure(e: ApiError) -> bool:
    # BreakerOpenError is status 0 but represents NO round-trip: it must
    # not feed back into the failure count that opened the breaker.
    if isinstance(e, BreakerOpenError):
        return False
    return e.status == 0 or e.status == 429 or e.status >= 500


class CircuitBreaker:
    """State machine + outcome accounting, shared by every verb of one
    cluster client (the apiserver is one backend; per-verb breakers
    would each have to rediscover the same outage)."""

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 5.0,
                 probe_calls: int = 2,
                 probe_successes: int = 2,
                 error_rate_threshold: float | None = 0.5,
                 window: int = 20,
                 min_samples: int = 10,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.probe_calls = probe_calls
        self.probe_successes = probe_successes
        self.error_rate_threshold = error_rate_threshold
        self.min_samples = min_samples
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = 0
        self._probe_ok = 0
        self._outcomes: collections.deque[bool] = collections.deque(
            maxlen=window)

    # -- observability --------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def state_value(self) -> float:
        """0 = closed, 1 = half-open, 2 = open (the breaker_state gauge)."""
        return _STATE_VALUE[self.state]

    # -- state machine --------------------------------------------------------

    def _transition_locked(self, to: str) -> None:
        if to == self._state:
            return
        BREAKER_TRANSITIONS.inc(self._state, to)
        self._state = to
        if to == OPEN:
            self._opened_at = self._clock()
        elif to == HALF_OPEN:
            self._probe_inflight = 0
            self._probe_ok = 0
        elif to == CLOSED:
            self._consecutive_failures = 0
            self._outcomes.clear()

    def _maybe_half_open_locked(self) -> None:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.reset_timeout_s:
            self._transition_locked(HALF_OPEN)

    def allow(self) -> bool:
        """Admission check for one call; half-open admits at most
        ``probe_calls`` concurrent probes (the rest fail fast so a
        thundering herd cannot re-drown a recovering apiserver)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return False
            if self._probe_inflight >= self.probe_calls:
                return False
            self._probe_inflight += 1
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._outcomes.append(True)
            if self._state == HALF_OPEN:
                self._probe_inflight = max(0, self._probe_inflight - 1)
                self._probe_ok += 1
                if self._probe_ok >= self.probe_successes:
                    self._transition_locked(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # the probe proved the backend is still down
                self._probe_inflight = max(0, self._probe_inflight - 1)
                self._transition_locked(OPEN)
                return
            self._consecutive_failures += 1
            self._outcomes.append(False)
            trip = self._consecutive_failures >= self.failure_threshold
            if not trip and self.error_rate_threshold is not None \
                    and len(self._outcomes) >= self.min_samples:
                failures = sum(1 for ok in self._outcomes if not ok)
                trip = failures / len(self._outcomes) \
                    >= self.error_rate_threshold
            if trip and self._state == CLOSED:
                self._transition_locked(OPEN)


# watches are exempt: a breaker-refused watch would silently freeze the
# informer, which is the exact component degraded mode depends on
_GUARDED_VERBS = frozenset({
    "list_pods", "get_pod", "list_nodes", "get_node", "get_configmap",
    "patch_pod", "replace_pod", "bind_pod", "create_event", "patch_node",
    "put_configmap", "get_lease", "create_lease", "update_lease",
    "list_leases", "forward_post",
})


class BreakerCluster:
    """Transparent ClusterClient proxy feeding call outcomes into a
    shared :class:`CircuitBreaker` and fail-fasting while it is open."""

    def __init__(self, inner: Any,
                 breaker: CircuitBreaker | None = None) -> None:
        self._inner = inner
        self.breaker = breaker or CircuitBreaker()

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._inner, name)
        if name not in _GUARDED_VERBS or not callable(attr):
            return attr

        def guarded(*args: Any, **kwargs: Any) -> Any:
            if not self.breaker.allow():
                BREAKER_FASTFAIL.inc()
                annotate_current("breaker_fastfail", verb=name,
                                 state=self.breaker.state)
                raise BreakerOpenError(
                    f"{name}: apiserver circuit open (failing fast; "
                    f"reset probe in <= {self.breaker.reset_timeout_s}s)")
            try:
                result = attr(*args, **kwargs)
            except ApiError as e:
                if _is_transport_failure(e):
                    self.breaker.record_failure()
                else:
                    self.breaker.record_success()  # server answered
                raise
            self.breaker.record_success()
            return result
        return guarded


def harden(cluster: Any, breaker: CircuitBreaker | None = None,
           policy=None):
    """The canonical fault-containment stack over any cluster client:
    retries outside, breaker inside, so each real attempt is one breaker
    outcome and an open breaker stops the retry loop immediately.
    Returns the wrapped client; reach the breaker via ``.breaker`` on
    the inner proxy or pass your own instance."""
    from tpushare.k8s.retry import RetryingCluster
    return RetryingCluster(BreakerCluster(cluster, breaker), policy)


def register_breaker_gauge(registry, breaker: CircuitBreaker) -> None:
    """Expose ``tpushare_breaker_state`` (0 closed / 1 half-open /
    2 open) plus the transition/fast-fail counters on a Registry."""
    registry.gauge_func(
        "tpushare_breaker_state",
        "Apiserver circuit state: 0 closed, 1 half-open, 2 open "
        "(alert on sustained 2: binds are failing fast and Filter "
        "serves from the informer cache)",
        lambda: [("", breaker.state_value())])
    registry.register(BREAKER_TRANSITIONS)
    registry.register(BREAKER_FASTFAIL)
