"""In-memory apiserver for hermetic tests and the bench suite.

Implements the :class:`tpushare.k8s.client.ClusterClient` protocol with real
apiserver semantics where they matter to the scheduler:

- resourceVersion bumps on every mutation; patch/bind take an optional UID
  precondition and fail 409 on mismatch — exercising the extender's
  optimistic-lock retry (reference: nodeinfo.go:202-218 retries once on
  conflict).
- bind on an already-bound pod fails 409 (kubelet/apiserver behavior); bind
  on a missing pod 404.
- every mutation fans out WatchEvents to open watch streams, so the
  controller's informer loop is tested against the same event flow a real
  cluster produces.

Also provides seeding helpers (`add_tpu_node`, `create_pod`) used by tests,
bench.py, and the extender's `--fake` development mode.
"""

from __future__ import annotations

import copy
import itertools
import json
import queue
import threading
import uuid
from typing import Any, Iterator

from tpushare.contract.constants import (
    LABEL_MESH,
    RESOURCE_COUNT,
    RESOURCE_HBM,
)
from tpushare.k8s.client import ApiError, WatchEvent, strategic_merge

# queued into a live watch stream by break_watches(): the consumer side
# raises mid-iteration, exactly like a dropped apiserver connection
_SEVER = object()


class FakeCluster:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rv = itertools.count(1)
        self._pods: dict[str, dict[str, Any]] = {}      # ns/name -> pod
        self._nodes: dict[str, dict[str, Any]] = {}
        self._configmaps: dict[str, dict[str, Any]] = {}  # ns/name -> cm
        self._leases: dict[str, dict[str, Any]] = {}
        self._events: list[dict[str, Any]] = []
        self._watchers: dict[str, list[queue.Queue]] = {
            "pods": [], "nodes": [], "configmaps": []}
        self._partitioned: set[str] = set()

    # -- internal ------------------------------------------------------------

    def _bump(self, obj: dict[str, Any]) -> None:
        obj.setdefault("metadata", {})["resourceVersion"] = str(next(self._rv))

    def _notify(self, kind: str, etype: str, obj: dict[str, Any]) -> None:
        # one isolated copy per WATCHER (not one shared copy, and none
        # at all with no watchers): consumers never see the live object
        # or each other's, and an unwatched cluster pays nothing — the
        # unconditional deepcopy was ~40% of a hermetic bind cycle
        for q in list(self._watchers[kind]):
            q.put(WatchEvent(etype, copy.deepcopy(obj)))

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    def _check_partition(self, node_name: str) -> None:
        if node_name in self._partitioned:
            raise ApiError(503, f"node {node_name} partitioned (chaos)")

    # -- chaos primitives ----------------------------------------------------

    def break_watches(self) -> int:
        """Sever every live watch stream once — the consumer's iterator
        raises mid-iteration, exactly like a dropped apiserver
        connection. New watches connect normally, so an informer's
        backoff -> relist healing path is what gets exercised. Returns
        the number of streams severed."""
        with self._lock:
            queues = [q for qs in self._watchers.values() for q in qs]
        for q in queues:
            q.put(_SEVER)
        return len(queues)

    def partition(self, node_name: str) -> None:
        """Node-scoped network partition: every verb that names this
        node (get/patch/bind) fails 503 until :meth:`heal` — the shape
        of a rack losing its uplink while the apiserver stays up."""
        with self._lock:
            self._partitioned.add(node_name)

    def heal(self, node_name: str | None = None) -> None:
        """Lift a node partition (all of them when ``node_name`` is
        None)."""
        with self._lock:
            if node_name is None:
                self._partitioned.clear()
            else:
                self._partitioned.discard(node_name)

    # -- seeding helpers -----------------------------------------------------

    def add_tpu_node(self, name: str, chips: int, hbm_per_chip_mib: int,
                     mesh: str | None = None,
                     slice_id: str | None = None,
                     slice_origin: str | None = None) -> dict[str, Any]:
        """Register a TPU host the way the device plugin would: aggregate
        tpu-hbm, tpu-count, and the mesh topology label (designs.md:57-63
        reports count x mem through ListAndWatch). ``slice_id`` +
        ``slice_origin`` ("RxC") label the host into a multi-host ICI
        slice for gang placement."""
        labels = ({LABEL_MESH: mesh} if mesh else {}) | {"tpushare": "true"}
        if slice_id is not None and slice_origin is not None:
            from tpushare.contract import LABEL_SLICE, LABEL_SLICE_ORIGIN
            labels |= {LABEL_SLICE: slice_id,
                       LABEL_SLICE_ORIGIN: slice_origin}
        node = {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {
                "name": name,
                "labels": labels,
            },
            "status": {
                "allocatable": {
                    RESOURCE_HBM: str(chips * hbm_per_chip_mib),
                    RESOURCE_COUNT: str(chips),
                },
                "capacity": {
                    RESOURCE_HBM: str(chips * hbm_per_chip_mib),
                    RESOURCE_COUNT: str(chips),
                },
            },
        }
        with self._lock:
            self._bump(node)
            self._nodes[name] = node
            self._notify("nodes", "ADDED", node)
        return copy.deepcopy(node)

    def create_pod(self, pod: dict[str, Any]) -> dict[str, Any]:
        # defaulting, uid generation (a urandom syscall) and the
        # isolating input copy all happen OUTSIDE the store lock: the
        # single fake-apiserver lock is the hermetic bench's convoy
        # point, and only the dict insert + notify need it. The copy
        # also stops the store from aliasing the CALLER's dict (a
        # caller mutating its pod after create must not edit ours).
        pod = copy.deepcopy(pod)
        meta = pod.setdefault("metadata", {})
        meta.setdefault("namespace", "default")
        meta.setdefault("uid", str(uuid.uuid4()))
        pod.setdefault("status", {}).setdefault("phase", "Pending")
        key = self._key(meta["namespace"], meta["name"])
        with self._lock:
            if key in self._pods:
                raise ApiError(409, f"pod {key} already exists")
            self._bump(pod)
            self._pods[key] = pod
            self._notify("pods", "ADDED", pod)
            return copy.deepcopy(pod)

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        with self._lock:
            pod = self._pods.get(self._key(namespace, name))
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{name}")
            pod["status"]["phase"] = phase
            self._bump(pod)
            self._notify("pods", "MODIFIED", pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            pod = self._pods.pop(self._key(namespace, name), None)
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{name}")
            self._notify("pods", "DELETED", pod)

    def delete_configmap(self, namespace: str, name: str) -> None:
        with self._lock:
            cm = self._configmaps.pop(self._key(namespace, name), None)
            if cm is not None:
                self._notify("configmaps", "DELETED", cm)

    def set_configmap(self, namespace: str, name: str,
                      data: dict[str, str]) -> None:
        with self._lock:
            cm = {
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": namespace},
                "data": dict(data),
            }
            self._bump(cm)
            self._configmaps[self._key(namespace, name)] = cm
            self._notify("configmaps", "MODIFIED", cm)

    # -- ClusterClient reads -------------------------------------------------

    def list_pods(self, node_name: str | None = None,
                  namespace: str | None = None) -> list[dict[str, Any]]:
        with self._lock:
            pods = list(self._pods.values())
        if node_name:
            pods = [p for p in pods
                    if (p.get("spec") or {}).get("nodeName") == node_name]
        if namespace:
            pods = [p for p in pods
                    if (p.get("metadata") or {}).get("namespace")
                    == namespace]
        return copy.deepcopy(pods)

    def get_pod(self, namespace: str, name: str) -> dict[str, Any]:
        with self._lock:
            pod = self._pods.get(self._key(namespace, name))
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{name}")
            return copy.deepcopy(pod)

    def peek_pod(self, namespace: str, name: str) -> dict[str, Any] | None:
        """Watch-warmed-lister analogue for hermetic rigs: the STORED
        pod object by reference, no copy and no simulated round-trip —
        the same read a production informer lister serves (its handlers
        also receive the store's object). Read-only by contract; None on
        a miss (the caller falls back to the GET path, like a lister)."""
        with self._lock:
            return self._pods.get(self._key(namespace, name))

    def list_nodes(self) -> list[dict[str, Any]]:
        with self._lock:
            return copy.deepcopy(list(self._nodes.values()))

    def get_node(self, name: str) -> dict[str, Any]:
        with self._lock:
            self._check_partition(name)
            node = self._nodes.get(name)
            if node is None:
                raise ApiError(404, f"node {name}")
            return copy.deepcopy(node)

    def get_configmap(self, namespace: str, name: str) -> dict[str, Any]:
        with self._lock:
            cm = self._configmaps.get(self._key(namespace, name))
            if cm is None:
                raise ApiError(404, f"configmap {namespace}/{name}")
            return copy.deepcopy(cm)

    # -- ClusterClient writes ------------------------------------------------

    def patch_pod(self, namespace: str, name: str,
                  patch: dict[str, Any]) -> dict[str, Any]:
        with self._lock:
            key = self._key(namespace, name)
            pod = self._pods.get(key)
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{name}")
            # metadata.resourceVersion in a merge-patch body is an
            # optimistic-concurrency precondition (real apiserver behavior)
            want_rv = (patch.get("metadata") or {}).get("resourceVersion")
            if want_rv is not None and \
                    want_rv != pod["metadata"].get("resourceVersion"):
                raise ApiError(409, f"pod {namespace}/{name}: "
                                    f"resourceVersion conflict")
            merged = strategic_merge(pod, json.loads(json.dumps(patch)))
            self._bump(merged)
            self._pods[key] = merged
            self._notify("pods", "MODIFIED", merged)
            return copy.deepcopy(merged)

    def replace_pod(self, namespace: str, name: str,
                    pod: dict[str, Any]) -> dict[str, Any]:
        """PUT semantics: optimistic concurrency on metadata.resourceVersion
        (409 on mismatch) — the CAS the stale-placement reclaim relies on."""
        with self._lock:
            key = self._key(namespace, name)
            cur = self._pods.get(key)
            if cur is None:
                raise ApiError(404, f"pod {namespace}/{name}")
            want_rv = (pod.get("metadata") or {}).get("resourceVersion")
            have_rv = (cur.get("metadata") or {}).get("resourceVersion")
            if want_rv is not None and want_rv != have_rv:
                raise ApiError(409,
                               f"resourceVersion {want_rv} != {have_rv}")
            new = json.loads(json.dumps(pod))
            self._bump(new)
            self._pods[key] = new
            self._notify("pods", "MODIFIED", new)
            return copy.deepcopy(new)

    def bind_pod(self, namespace: str, name: str, node: str,
                 uid: str | None = None) -> None:
        with self._lock:
            self._check_partition(node)
            pod = self._pods.get(self._key(namespace, name))
            if pod is None:
                raise ApiError(404, f"pod {namespace}/{name}")
            if uid is not None and pod["metadata"].get("uid") != uid:
                raise ApiError(409, "uid precondition failed")
            if node not in self._nodes:
                raise ApiError(404, f"node {node}")
            if pod.get("spec", {}).get("nodeName"):
                raise ApiError(409, f"pod {namespace}/{name} already bound")
            pod.setdefault("spec", {})["nodeName"] = node
            self._bump(pod)
            self._notify("pods", "MODIFIED", pod)

    # -- leases (coordination.k8s.io/v1) --------------------------------------

    def get_lease(self, namespace: str, name: str) -> dict[str, Any]:
        with self._lock:
            lease = self._leases.get(self._key(namespace, name))
            if lease is None:
                raise ApiError(404, f"lease {namespace}/{name}")
            return copy.deepcopy(lease)

    def create_lease(self, namespace: str, name: str,
                     spec: dict[str, Any]) -> dict[str, Any]:
        with self._lock:
            key = self._key(namespace, name)
            if key in self._leases:
                raise ApiError(409, f"lease {key} exists")
            lease = {
                "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": name, "namespace": namespace},
                "spec": dict(spec),
            }
            self._bump(lease)
            self._leases[key] = lease
            return copy.deepcopy(lease)

    def list_leases(self, namespace: str) -> list[dict[str, Any]]:
        with self._lock:
            prefix = namespace + "/"
            return [copy.deepcopy(lease)
                    for key, lease in sorted(self._leases.items())
                    if key.startswith(prefix)]

    def update_lease(self, namespace: str, name: str, spec: dict[str, Any],
                     resource_version: str | None = None) -> dict[str, Any]:
        with self._lock:
            lease = self._leases.get(self._key(namespace, name))
            if lease is None:
                raise ApiError(404, f"lease {namespace}/{name}")
            if resource_version is not None and \
                    lease["metadata"].get("resourceVersion") != resource_version:
                raise ApiError(409, "lease resourceVersion conflict")
            lease["spec"] = dict(spec)
            self._bump(lease)
            return copy.deepcopy(lease)

    def patch_node(self, name: str, patch: dict[str, Any],
                   status: bool = False) -> dict[str, Any]:
        with self._lock:
            self._check_partition(name)
            node = self._nodes.get(name)
            if node is None:
                raise ApiError(404, f"node {name}")
            want_rv = (patch.get("metadata") or {}).get("resourceVersion")
            if want_rv is not None and \
                    want_rv != node["metadata"].get("resourceVersion"):
                raise ApiError(409, f"node {name}: resourceVersion conflict")
            merged = strategic_merge(node, json.loads(json.dumps(patch)))
            self._bump(merged)
            self._nodes[name] = merged
            self._notify("nodes", "MODIFIED", merged)
            return copy.deepcopy(merged)

    def put_configmap(self, namespace: str, name: str,
                      data: dict[str, str]) -> None:
        self.set_configmap(namespace, name, data)

    def create_event(self, namespace: str, event: dict[str, Any]) -> None:
        with self._lock:
            self._events.append({"namespace": namespace, **event})

    @property
    def events(self) -> list[dict[str, Any]]:
        with self._lock:
            return copy.deepcopy(self._events)

    # -- watches -------------------------------------------------------------

    def _watch(self, kind: str, stop: threading.Event) -> Iterator[WatchEvent]:
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._watchers[kind].append(q)
        try:
            while not stop.is_set():
                try:
                    ev = q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if ev is _SEVER:
                    raise ApiError(500, f"{kind} watch severed (chaos)")
                yield ev
        finally:
            with self._lock:
                self._watchers[kind].remove(q)

    def watch_pods(self, stop) -> Iterator[WatchEvent]:
        return self._watch("pods", stop)

    def watch_nodes(self, stop) -> Iterator[WatchEvent]:
        return self._watch("nodes", stop)

    def watch_configmaps(self, stop) -> Iterator[WatchEvent]:
        return self._watch("configmaps", stop)
