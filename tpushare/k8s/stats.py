"""Per-verb apiserver round-trip accounting.

The whole point of the informer/lister/memo work is to take the apiserver
out of the scheduling hot path — which is only provable if every
round-trip is counted. :class:`CountingCluster` wraps any ClusterClient
and increments ``tpushare_apiserver_requests_total{verb,origin}`` on
every call; ``origin`` comes from a thread-local scope the hot paths set
(``with api_origin("bind"): ...``), so one shared client can attribute
traffic to bind vs filter vs controller vs allocate without plumbing a
tag through every call site.

bench.py diffs snapshots of the counter around its measured windows to
publish ``apiserver_requests_per_bind`` and to FAIL when a plain bind's
hot path issues any synchronous read.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Iterator

from tpushare.metrics import LabeledCounter
from tpushare.obs.trace import TRACER

APISERVER_REQUESTS = LabeledCounter(
    "tpushare_apiserver_requests_total",
    "Apiserver round-trips by verb and originating code path "
    "(origin set via tpushare.k8s.stats.api_origin)",
    ("verb", "origin"))

CONN_POOL_REQUESTS = LabeledCounter(
    "tpushare_conn_pool_requests_total",
    "Keep-alive pool outcomes per request/response apiserver call "
    '("reused": idle connection checked out; "fresh": none idle, new '
    'connect (+TLS); "stale_replaced": the recv-before-send probe '
    "caught a peer-closed idle connection and replaced it BEFORE the "
    'request left; "replayed": a replay-safe verb was resent once '
    "after a reused connection died mid-request)",
    ("outcome",))

# verbs that transfer state FROM the apiserver on a request/response call
# (watches are long-lived streams, counted once at attach, and excluded
# from the read budget — they are the mechanism that REMOVES reads)
READ_VERBS = frozenset({
    "list_pods", "list_pods_node", "list_pods_ns", "list_nodes",
    "get_pod", "get_node", "get_configmap", "get_lease", "list_leases"})
WRITE_VERBS = frozenset({
    "patch_pod", "replace_pod", "bind_pod", "patch_node",
    "put_configmap", "create_lease", "update_lease"})
# create_event is a write too, but it is explicitly post-latency
# best-effort observability — tracked under its own verb so the bind
# write budget (patch+bind) stays honest without hiding event traffic.

_local = threading.local()


def current_origin() -> str:
    return getattr(_local, "origin", "other")


@contextlib.contextmanager
def api_origin(origin: str) -> Iterator[None]:
    """Attribute every apiserver call made by this thread inside the
    scope to ``origin`` (nesting restores the outer scope on exit)."""
    prev = getattr(_local, "origin", None)
    _local.origin = origin
    try:
        yield
    finally:
        if prev is None:
            del _local.origin
        else:
            _local.origin = prev


class CountingCluster:
    """Transparent ClusterClient proxy that counts every round-trip.

    ``list_pods`` is split by scope (cluster / node / namespace) because
    the three differ by orders of magnitude in transferred bytes — the
    gang-Allocate acceptance bar is specifically "at most one
    namespace-scoped LIST", which a single verb could not verify.
    """

    def __init__(self, inner: Any,
                 stats: LabeledCounter = APISERVER_REQUESTS) -> None:
        self._inner = inner
        self._stats = stats

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._inner, name)
        if not callable(attr) or name.startswith("_"):
            return attr
        if name == "list_pods":
            def counted_list(*args: Any, **kwargs: Any) -> Any:
                verb = "list_pods"
                if kwargs.get("node_name") or (args and args[0]):
                    verb = "list_pods_node"
                elif kwargs.get("namespace") or len(args) > 1:
                    verb = "list_pods_ns"
                self._stats.inc(verb, current_origin())
                return _traced_call(attr, verb, args, kwargs)
            return counted_list
        if name.startswith("watch_"):
            def counted_watch(*args: Any, **kwargs: Any) -> Any:
                self._stats.inc(name, current_origin())
                return attr(*args, **kwargs)
            return counted_watch

        def counted(*args: Any, **kwargs: Any) -> Any:
            self._stats.inc(name, current_origin())
            return _traced_call(attr, name, args, kwargs)
        return counted


def _traced_call(attr: Any, verb: str, args: tuple, kwargs: dict) -> Any:
    """Run one apiserver round-trip; when the calling thread is inside a
    trace span, record the call as an event (verb, origin, ms, error) on
    it. Outside a span this is one attribute read of overhead."""
    span = TRACER.current_span()
    if span is None:
        return attr(*args, **kwargs)
    t0 = time.perf_counter()
    try:
        result = attr(*args, **kwargs)
    except Exception as e:  # noqa: BLE001 — annotate and re-raise as-is
        span.annotate("api", verb=verb, origin=current_origin(),
                      ms=round((time.perf_counter() - t0) * 1e3, 3),
                      error=f"{type(e).__name__}: {e}"[:160])
        raise
    span.annotate("api", verb=verb, origin=current_origin(),
                  ms=round((time.perf_counter() - t0) * 1e3, 3))
    return result


def hit_rate(before: dict[tuple[str, ...], float],
             after: dict[tuple[str, ...], float],
             hit: str = "hit", miss: str = "miss") -> float | None:
    """hits / (hits + misses) over the movement between two
    LabeledCounter snapshots whose LAST label is the outcome — the
    shared shape of the lister / memo / per-node-reuse counters. None
    when nothing moved (no traffic in the window)."""
    moved = {k: after.get(k, 0.0) - before.get(k, 0.0) for k in after}
    hits = sum(v for k, v in moved.items() if k[-1] == hit)
    misses = sum(v for k, v in moved.items() if k[-1] == miss)
    if hits + misses == 0:
        return None
    return round(hits / (hits + misses), 4)


def delta(before: dict[tuple[str, ...], float],
          after: dict[tuple[str, ...], float],
          verbs: frozenset[str] | None = None,
          origin: str | None = None) -> float:
    """Sum of counter movement between two APISERVER_REQUESTS.snapshot()
    calls, optionally filtered by verb set and/or origin."""
    out = 0.0
    for key, v in after.items():
        verb, org = key
        if verbs is not None and verb not in verbs:
            continue
        if origin is not None and org != origin:
            continue
        out += v - before.get(key, 0.0)
    return out
