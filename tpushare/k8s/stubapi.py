"""A stub kube-apiserver speaking the real wire format, for integration
tests and off-cluster development.

``InClusterClient`` (incluster.py) is the one component that talks to a
real apiserver — the analogue of the reference's client-go usage
(/root/reference/cmd/main.go:32-50) — and its failure modes live in the
wire protocol: chunked watch streams, BOOKMARK events, 410-Gone watch
restarts, mid-stream disconnects, strategic-merge PATCH semantics, the
pods/binding subresource, lease optimistic concurrency, and bearer-token
rotation. This server implements exactly those behaviors over stdlib
http.server so the client (and the cache/controller/extender stack above
it) can be driven against them hermetically, with fault-injection knobs:

- ``inject_bookmark()``          — send a BOOKMARK to live pod watches
- ``gone_on_next_watch()``       — next watch connect gets ERROR 410
- ``drop_watch_connections()``   — abruptly reset live watch sockets
- ``close_watch_after(n)``       — end each watch stream after n events

State is apiserver-like: every write bumps a global resourceVersion,
appends to a bounded event history, and wakes watchers; a watch from an
rv older than history start gets 410 (compaction), matching apiserver
semantics.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from tpushare.k8s.client import strategic_merge

HISTORY_LIMIT = 4096


def _status(code: int, reason: str, message: str) -> dict[str, Any]:
    return {"kind": "Status", "apiVersion": "v1", "status": "Failure",
            "reason": reason, "message": message, "code": code}


class _State:
    """Object stores + watch event history, RWLock-free (one big lock —
    this is a test double, not a production server)."""

    def __init__(self) -> None:
        self.lock = threading.Condition()
        self.rv = 100  # arbitrary non-zero start, like a real cluster
        # kind -> {key: obj}; keys are "ns/name" or "name" for nodes
        self.objects: dict[str, dict[str, dict[str, Any]]] = {
            "pods": {}, "nodes": {}, "configmaps": {}, "leases": {},
            "events": {},
        }
        # (rv, kind, type, obj) in commit order
        self.history: list[tuple[int, str, str, dict[str, Any]]] = []
        self.history_start = 101  # rv of the oldest retained event + 1

    def commit(self, kind: str, etype: str, obj: dict[str, Any],
               key: str) -> dict[str, Any]:
        """Record a write: bump rv, stamp it on the object, append to the
        watch history, wake watchers. Caller holds the lock."""
        self.rv += 1
        meta = obj.setdefault("metadata", {})
        meta["resourceVersion"] = str(self.rv)
        if etype == "ADDED" and not meta.get("uid"):
            # a real apiserver stamps a UID on every created object; the
            # allocation cache keys accounting on it, and uid-less pods
            # once collapsed onto one cache entry (r3 HA storm finding)
            meta["uid"] = f"stub-{uuid.uuid4()}"
        if etype == "DELETED":
            self.objects[kind].pop(key, None)
        else:
            self.objects[kind][key] = obj
        self.history.append((self.rv, kind, etype, json.loads(json.dumps(obj))))
        if len(self.history) > HISTORY_LIMIT:
            drop = len(self.history) - HISTORY_LIMIT
            self.history_start = self.history[drop][0]
            del self.history[:drop]
        self.lock.notify_all()
        return obj


class StubApiServer:
    def __init__(self, token: str | None = None,
                 write_delay_s: float = 0.0) -> None:
        self.state = _State()
        self.token = token  # None = no auth required
        # per-write commit latency (etcd raft+fsync emulation): a plain
        # loopback stub answers writes in pure-CPU time, which the GIL
        # serializes across this process's threads — concurrency wins
        # (e.g. the pipelined PATCH+POST bind) are only measurable when
        # writes carry real, GIL-released wait time like a production
        # apiserver's. Applied per mutating request, OUTSIDE the store
        # lock (commit batching: concurrent writes wait concurrently).
        self.write_delay_s = write_delay_s
        self._fault_lock = threading.Lock()
        self._gone_next_watch = 0
        self._close_after_events: int | None = None
        self._live_watch_sockets: list[socket.socket] = []
        self._bookmark_seq = 0
        self._partitioned: set[str] = set()
        state = self.state
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # keep-alive + Nagle + delayed-ACK = ~40ms per request (the
            # headers flush and body are separate segments); real
            # apiservers run with TCP_NODELAY for the same reason
            disable_nagle_algorithm = True

            def log_message(self, *a):  # quiet
                pass

            # -- helpers -------------------------------------------------------

            def _send_json(self, code: int, obj: dict[str, Any]) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _fail(self, code: int, reason: str, message: str) -> None:
                self._send_json(code, _status(code, reason, message))

            def _body(self) -> dict[str, Any]:
                n = int(self.headers.get("Content-Length", "0"))
                return json.loads(self.rfile.read(n)) if n else {}

            def _authed(self) -> bool:
                if stub.token is None:
                    return True
                if self.headers.get("Authorization") == f"Bearer {stub.token}":
                    return True
                self._fail(401, "Unauthorized", "bad or missing bearer token")
                return False

            def _route(self):
                """Parse path into (kind, namespace, name, subresource)."""
                path = self.path.split("?", 1)[0].strip("/")
                parts = path.split("/")
                # /api/v1/... or /apis/coordination.k8s.io/v1/...
                if parts[:2] == ["api", "v1"]:
                    rest = parts[2:]
                elif parts[:3] == ["apis", "coordination.k8s.io", "v1"]:
                    rest = parts[3:]
                else:
                    return None
                if not rest:
                    return None
                if rest[0] == "namespaces" and len(rest) >= 3:
                    ns, kind = rest[1], rest[2]
                    name = rest[3] if len(rest) > 3 else ""
                    sub = rest[4] if len(rest) > 4 else ""
                    return kind, ns, name, sub
                kind = rest[0]
                name = rest[1] if len(rest) > 1 else ""
                sub = rest[2] if len(rest) > 2 else ""
                return kind, "", name, sub

            def _query(self) -> dict[str, str]:
                if "?" not in self.path:
                    return {}
                out = {}
                for kv in self.path.split("?", 1)[1].split("&"):
                    k, _, v = kv.partition("=")
                    out[k] = v
                return out

            @staticmethod
            def _key(kind, ns, name):
                return f"{ns}/{name}" if ns else name

            def _partitioned(self, node: str) -> bool:
                """Node-scoped partition gate: a 503 for any verb that
                names a partitioned node (chaos conductor primitive)."""
                if not node:
                    return False
                with stub._fault_lock:
                    hit = node in stub._partitioned
                if hit:
                    self._fail(503, "ServiceUnavailable",
                               f"node {node} partitioned (chaos)")
                return hit

            # -- verbs ---------------------------------------------------------

            def do_GET(self):
                if not self._authed():
                    return
                route = self._route()
                if route is None:
                    return self._fail(404, "NotFound", self.path)
                kind, ns, name, _sub = route
                if kind not in state.objects:
                    return self._fail(404, "NotFound", kind)
                q = self._query()
                if q.get("watch") == "true" and not name:
                    return self._watch(kind, q)
                if kind == "nodes" and self._partitioned(name):
                    return
                with state.lock:
                    if name:
                        obj = state.objects[kind].get(self._key(kind, ns, name))
                        if obj is None:
                            return self._fail(404, "NotFound",
                                              f"{kind} {ns}/{name}")
                        return self._send_json(200, obj)
                    items = [o for k, o in sorted(state.objects[kind].items())
                             if not ns or k.startswith(f"{ns}/")]
                    fs = urllib.parse.unquote(q.get("fieldSelector", ""))
                    if fs.startswith("spec.nodeName="):
                        want = fs.split("=", 1)[1]
                        items = [o for o in items
                                 if (o.get("spec") or {}).get(
                                     "nodeName") == want]
                    return self._send_json(200, {
                        "kind": "List", "items": items,
                        "metadata": {"resourceVersion": str(state.rv)}})

            def _commit_wait(self) -> None:
                if stub.write_delay_s:
                    time.sleep(stub.write_delay_s)

            def do_PATCH(self):
                if not self._authed():
                    return
                self._commit_wait()
                route = self._route()
                if route is None:
                    return self._fail(404, "NotFound", self.path)
                kind, ns, name, sub = route
                ct = self.headers.get("Content-Type", "")
                if ct != "application/strategic-merge-patch+json":
                    return self._fail(415, "UnsupportedMediaType", ct)
                patch = self._body()
                # gate AFTER draining the body: an unread body on a
                # keep-alive connection desyncs the next request
                if kind == "nodes" and self._partitioned(name):
                    return
                key = self._key(kind, ns, name)
                with state.lock:
                    obj = state.objects.get(kind, {}).get(key)
                    if obj is None:
                        return self._fail(404, "NotFound", f"{kind} {key}")
                    # metadata.resourceVersion in the body is a CAS
                    # precondition (real apiserver semantics)
                    want_rv = (patch.get("metadata") or {}).get(
                        "resourceVersion")
                    if want_rv is not None and want_rv != \
                            obj.get("metadata", {}).get("resourceVersion"):
                        return self._fail(
                            409, "Conflict",
                            f"{key}: resourceVersion {want_rv} is stale")
                    # /status patches touch only status in real k8s; the
                    # merge itself is identical
                    merged = strategic_merge(obj, patch)
                    merged = state.commit(kind, "MODIFIED", merged, key)
                    return self._send_json(200, merged)

            def do_POST(self):
                if not self._authed():
                    return
                self._commit_wait()
                route = self._route()
                if route is None:
                    return self._fail(404, "NotFound", self.path)
                kind, ns, name, sub = route
                body = self._body()
                if kind == "pods" and sub == "binding":
                    return self._bind(ns, name, body)
                if kind == "events":
                    with state.lock:
                        key = f"{ns}/ev-{state.rv}"
                        state.commit("events", "ADDED", body, key)
                    return self._send_json(201, body)
                # generic create (configmaps, leases, pods in tests)
                if kind not in state.objects:
                    return self._fail(404, "NotFound", kind)
                meta = body.setdefault("metadata", {})
                meta.setdefault("namespace", ns)
                key = self._key(kind, ns, meta.get("name", ""))
                with state.lock:
                    if key in state.objects[kind]:
                        return self._fail(409, "AlreadyExists", key)
                    out = state.commit(kind, "ADDED", body, key)
                    return self._send_json(201, out)

            def do_PUT(self):
                if not self._authed():
                    return
                self._commit_wait()
                route = self._route()
                if route is None:
                    return self._fail(404, "NotFound", self.path)
                kind, ns, name, _sub = route
                body = self._body()
                key = self._key(kind, ns, name)
                with state.lock:
                    cur = state.objects.get(kind, {}).get(key)
                    if cur is None:
                        return self._fail(404, "NotFound", f"{kind} {key}")
                    want_rv = (body.get("metadata") or {}).get(
                        "resourceVersion")
                    have_rv = (cur.get("metadata") or {}).get(
                        "resourceVersion")
                    if want_rv is not None and want_rv != have_rv:
                        # the optimistic-concurrency CAS leases rely on
                        return self._fail(
                            409, "Conflict",
                            f"resourceVersion {want_rv} != {have_rv}")
                    body.setdefault("metadata", {}).setdefault(
                        "namespace", ns)
                    out = state.commit(kind, "MODIFIED", body, key)
                    return self._send_json(200, out)

            def do_DELETE(self):
                if not self._authed():
                    return
                self._commit_wait()
                route = self._route()
                if route is None:
                    return self._fail(404, "NotFound", self.path)
                kind, ns, name, _sub = route
                key = self._key(kind, ns, name)
                with state.lock:
                    obj = state.objects.get(kind, {}).get(key)
                    if obj is None:
                        return self._fail(404, "NotFound", key)
                    state.commit(kind, "DELETED", obj, key)
                    return self._send_json(200, obj)

            # -- subresources --------------------------------------------------

            def _bind(self, ns, name, body):
                """pods/binding: the verb the scheduler delegates to the
                extender (reference nodeinfo.go:226-239)."""
                key = f"{ns}/{name}"
                node = ((body.get("target") or {}).get("name")) or ""
                uid = (body.get("metadata") or {}).get("uid")
                if self._partitioned(node):
                    return
                with state.lock:
                    pod = state.objects["pods"].get(key)
                    if pod is None:
                        return self._fail(404, "NotFound", key)
                    pod_uid = (pod.get("metadata") or {}).get("uid")
                    if uid and pod_uid and uid != pod_uid:
                        return self._fail(409, "Conflict",
                                          f"uid {uid} != {pod_uid}")
                    if (pod.get("spec") or {}).get("nodeName"):
                        return self._fail(409, "Conflict",
                                          "pod already bound")
                    pod = json.loads(json.dumps(pod))
                    pod.setdefault("spec", {})["nodeName"] = node
                    state.commit("pods", "MODIFIED", pod, key)
                return self._send_json(201, _status(201, "Created", "bound"))

            # -- watch ---------------------------------------------------------

            def _chunk(self, data: bytes) -> None:
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")
                self.wfile.flush()

            def _watch(self, kind: str, q: dict[str, str]) -> None:
                with stub._fault_lock:
                    gone = stub._gone_next_watch > 0
                    if gone:
                        stub._gone_next_watch -= 1
                    close_after = stub._close_after_events
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                if gone:
                    self._chunk(json.dumps(
                        {"type": "ERROR",
                         "object": _status(410, "Expired",
                                           "too old resource version")}
                    ).encode() + b"\n")
                    self._chunk(b"")  # clean end-of-stream
                    return
                rv = int(q.get("resourceVersion") or state.rv)
                with stub._fault_lock:
                    stub._live_watch_sockets.append(self.connection)
                sent = 0
                last_bookmark = stub._bookmark_seq  # only future injections
                try:
                    while True:
                        bookmark = None
                        with state.lock:
                            if rv < state.history_start - 1:
                                # compacted away: real apiservers 410 here
                                events: list | None = None
                            else:
                                events = [(erv, et, obj) for
                                          (erv, k, et, obj) in state.history
                                          if k == kind and erv > rv]
                                bookmark = (stub._bookmark_seq
                                            if stub._bookmark_seq >
                                            last_bookmark else None)
                                if not events and bookmark is None:
                                    state.lock.wait(timeout=0.25)
                                    continue
                        if events is None:
                            self._chunk(json.dumps(
                                {"type": "ERROR",
                                 "object": _status(410, "Expired", "gone")}
                            ).encode() + b"\n")
                            break
                        if not events and bookmark is not None:
                            last_bookmark = bookmark
                            self._chunk(json.dumps(
                                {"type": "BOOKMARK",
                                 "object": {"kind": kind,
                                            "metadata": {
                                                "resourceVersion": str(rv)}}}
                            ).encode() + b"\n")
                            continue
                        for erv, et, obj in events:
                            self._chunk(json.dumps(
                                {"type": et, "object": obj}).encode() + b"\n")
                            rv = erv
                            sent += 1
                            if close_after is not None and sent >= close_after:
                                self._chunk(b"")
                                return
                except (BrokenPipeError, ConnectionResetError, OSError):
                    return  # client went away or we were reset
                finally:
                    with stub._fault_lock:
                        try:
                            stub._live_watch_sockets.remove(self.connection)
                        except ValueError:
                            pass
                self._chunk(b"")

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._server.daemon_threads = True
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    @property
    def base_url(self) -> str:
        host, port = self._server.server_address
        return f"http://{host}:{port}"

    def start(self) -> "StubApiServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="stub-apiserver", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- seeding (test-side, no HTTP) ------------------------------------------

    def seed(self, kind: str, obj: dict[str, Any]) -> dict[str, Any]:
        meta = obj.setdefault("metadata", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
        key = f"{ns}/{name}" if kind != "nodes" else name
        with self.state.lock:
            return self.state.commit(kind, "ADDED", obj, key)

    def delete(self, kind: str, key: str) -> None:
        with self.state.lock:
            obj = self.state.objects[kind].get(key)
            if obj is not None:
                self.state.commit(kind, "DELETED", obj, key)

    def get(self, kind: str, key: str) -> dict[str, Any] | None:
        with self.state.lock:
            obj = self.state.objects[kind].get(key)
            return json.loads(json.dumps(obj)) if obj is not None else None

    # -- fault injection -------------------------------------------------------

    def watch_count(self) -> int:
        """Live watch connections (lets tests wait for attachment before
        seeding — watches start at the current rv, like a real apiserver)."""
        with self._fault_lock:
            return len(self._live_watch_sockets)

    def inject_bookmark(self) -> None:
        with self._fault_lock:
            self._bookmark_seq += 1
        with self.state.lock:
            self.state.lock.notify_all()

    def gone_on_next_watch(self, n: int = 1) -> None:
        with self._fault_lock:
            self._gone_next_watch = n

    def close_watch_after(self, n_events: int | None) -> None:
        with self._fault_lock:
            self._close_after_events = n_events

    def drop_watch_connections(self) -> None:
        """Abruptly reset live watch sockets (mid-stream network failure)."""
        with self._fault_lock:
            socks = list(self._live_watch_sockets)
        for s in socks:
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             b"\x01\x00\x00\x00\x00\x00\x00\x00")
                s.close()
            except OSError:
                pass

    def break_watches(self) -> int:
        """Sever every live watch stream (FakeCluster-parity name for
        :meth:`drop_watch_connections`): the chaos conductor speaks one
        verb against either backend. Returns the number of streams cut."""
        with self._fault_lock:
            n = len(self._live_watch_sockets)
        self.drop_watch_connections()
        return n

    def partition(self, node_name: str) -> None:
        """Node-scoped partition: GET/PATCH on the node and any bind
        targeting it fail 503 until :meth:`heal` — the rack-lost-uplink
        fault, distinct from a full apiserver brownout."""
        with self._fault_lock:
            self._partitioned.add(node_name)

    def heal(self, node_name: str | None = None) -> None:
        """Lift a node partition (all of them when ``node_name`` is
        None)."""
        with self._fault_lock:
            if node_name is None:
                self._partitioned.clear()
            else:
                self._partitioned.discard(node_name)


def main(argv: list[str] | None = None) -> int:
    """Standalone stub apiserver for local development:

        python -m tpushare.k8s.stubapi --port 8001 --tpu-nodes n1:4x16384
        python -m tpushare.extender --apiserver http://127.0.0.1:8001

    gives the full real-wire control plane (watches, PATCH, binding) with
    no cluster."""
    import argparse

    ap = argparse.ArgumentParser(prog="tpushare-stub-apiserver")
    ap.add_argument("--port", type=int, default=8001)
    ap.add_argument("--token", default=None,
                    help="require this bearer token when set")
    ap.add_argument("--tpu-nodes", default=None,
                    help="seed TPU nodes: 'n1:4x16384:2x2,n2:2x8192'")
    args = ap.parse_args(argv)

    stub = StubApiServer(token=args.token)
    # rebind to the requested port
    stub._server.server_close()
    from http.server import ThreadingHTTPServer
    handler = stub._server.RequestHandlerClass
    stub._server = ThreadingHTTPServer(("127.0.0.1", args.port), handler)
    stub._server.daemon_threads = True
    stub.start()
    for spec in (args.tpu_nodes or "").split(","):
        if not spec:
            continue
        parts = spec.split(":")
        name = parts[0]
        chips_s, _, hbm_s = parts[1].partition("x")
        mesh = parts[2] if len(parts) > 2 else None
        labels = {"tpushare": "true"}
        if mesh:
            labels["tpushare.aliyun.com/mesh"] = mesh
        total = int(chips_s) * int(hbm_s)
        stub.seed("nodes", {
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": name, "labels": labels},
            "status": {"capacity": {
                "aliyun.com/tpu-hbm": str(total),
                "aliyun.com/tpu-count": chips_s}}})
        print(f"seeded node {name}: {chips_s} chips x {hbm_s} MiB")
    print(f"stub apiserver on {stub.base_url}")
    try:
        import threading
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    stub.stop()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
