"""Fault injection for the cluster-client surface.

The reference has **no fault injection anywhere** (SURVEY.md §5.3); its
failure handling — bind rollback, optimistic-lock retry, watch-loop
restart, annotation replay — is only ever exercised by production
incidents. tpushare ships this chaos proxy as a first-class test facility
instead: wrap any cluster client (normally the :class:`FakeCluster`) and
declare failures per method, then assert the scheduler's invariants hold
through the storm (tests/test_chaos.py).

Rules are consumed call-by-call and are thread-safe, so a chaos cluster
can sit under a concurrent bind storm:

    chaos = ChaosCluster(FakeCluster(), seed=7)
    chaos.fail("patch_pod", status=409, times=2)        # next 2 calls 409
    chaos.fail("bind_pod", probability=0.3, times=None) # 30% of calls 500
    chaos.delay("get_pod", seconds=0.05, times=None)    # slow apiserver
    chaos.drop_watch("pods", after=3)                   # stream dies after 3

Every injected fault is counted in ``chaos.injected`` so tests can assert
the storm actually happened (a chaos test that injected nothing proves
nothing).
"""

from __future__ import annotations

import random
import threading
import time
from collections import Counter
from typing import Any, Callable

from tpushare.k8s.client import ApiError

_WATCH_KINDS = {"pods": "watch_pods", "nodes": "watch_nodes",
                "configmaps": "watch_configmaps"}


class _Rule:
    __slots__ = ("action", "status", "message", "seconds", "after",
                 "remaining", "probability", "retry_after", "prob_fn")

    def __init__(self, action: str, *, status: int = 500,
                 message: str | None = None, seconds: float = 0.0,
                 after: int = 0, times: int | None = 1,
                 probability: float = 1.0,
                 retry_after: float | None = None,
                 prob_fn: Callable[[], float | None] | None = None) -> None:
        self.action = action          # "fail" | "delay" | "drop"
        self.status = status
        self.message = message
        self.seconds = seconds
        self.after = after
        self.remaining = float("inf") if times is None else int(times)
        self.probability = probability
        self.retry_after = retry_after  # attached to injected ApiErrors
        # time-varying probability (brownout ramps); None return = the
        # window is over and the rule is dead
        self.prob_fn = prob_fn


class ChaosCluster:
    """Transparent proxy over a cluster client that injects declared
    faults. Methods without active rules pass straight through; non-method
    attributes are proxied untouched."""

    def __init__(self, inner: Any, seed: int = 0) -> None:
        self._inner = inner
        self._rng = random.Random(seed)
        self._rules_lock = threading.Lock()
        self._rules: dict[str, list[_Rule]] = {}
        self.injected: Counter = Counter()

    # -- rule declaration -----------------------------------------------------

    def fail(self, method: str, *, status: int = 500,
             message: str | None = None, times: int | None = 1,
             probability: float = 1.0,
             retry_after: float | None = None) -> None:
        """Make the next ``times`` calls of ``method`` raise
        ``ApiError(status)`` (each with ``probability``; times=None =
        forever). ``retry_after`` rides on the error the way a 429's
        Retry-After header would (``fail(..., status=429,
        retry_after=0.2)`` is how the retry policy's header honoring is
        tested). At most one fail rule fires per call, so stacked rules
        (e.g. a 500 rule and a 409 rule) take turns rather than the later
        ones being consumed-but-ignored."""
        self._check_not_watch(method)
        self._add(method, _Rule("fail", status=status, message=message,
                                times=times, probability=probability,
                                retry_after=retry_after))

    def brownout(self, method: str, *, seconds: float, peak: float = 0.9,
                 status: int = 500, retry_after: float | None = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        """A rolling apiserver brownout on ``method``: the failure
        probability ramps 0 -> ``peak`` -> 0 over a ``seconds``-long
        window (triangular ramp, starting now), then the rule dies. This
        is the soak test's storm shape — a burst that worsens, crests,
        and recedes, which is what exercises breaker open/half-open/close
        transitions rather than a flat failure rate."""
        self._check_not_watch(method)
        t0 = clock()

        def prob() -> float | None:
            t = clock() - t0
            if t >= seconds:
                return None  # window over: rule is dead
            return peak * (1.0 - abs(2.0 * t / seconds - 1.0))

        self._add(method, _Rule("fail", status=status, times=None,
                                retry_after=retry_after, prob_fn=prob))

    def delay(self, method: str, *, seconds: float,
              times: int | None = None, probability: float = 1.0) -> None:
        """Sleep ``seconds`` before the next ``times`` calls of
        ``method`` (default: every call) — apiserver latency."""
        self._check_not_watch(method)
        self._add(method, _Rule("delay", seconds=seconds, times=times,
                                probability=probability))

    @staticmethod
    def _check_not_watch(method: str) -> None:
        if method in _WATCH_KINDS.values():
            raise ValueError(
                f"{method} is a watch stream; use drop_watch() — fail/delay "
                "rules would be counted but never fire there")

    def drop_watch(self, kind: str, *, after: int = 0,
                   times: int | None = 1) -> None:
        """Close the next ``times`` ``kind`` watch streams ("pods",
        "nodes", "configmaps") after yielding ``after`` events — the
        apiserver hanging up mid-stream."""
        method = _WATCH_KINDS[kind]
        self._add(method, _Rule("drop", after=after, times=times))

    def clear(self) -> None:
        with self._rules_lock:
            self._rules.clear()

    def _add(self, method: str, rule: _Rule) -> None:
        with self._rules_lock:
            self._rules.setdefault(method, []).append(rule)

    def _take(self, method: str) -> list[_Rule]:
        """Consume (decrement) whichever rules fire for this call.

        Every fired rule takes effect: all delays apply, but at most one
        fail rule is consumed per call (the caller raises exactly one
        error, so consuming more would overcount ``injected``)."""
        with self._rules_lock:
            fired = []
            fail_taken = False
            for rule in self._rules.get(method, []):
                if rule.remaining <= 0:
                    continue
                if rule.action == "fail" and fail_taken:
                    continue
                p = rule.probability
                if rule.prob_fn is not None:
                    p = rule.prob_fn()
                    if p is None:  # brownout window over: rule is dead
                        rule.remaining = 0
                        continue
                if p < 1.0 and self._rng.random() >= p:
                    continue
                rule.remaining -= 1
                self.injected[method] += 1
                fired.append(rule)
                if rule.action == "fail":
                    fail_taken = True
            return fired

    # -- proxy ----------------------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr
        if name in _WATCH_KINDS.values():
            return self._wrap_watch(name, attr)
        return self._wrap_call(name, attr)

    def _wrap_call(self, name: str, fn: Any) -> Any:
        def call(*args: Any, **kwargs: Any) -> Any:
            failure: _Rule | None = None
            for rule in self._take(name):
                if rule.action == "delay":
                    time.sleep(rule.seconds)
                elif rule.action == "fail":
                    failure = rule
            if failure is not None:
                raise ApiError(
                    failure.status,
                    failure.message or f"chaos: injected {failure.status} "
                                       f"on {name}",
                    retry_after=failure.retry_after)
            return fn(*args, **kwargs)
        return call

    def _take_drop(self, method: str) -> _Rule | None:
        """Reserve (decrement) the first live drop rule for a new stream.
        ``injected`` is NOT counted here — only when the drop fires — and
        a stream that ends before firing refunds its reservation, so the
        counter reflects actual hangups and stacked times=N budgets don't
        deplete on streams that were never dropped."""
        with self._rules_lock:
            for rule in self._rules.get(method, []):
                if rule.action != "drop" or rule.remaining <= 0:
                    continue
                if rule.probability < 1.0 and \
                        self._rng.random() >= rule.probability:
                    continue
                rule.remaining -= 1
                return rule
            return None

    def _wrap_watch(self, name: str, fn: Any) -> Any:
        def watch(*args: Any, **kwargs: Any):
            rule = self._take_drop(name)
            n = 0
            fired = False
            inner = fn(*args, **kwargs)
            try:
                while True:
                    # check BEFORE pulling: a dropped stream on a quiet
                    # cluster must hang up, not block waiting for an event
                    # that never comes
                    if rule is not None and n >= rule.after:
                        fired = True
                        with self._rules_lock:
                            self.injected[name] += 1
                        raise ApiError(
                            500, f"chaos: {name} stream dropped "
                                 f"after {n} events")
                    try:
                        ev = next(inner)
                    except StopIteration:
                        return
                    yield ev
                    n += 1
            finally:
                if rule is not None and not fired:
                    with self._rules_lock:
                        rule.remaining += 1
        return watch
