"""Real apiserver client over stdlib http.client (no external deps).

Replaces the reference's client-go usage (cmd/main.go:32-50 builds a
clientset from kubeconfig or in-cluster config). Construction paths, same
precedence as the reference's initKubeClient (cmd/main.go:24-38):

- :meth:`InClusterClient.autodetect` — ``--kubeconfig`` flag, else
  ``$KUBECONFIG``, else the pod's in-cluster service account;
- :meth:`InClusterClient.from_kubeconfig` — out-of-cluster dev flow
  (token / client-cert / exec-plugin auth, see k8s/kubeconfig.py);
- explicit ``base_url``/``token`` for development against `kubectl proxy`.

Watches use the apiserver's streaming JSON-lines protocol
(`?watch=true&resourceVersion=...`) and reconnect from the server's current
state after a gap. Events dropped during the gap are NOT replayed by the
watch API — the Controller's periodic resync (controller.py::_resync_loop)
is the anti-entropy mechanism that reconciles them.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import ssl
import threading
import urllib.parse
import urllib.request
from typing import Any, Iterator

from tpushare.k8s.client import ApiError, WatchEvent
from tpushare.k8s.stats import CONN_POOL_REQUESTS

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _parse_retry_after(raw: str | None) -> float | None:
    """Retry-After in delta-seconds (the form the apiserver sends); the
    HTTP-date form is ignored rather than misparsed."""
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v >= 0 else None


class _ConnPool:
    """Keep-alive HTTP(S) connection pool for the request/response calls.

    urllib opens (and for https, TLS-handshakes) a fresh connection per
    request; on the bind hot path that is two handshakes per pod. The
    pool checks connections out per request, so concurrent callers never
    share an http.client connection (they are not thread-safe), and a
    dead keep-alive connection is detected and retried once with a fresh
    one. Watches do NOT use the pool — a watch monopolizes its connection
    for the stream's lifetime (incluster.py _watch).
    """

    def __init__(self, host: str, port: int, https: bool,
                 ctx: ssl.SSLContext | None, max_idle: int = 8) -> None:
        self._host, self._port, self._https, self._ctx = \
            host, port, https, ctx
        self._idle: list[http.client.HTTPConnection] = []
        self._lock = threading.Lock()
        self._max_idle = max_idle

    def _new_conn(self, timeout: float) -> http.client.HTTPConnection:
        if self._https:
            conn: http.client.HTTPConnection = http.client.HTTPSConnection(
                self._host, self._port, timeout=timeout, context=self._ctx)
        else:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=timeout)
        conn.connect()
        # Nagle + delayed-ACK stalls reused connections ~40ms per request
        # (headers and body are separate send()s); a scheduler webhook
        # cannot afford that on its bind path
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    # verbs whose replay cannot duplicate a side effect: reads, and the
    # PUT/PATCH writes that are CAS-guarded (resourceVersion) or
    # last-writer-wins. POST is excluded — a binding or event POST whose
    # response was lost may have LANDED, and a blind transport resend
    # would duplicate it; those route through the retry policy
    # (k8s/retry.py), whose call sites tolerate duplicates explicitly.
    _REPLAY_SAFE = frozenset({"GET", "HEAD", "PUT", "PATCH", "DELETE"})

    @staticmethod
    def _looks_stale(conn: http.client.HTTPConnection) -> bool:
        """Recv-before-send staleness probe for a REUSED connection.

        An idle keep-alive connection the peer has half-closed (the
        apiserver's idle timeout) is READABLE: EOF, a TLS close_notify,
        or stray bytes are all waiting. A healthy idle connection has
        nothing to read. One zero-timeout select answers which, BEFORE
        any request bytes leave — so a binding POST can reuse pooled
        connections again (keep-alive setup cost off the bind path)
        without ever reaching the ambiguous sent-then-died state the
        replay-safety rule exists for. The probe cannot catch a close
        that races the request itself; that window still surfaces as an
        error for non-replay-safe verbs, exactly as before."""
        sock = conn.sock
        if sock is None:
            return True
        try:
            if isinstance(sock, ssl.SSLSocket) and sock.pending():
                return True  # already-decrypted bytes: close_notify
            import select
            readable, _, _ = select.select([sock], [], [], 0)
            return bool(readable)
        except (OSError, ValueError):
            return True  # unselectable socket = unusable connection

    def request(self, method: str, path: str, body: bytes | None,
                headers: dict[str, str], timeout: float
                ) -> tuple[int, bytes, str | None]:
        with self._lock:
            conn = self._idle.pop() if self._idle else None
        if conn is not None and self._looks_stale(conn):
            CONN_POOL_REQUESTS.inc("stale_replaced")
            conn.close()
            conn = None
        fresh = conn is None
        if conn is None:
            conn = self._new_conn(timeout)
        else:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
        CONN_POOL_REQUESTS.inc("fresh" if fresh else "reused")
        try:
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.HTTPException, OSError):
            conn.close()
            if fresh or method not in self._REPLAY_SAFE:
                # a fresh-socket failure is a real transport error; a
                # reused-socket failure on a non-idempotent verb is
                # AMBIGUOUS (the request may have been processed before
                # the connection died) — surface it rather than risk a
                # duplicate POST, and let the retry policy decide
                raise
            # stale keep-alive connection (apiserver idle-closed it):
            # safe-to-replay request, retry exactly once on a fresh socket
            CONN_POOL_REQUESTS.inc("replayed")
            conn = self._new_conn(timeout)
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        retry_after = resp.getheader("Retry-After")
        if resp.will_close:
            conn.close()
        else:
            with self._lock:
                if len(self._idle) < self._max_idle:
                    self._idle.append(conn)
                else:
                    conn.close()
        return resp.status, data, retry_after


class InClusterClient:
    def __init__(self, base_url: str | None = None, token: str | None = None,
                 ca_file: str | None = None, timeout: float = 10.0,
                 token_file: str | None = None,
                 ssl_context: ssl.SSLContext | None = None,
                 extra_headers: dict[str, str] | None = None) -> None:
        self._extra_headers = dict(extra_headers or {})
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not running in-cluster (KUBERNETES_SERVICE_HOST unset); "
                    "pass base_url explicitly")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        self._token_file = token_file or os.path.join(SA_DIR, "token")
        self._token = token
        self.timeout = timeout
        ca = ca_file or os.path.join(SA_DIR, "ca.crt")
        if ssl_context is not None:
            self._ctx: ssl.SSLContext | None = ssl_context
        elif self.base_url.startswith("https") and os.path.exists(ca):
            self._ctx = ssl.create_default_context(cafile=ca)
        elif self.base_url.startswith("https"):
            self._ctx = ssl.create_default_context()
        else:
            self._ctx = None
        parsed = urllib.parse.urlsplit(self.base_url)
        self._pool = _ConnPool(
            parsed.hostname or "localhost",
            parsed.port or (443 if parsed.scheme == "https" else 80),
            parsed.scheme == "https", self._ctx)

    @classmethod
    def from_kubeconfig(cls, path: str | None = None,
                        context: str | None = None,
                        timeout: float = 10.0) -> "InClusterClient":
        """Out-of-cluster construction from a kubeconfig — the reference's
        dev flow (initKubeClient honors KUBECONFIG before in-cluster
        config, /root/reference/cmd/main.go:24-38)."""
        from tpushare.k8s.kubeconfig import load_kubeconfig
        auth = load_kubeconfig(path, context)
        return cls(base_url=auth.server, token=auth.token,
                   ssl_context=auth.ssl_context, timeout=timeout,
                   extra_headers=(
                       {} if auth.token else auth.headers()))

    @classmethod
    def autodetect(cls, kubeconfig: str | None = None,
                   timeout: float = 10.0) -> "InClusterClient":
        """kubeconfig flag > $KUBECONFIG > in-cluster SA, matching the
        reference's initKubeClient precedence (cmd/main.go:24-38)."""
        if kubeconfig or os.environ.get("KUBECONFIG"):
            return cls.from_kubeconfig(kubeconfig, timeout=timeout)
        return cls(timeout=timeout)

    # -- plumbing ------------------------------------------------------------

    def _auth_header(self) -> dict[str, str]:
        token = self._token
        if token is None and os.path.exists(self._token_file):
            # re-read every request: kubelet rotates projected SA tokens
            with open(self._token_file) as f:
                token = f.read().strip()
        headers = dict(self._extra_headers)
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    def _request(self, method: str, path: str, body: Any = None,
                 content_type: str = "application/json",
                 timeout: float | None = None):
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        for k, v in self._auth_header().items():
            req.add_header(k, v)
        try:
            return urllib.request.urlopen(
                req, timeout=self.timeout if timeout is None else timeout,
                context=self._ctx)
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode(errors="replace")[:512]
            except Exception:
                pass
            raise ApiError(e.code, detail, retry_after=_parse_retry_after(
                e.headers.get("Retry-After"))) from None
        except (urllib.error.URLError, socket.timeout, OSError) as e:
            raise ApiError(0, str(e)) from None

    def _json(self, method: str, path: str, body: Any = None,
              content_type: str = "application/json") -> dict[str, Any]:
        """Request/response call over the keep-alive pool (watches use
        :meth:`_request`/urllib instead — they monopolize a connection
        for the stream's lifetime)."""
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Accept": "application/json"}
        if data is not None:
            headers["Content-Type"] = content_type
        headers.update(self._auth_header())
        try:
            status, raw, retry_after = self._pool.request(
                method, path, data, headers, self.timeout)
        except (http.client.HTTPException, OSError) as e:
            raise ApiError(0, str(e)) from None
        if status >= 400:
            raise ApiError(status, raw.decode(errors="replace")[:512],
                           retry_after=_parse_retry_after(retry_after))
        return json.loads(raw) if raw else {}

    # -- reads ---------------------------------------------------------------

    def list_pods(self, node_name: str | None = None,
                  namespace: str | None = None) -> list[dict[str, Any]]:
        """LIST pods cluster-wide, one node's pods via an apiserver-side
        fieldSelector (the device-plugin rendezvous path — an Allocate on
        a 5000-pod cluster must not transfer the whole pod list), or one
        namespace's pods (the gang peer scan)."""
        path = (f"/api/v1/namespaces/{namespace}/pods" if namespace
                else "/api/v1/pods")
        if node_name:
            path += "?" + urllib.parse.urlencode(
                {"fieldSelector": f"spec.nodeName={node_name}"})
        return self._json("GET", path).get("items", [])

    def get_pod(self, namespace: str, name: str) -> dict[str, Any]:
        return self._json("GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def list_nodes(self) -> list[dict[str, Any]]:
        return self._json("GET", "/api/v1/nodes").get("items", [])

    def get_node(self, name: str) -> dict[str, Any]:
        return self._json("GET", f"/api/v1/nodes/{name}")

    def get_configmap(self, namespace: str, name: str) -> dict[str, Any]:
        return self._json(
            "GET", f"/api/v1/namespaces/{namespace}/configmaps/{name}")

    # -- writes --------------------------------------------------------------

    def patch_pod(self, namespace: str, name: str,
                  patch: dict[str, Any]) -> dict[str, Any]:
        # strategic merge patch, like the reference (nodeinfo.go:198)
        return self._json(
            "PATCH", f"/api/v1/namespaces/{namespace}/pods/{name}", patch,
            content_type="application/strategic-merge-patch+json")

    def replace_pod(self, namespace: str, name: str,
                    pod: dict[str, Any]) -> dict[str, Any]:
        """PUT with metadata.resourceVersion = apiserver-side CAS (409 on
        conflict) — used by the device plugin's stale-placement reclaim."""
        return self._json(
            "PUT", f"/api/v1/namespaces/{namespace}/pods/{name}", pod)

    def bind_pod(self, namespace: str, name: str, node: str,
                 uid: str | None = None) -> None:
        # pods/binding subresource — the write the extender is delegated
        # via the policy's bindVerb (reference nodeinfo.go:226-239)
        binding: dict[str, Any] = {
            "apiVersion": "v1", "kind": "Binding",
            "metadata": {"name": name, "namespace": namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node},
        }
        if uid:
            binding["metadata"]["uid"] = uid
        self._json(
            "POST", f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
            binding)

    def patch_node(self, name: str, patch: dict[str, Any],
                   status: bool = False) -> dict[str, Any]:
        path = f"/api/v1/nodes/{name}" + ("/status" if status else "")
        return self._json(
            "PATCH", path, patch,
            content_type="application/strategic-merge-patch+json")

    def put_configmap(self, namespace: str, name: str,
                      data: dict[str, str]) -> None:
        body = {"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": namespace},
                "data": dict(data)}
        try:
            self._json(
                "PUT", f"/api/v1/namespaces/{namespace}/configmaps/{name}",
                body)
        except ApiError as e:
            if not e.is_not_found:
                raise
            self._json("POST", f"/api/v1/namespaces/{namespace}/configmaps",
                       body)

    def create_event(self, namespace: str, event: dict[str, Any]) -> None:
        body = {"apiVersion": "v1", "kind": "Event", **event}
        try:
            self._json("POST", f"/api/v1/namespaces/{namespace}/events", body)
        except ApiError:
            pass  # events are best-effort (reference: record.EventBroadcaster)

    # -- leases (coordination.k8s.io/v1) --------------------------------------

    def _lease_path(self, namespace: str, name: str = "") -> str:
        base = f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        return f"{base}/{name}" if name else base

    def get_lease(self, namespace: str, name: str) -> dict[str, Any]:
        return self._json("GET", self._lease_path(namespace, name))

    def create_lease(self, namespace: str, name: str,
                     spec: dict[str, Any]) -> dict[str, Any]:
        body = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": name, "namespace": namespace},
                "spec": spec}
        return self._json("POST", self._lease_path(namespace), body)

    def list_leases(self, namespace: str) -> list[dict[str, Any]]:
        return self._json("GET", self._lease_path(namespace)) \
            .get("items", [])

    def update_lease(self, namespace: str, name: str, spec: dict[str, Any],
                     resource_version: str | None = None) -> dict[str, Any]:
        body = {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": name, "namespace": namespace},
                "spec": spec}
        if resource_version is not None:
            body["metadata"]["resourceVersion"] = resource_version
        # PUT with resourceVersion = optimistic concurrency on the apiserver
        return self._json("PUT", self._lease_path(namespace, name), body)

    # -- watches -------------------------------------------------------------

    def _watch(self, path: str, stop: threading.Event) -> Iterator[WatchEvent]:
        rv = ""
        while not stop.is_set():
            q = {"watch": "true", "allowWatchBookmarks": "true"}
            if rv:
                q["resourceVersion"] = rv
            url = f"{path}?{urllib.parse.urlencode(q)}"
            try:
                resp = self._request("GET", url, timeout=300)
            except ApiError:
                if stop.wait(2.0):
                    return
                rv = ""  # re-list from now
                continue
            try:
                for line in resp:
                    if stop.is_set():
                        return
                    if not line.strip():
                        continue
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        break  # truncated stream; reconnect
                    etype = ev.get("type", "")
                    obj = ev.get("object", {})
                    rv = (obj.get("metadata") or {}).get("resourceVersion", rv)
                    if etype == "BOOKMARK":
                        continue
                    if etype == "ERROR":
                        rv = ""  # 410 Gone et al: restart from fresh list
                        break
                    yield WatchEvent(etype, obj)
            except (OSError, http.client.HTTPException):
                # mid-stream timeout/reset (incl. the 300 s idle timeout on
                # quiet clusters): reconnect from the last seen rv; the
                # controller resync reconciles anything missed in the gap.
                # An abrupt close of a chunked stream surfaces as
                # http.client.IncompleteRead (HTTPException), not OSError.
                if stop.wait(1.0):
                    return
            finally:
                resp.close()

    def watch_pods(self, stop) -> Iterator[WatchEvent]:
        return self._watch("/api/v1/pods", stop)

    def watch_nodes(self, stop) -> Iterator[WatchEvent]:
        return self._watch("/api/v1/nodes", stop)

    def watch_configmaps(self, stop) -> Iterator[WatchEvent]:
        return self._watch("/api/v1/configmaps", stop)
