"""Watch-driven local object stores (the client-go informer/lister shape).

The reference scheduler never GETs a pod on its bind path — client-go
listers answer from a watch-maintained local index, and the apiserver is
only consulted on a miss or a UID mismatch (gpushare-bind.go:45-70).
tpushare's hot paths originally paid synchronous round-trips instead:
every Bind re-GET the pod, ``SchedulerCache.get_node_info`` lazily GET
nodes, and a gang member's Allocate LISTed the whole cluster's pods
twice. This module closes that gap:

- :class:`PodLister` — pods indexed by (namespace, name), by UID, by
  node, and by (namespace, gang-id), maintained from watch events;
- :class:`NodeLister` — nodes by name;
- :class:`Informer` — owns both stores: one initial LIST each, then watch
  streams applied as they arrive. A broken stream relists (heals any gap,
  including 410 Gone compactions the client absorbs internally) after a
  jittered exponential backoff, so a flapping apiserver sees a spread-out
  trickle of relists instead of a reconnect stampede.

resourceVersion bookkeeping: the underlying ``ClusterClient.watch_*``
implementations own rv resume (incluster.py reconnects from the last
seen rv and restarts from "now" on 410); the informer tracks the last
applied rv for observability and treats *any* stream termination as a
potential gap — relist, don't guess.

Listers are best-effort by contract: readers MUST fall back to the
apiserver on miss or staleness signals (UID mismatch). The hit/miss
counters below are how bench.py proves the fallback is the exception.
"""

from __future__ import annotations

import logging
import random
import sys
import threading
import time
from typing import Any

from tpushare.contract.constants import ANN_GANG
from tpushare.metrics import LabeledCounter

log = logging.getLogger("tpushare.k8s.informer")

# process-wide, like CLAIM_CAS_RETRIES: every lister consumer reports
# here so bench.py and /metrics see one hit-rate regardless of wiring
LISTER_REQUESTS = LabeledCounter(
    "tpushare_lister_requests_total",
    "Lister lookups by resource and outcome (miss = apiserver fallback)",
    ("resource", "outcome"))
INFORMER_EVENTS = LabeledCounter(
    "tpushare_informer_events_total",
    "Watch events applied to the local stores", ("resource",))
INFORMER_RELISTS = LabeledCounter(
    "tpushare_informer_relists_total",
    "Full re-LISTs after a watch stream break (gap healing)",
    ("resource",))


def lister_hit_rate() -> float | None:
    """Fraction of lister lookups served locally (None = no lookups)."""
    hits = LISTER_REQUESTS.total(outcome="hit")
    misses = LISTER_REQUESTS.total(outcome="miss")
    if hits + misses == 0:
        return None
    return hits / (hits + misses)


def _meta(obj: dict[str, Any]) -> dict[str, Any]:
    return obj.get("metadata") or {}


class PodLister:
    """Thread-safe pod store with the three indexes the hot paths need."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_key: dict[tuple[str, str], dict[str, Any]] = {}
        self._by_uid: dict[str, tuple[str, str]] = {}
        self._by_node: dict[str, set[tuple[str, str]]] = {}
        self._by_gang: dict[tuple[str, str], set[tuple[str, str]]] = {}

    @staticmethod
    def _pod_key(pod: dict[str, Any]) -> tuple[str, str]:
        meta = _meta(pod)
        return meta.get("namespace", "default"), meta.get("name", "")

    def _unindex(self, key: tuple[str, str], pod: dict[str, Any]) -> None:
        uid = _meta(pod).get("uid", "")
        if uid and self._by_uid.get(uid) == key:
            del self._by_uid[uid]
        node = (pod.get("spec") or {}).get("nodeName", "")
        if node:
            bucket = self._by_node.get(node)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._by_node[node]
        gid = (_meta(pod).get("annotations") or {}).get(ANN_GANG, "")
        if gid:
            gkey = (key[0], gid)
            bucket = self._by_gang.get(gkey)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._by_gang[gkey]

    def _index(self, key: tuple[str, str], pod: dict[str, Any]) -> None:
        uid = _meta(pod).get("uid", "")
        if uid:
            self._by_uid[uid] = key
        node = (pod.get("spec") or {}).get("nodeName", "")
        if node:
            self._by_node.setdefault(sys.intern(node), set()).add(key)
        gid = (_meta(pod).get("annotations") or {}).get(ANN_GANG, "")
        if gid:
            self._by_gang.setdefault((key[0], gid), set()).add(key)

    def apply(self, etype: str, pod: dict[str, Any]) -> None:
        key = self._pod_key(pod)
        with self._lock:
            old = self._by_key.pop(key, None)
            if old is not None:
                self._unindex(key, old)
            if etype != "DELETED":
                self._by_key[key] = pod
                self._index(key, pod)

    def replace(self, pods: list[dict[str, Any]]) -> None:
        with self._lock:
            self._by_key.clear()
            self._by_uid.clear()
            self._by_node.clear()
            self._by_gang.clear()
            for pod in pods:
                key = self._pod_key(pod)
                self._by_key[key] = pod
                self._index(key, pod)

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_key)

    def get(self, namespace: str, name: str) -> dict[str, Any] | None:
        with self._lock:
            return self._by_key.get((namespace, name))

    def by_uid(self, uid: str) -> dict[str, Any] | None:
        with self._lock:
            key = self._by_uid.get(uid)
            return self._by_key.get(key) if key is not None else None

    def on_node(self, node_name: str) -> list[dict[str, Any]]:
        with self._lock:
            keys = self._by_node.get(node_name, ())
            return [self._by_key[k] for k in keys if k in self._by_key]

    def gang_peers(self, namespace: str, gang_id: str
                   ) -> list[dict[str, Any]]:
        """Live view of one gang's pods, namespace-scoped by construction
        (the cross-namespace same-gang-id hazard cannot reach callers)."""
        with self._lock:
            keys = self._by_gang.get((namespace, gang_id), ())
            return [self._by_key[k] for k in keys if k in self._by_key]


class NodeLister:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_name: dict[str, dict[str, Any]] = {}

    def apply(self, etype: str, node: dict[str, Any]) -> None:
        # interned at the ingestion boundary: every layer keyed by node
        # name (cache, index, arena, wirecache) shares ONE string per
        # node instead of one per watch event
        name = sys.intern(_meta(node).get("name", ""))
        if not name:
            return
        with self._lock:
            if etype == "DELETED":
                self._by_name.pop(name, None)
            else:
                self._by_name[name] = node

    def replace(self, nodes: list[dict[str, Any]]) -> None:
        with self._lock:
            self._by_name = {
                sys.intern(_meta(n).get("name", "")): n for n in nodes
                if _meta(n).get("name")}

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_name)

    def get(self, name: str) -> dict[str, Any] | None:
        with self._lock:
            return self._by_name.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return list(self._by_name)


class Informer:
    """Keeps a PodLister + NodeLister warm from one ClusterClient.

    ``start()`` performs the initial LISTs synchronously (so callers see
    a populated store immediately — the same guarantee cache.WaitForCacheSync
    gives client-go consumers) and then spawns one daemon watch thread
    per resource.
    """

    BACKOFF_BASE_S = 0.2
    BACKOFF_CAP_S = 10.0

    def __init__(self, cluster, resync_seconds: float = 0.0,
                 rng: random.Random | None = None) -> None:
        self._cluster = cluster
        self.pods = PodLister()
        self.nodes = NodeLister()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._resync_seconds = resync_seconds
        self._rng = rng or random.Random()
        self.synced = False
        # last applied resourceVersion per resource (observability only;
        # rv resume itself lives in the client's watch implementation)
        self.last_rv: dict[str, str] = {}
        # freshness: monotonic timestamp of the last moment each store
        # was KNOWN current (a relist grounds it absolutely; an applied
        # watch event proves the stream is alive). /readyz reports the
        # worst-resource age as the degraded-mode staleness bound.
        self._fresh_lock = threading.Lock()
        self._last_fresh: dict[str, float] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Informer":
        self._relist("pods")
        self._relist("nodes")
        self.synced = True
        for resource in ("pods", "nodes"):
            t = threading.Thread(target=self._run, args=(resource,),
                                 name=f"tpushare-informer-{resource}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        if self._resync_seconds > 0:
            t = threading.Thread(target=self._resync_loop,
                                 name="tpushare-informer-resync",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)

    # -- internals -----------------------------------------------------------

    def _store(self, resource: str):
        return self.pods if resource == "pods" else self.nodes

    def _list(self, resource: str) -> list[dict[str, Any]]:
        if resource == "pods":
            return self._cluster.list_pods()
        return self._cluster.list_nodes()

    def _watch(self, resource: str):
        if resource == "pods":
            return self._cluster.watch_pods(self._stop)
        return self._cluster.watch_nodes(self._stop)

    def _mark_fresh(self, resource: str) -> None:
        with self._fresh_lock:
            self._last_fresh[resource] = time.monotonic()

    def staleness_s(self) -> float | None:
        """Age of the STALEST store's last freshness proof (relist or
        applied event), or None before the initial sync. On a quiet
        cluster this grows between events even though nothing was
        missed — it is an upper BOUND on staleness, which is exactly
        what degraded-mode consumers need to report honestly."""
        with self._fresh_lock:
            if len(self._last_fresh) < 2:  # pods + nodes
                return None
            oldest = min(self._last_fresh.values())
        return max(0.0, time.monotonic() - oldest)

    def _relist(self, resource: str) -> None:
        self._store(resource).replace(self._list(resource))
        self._mark_fresh(resource)
        INFORMER_RELISTS.inc(resource)

    def _run(self, resource: str) -> None:
        """Watch loop: apply events; on ANY stream termination while the
        stop flag is clear, back off (jittered exponential) and relist —
        the k8s watch API does not replay gaps, so termination means the
        store may have missed events and only a fresh LIST re-grounds it."""
        failures = 0
        while not self._stop.is_set():
            try:
                for ev in self._watch(resource):
                    self._store(resource).apply(ev.type, ev.object)
                    rv = _meta(ev.object).get("resourceVersion")
                    if rv:
                        self.last_rv[resource] = rv
                    self._mark_fresh(resource)
                    INFORMER_EVENTS.inc(resource)
                    failures = 0
            except Exception as e:  # noqa: BLE001 — the stream must heal
                log.warning("informer: %s watch broke: %s", resource, e)
            if self._stop.is_set():
                return
            failures += 1
            # full jitter: delay uniform in (0, base * 2^n], capped —
            # a fleet of replicas reconnecting after one apiserver blip
            # must not relist in lockstep
            cap = min(self.BACKOFF_CAP_S,
                      self.BACKOFF_BASE_S * (2 ** min(failures, 8)))
            if self._stop.wait(self._rng.uniform(0, cap)):
                return
            try:
                self._relist(resource)
            except Exception as e:  # noqa: BLE001
                log.warning("informer: %s relist failed: %s", resource, e)

    def _resync_loop(self) -> None:
        """Optional periodic anti-entropy relist (for deployments without
        a Controller heartbeat watching the same streams)."""
        while not self._stop.wait(self._resync_seconds):
            for resource in ("pods", "nodes"):
                try:
                    self._relist(resource)
                except Exception as e:  # noqa: BLE001
                    log.warning("informer: %s resync failed: %s",
                                resource, e)


def lookup(lister, resource: str, *args: Any,
           counter: LabeledCounter = LISTER_REQUESTS):
    """Counted lister read: returns the object or None, incrementing the
    shared hit/miss counter. ``lister`` may be None (always a miss —
    callers without an informer fall straight through)."""
    if lister is None:
        counter.inc(resource, "miss")
        return None
    obj = lister.get(*args)
    counter.inc(resource, "hit" if obj is not None else "miss")
    return obj
