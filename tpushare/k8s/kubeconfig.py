"""Kubeconfig parsing: the out-of-cluster auth path.

The reference's ``initKubeClient`` honors ``KUBECONFIG`` before falling
back to in-cluster config (/root/reference/cmd/main.go:24-38, client-go
``BuildConfigFromFlags``); this module gives :class:`InClusterClient` the
same dev flow. Supported: ``current-context`` resolution, cluster
``server`` / ``certificate-authority[-data]`` /
``insecure-skip-tls-verify``, user ``token[-file]`` /
``client-certificate[-data]`` + ``client-key[-data]`` / basic-auth, and
``exec`` credential plugins (ExecCredential v1/v1beta1, token only).
Exotic auth providers (gcp/oidc helpers) are out of scope, like most
non-client-go clients.

Kubeconfig is YAML, but PyYAML is in this image so no hand-rolled parser
is needed.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import subprocess
import tempfile
from typing import Any

import yaml


class KubeconfigError(Exception):
    pass


def _by_name(items: list[dict[str, Any]], name: str, what: str,
             payload: str) -> dict[str, Any]:
    for item in items or []:
        if item.get("name") == name:
            return item.get(payload) or {}
    raise KubeconfigError(f"{what} {name!r} not found in kubeconfig")


def _materialize(data_b64: str | None, path: str | None,
                 base_dir: str) -> str | None:
    """Inline ``*-data`` wins over file paths (client-go precedence); data
    is written to a temp file because ssl wants filenames."""
    if data_b64:
        f = tempfile.NamedTemporaryFile(
            mode="wb", suffix=".pem", delete=False)
        f.write(base64.b64decode(data_b64))
        f.close()
        return f.name
    if path:
        return path if os.path.isabs(path) else os.path.join(base_dir, path)
    return None


def _exec_token(spec: dict[str, Any], base_dir: str) -> str:
    """Run an ExecCredential plugin and extract status.token."""
    cmd = [spec["command"], *(spec.get("args") or [])]
    env = dict(os.environ)
    for e in spec.get("env") or []:
        env[e["name"]] = e.get("value", "")
    env["KUBERNETES_EXEC_INFO"] = json.dumps({
        "apiVersion": spec.get("apiVersion",
                               "client.authentication.k8s.io/v1"),
        "kind": "ExecCredential", "spec": {"interactive": False}})
    try:
        out = subprocess.run(cmd, env=env, cwd=base_dir, capture_output=True,
                             text=True, timeout=30, check=True).stdout
        cred = json.loads(out)
        return (cred.get("status") or {})["token"]
    except (OSError, subprocess.SubprocessError, ValueError, KeyError) as e:
        raise KubeconfigError(f"exec credential plugin failed: {e}") from None


class KubeconfigAuth:
    """Resolved connection parameters for one kubeconfig context."""

    def __init__(self, server: str, token: str | None = None,
                 ssl_context: ssl.SSLContext | None = None,
                 basic: tuple[str, str] | None = None) -> None:
        self.server = server
        self.token = token
        self.ssl_context = ssl_context
        self.basic = basic

    def headers(self) -> dict[str, str]:
        if self.token:
            return {"Authorization": f"Bearer {self.token}"}
        if self.basic:
            cred = base64.b64encode(
                f"{self.basic[0]}:{self.basic[1]}".encode()).decode()
            return {"Authorization": f"Basic {cred}"}
        return {}


def load_kubeconfig(path: str | None = None,
                    context: str | None = None) -> KubeconfigAuth:
    """Parse a kubeconfig into connection parameters.

    ``path`` defaults to ``$KUBECONFIG`` (first entry if a list) then
    ``~/.kube/config``; ``context`` defaults to ``current-context``.
    """
    if path is None:
        env = os.environ.get("KUBECONFIG", "")
        path = env.split(os.pathsep)[0] if env else \
            os.path.expanduser("~/.kube/config")
    if not os.path.exists(path):
        raise KubeconfigError(f"kubeconfig not found: {path}")
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    base_dir = os.path.dirname(os.path.abspath(path))

    ctx_name = context or cfg.get("current-context")
    if not ctx_name:
        raise KubeconfigError("no context selected (current-context unset)")
    ctx = _by_name(cfg.get("contexts"), ctx_name, "context", "context")
    cluster = _by_name(cfg.get("clusters"), ctx.get("cluster", ""),
                       "cluster", "cluster")
    user = _by_name(cfg.get("users"), ctx.get("user", ""), "user", "user") \
        if ctx.get("user") else {}

    server = cluster.get("server")
    if not server:
        raise KubeconfigError(f"cluster {ctx.get('cluster')!r} has no server")

    ssl_ctx: ssl.SSLContext | None = None
    if server.startswith("https"):
        ca = _materialize(cluster.get("certificate-authority-data"),
                          cluster.get("certificate-authority"), base_dir)
        ssl_ctx = ssl.create_default_context(cafile=ca)
        if cluster.get("insecure-skip-tls-verify"):
            ssl_ctx.check_hostname = False
            ssl_ctx.verify_mode = ssl.CERT_NONE
        cert = _materialize(user.get("client-certificate-data"),
                            user.get("client-certificate"), base_dir)
        key = _materialize(user.get("client-key-data"),
                           user.get("client-key"), base_dir)
        if cert:
            ssl_ctx.load_cert_chain(cert, key)

    token = user.get("token")
    if not token and user.get("tokenFile"):
        tf = user["tokenFile"]
        tf = tf if os.path.isabs(tf) else os.path.join(base_dir, tf)
        with open(tf) as f:
            token = f.read().strip()
    if not token and user.get("exec"):
        token = _exec_token(user["exec"], base_dir)

    basic = None
    if not token and user.get("username"):
        basic = (user["username"], user.get("password", ""))

    return KubeconfigAuth(server=server, token=token, ssl_context=ssl_ctx,
                          basic=basic)
