"""Cluster client protocol + shared error/merge machinery."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Protocol


class ApiError(Exception):
    """Kubernetes API failure with its HTTP status code.

    ``retry_after`` carries the server's Retry-After header in seconds
    when one was sent (429 priority-and-fairness rejections do); the
    retry policy honors it over its own backoff curve."""

    def __init__(self, status: int, message: str = "",
                 retry_after: float | None = None):
        super().__init__(f"{status}: {message}" if message else str(status))
        self.status = status
        self.message = message
        self.retry_after = retry_after

    @property
    def is_conflict(self) -> bool:  # optimistic-lock loser (409)
        return self.status == 409

    @property
    def is_not_found(self) -> bool:
        return self.status == 404


@dataclass(frozen=True)
class WatchEvent:
    """One apiserver watch event: type ADDED|MODIFIED|DELETED."""

    type: str
    object: dict[str, Any]


class ClusterClient(Protocol):
    """The exact cluster surface tpushare uses.

    Mirrors the reference's dependency set (SURVEY §4: "client-go listers +
    three write calls — Patch, Bind, ListAndWatch"), plus configmap reads
    for unhealthy chips and event creation for observability.
    """

    # reads
    def list_pods(self, node_name: str | None = None,
                  namespace: str | None = None
                  ) -> list[dict[str, Any]]: ...
    def get_pod(self, namespace: str, name: str) -> dict[str, Any]: ...
    def list_nodes(self) -> list[dict[str, Any]]: ...
    def get_node(self, name: str) -> dict[str, Any]: ...
    def get_configmap(self, namespace: str, name: str) -> dict[str, Any]: ...

    # writes
    def patch_pod(self, namespace: str, name: str,
                  patch: dict[str, Any]) -> dict[str, Any]: ...
    def replace_pod(self, namespace: str, name: str,
                    pod: dict[str, Any]) -> dict[str, Any]: ...

    def bind_pod(self, namespace: str, name: str, node: str,
                 uid: str | None = None) -> None: ...
    def create_event(self, namespace: str, event: dict[str, Any]) -> None: ...
    # device-plugin writes (reference device-plugin RBAC includes
    # nodes/status patch and configmap writes, device-plugin-rbac.yaml)
    def patch_node(self, name: str, patch: dict[str, Any],
                   status: bool = False) -> dict[str, Any]: ...
    def put_configmap(self, namespace: str, name: str,
                      data: dict[str, str]) -> None: ...

    # leases (coordination.k8s.io/v1; HA leader election)
    def get_lease(self, namespace: str, name: str) -> dict[str, Any]: ...
    def create_lease(self, namespace: str, name: str,
                     spec: dict[str, Any]) -> dict[str, Any]: ...
    def update_lease(self, namespace: str, name: str, spec: dict[str, Any],
                     resource_version: str | None = None) -> dict[str, Any]: ...

    # watches (blocking iterators; controller runs them on threads)
    def watch_pods(self, stop) -> Iterator[WatchEvent]: ...
    def watch_nodes(self, stop) -> Iterator[WatchEvent]: ...
    def watch_configmaps(self, stop) -> Iterator[WatchEvent]: ...


def strategic_merge(base: dict[str, Any], patch: dict[str, Any]) -> dict[str, Any]:
    """Strategic-merge-patch subset: recursive dict merge, None deletes,
    scalars/lists replace. Sufficient for the metadata.annotations patches
    this framework writes (reference uses types.StrategicMergePatchType,
    nodeinfo.go:198)."""
    out = dict(base)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = strategic_merge(out[k], v)
        else:
            out[k] = v
    return out
