"""Deadline-bounded retry policy for apiserver writes.

The reference treats every transient apiserver failure as a terminal
bind failure (SURVEY §5.3: no write-retry policy anywhere) — a single
5xx during an apiserver brownout fails the bind and burns a full
kube-scheduler webhook timeout before the pod is retried. This module is
the write-path half of the fault-containment layer:

- :class:`RetryPolicy` — exponential backoff with FULL jitter and a
  per-operation attempt budget. Classification is strict:

  * **409 is never retried at this level.** A conflict is an
    optimistic-concurrency *correctness signal* (another writer moved the
    object); replaying the same body would overwrite the winner. The
    call sites that can retry a 409 safely (claim CAS, assigned-flag
    CAS) re-read and re-validate first — that loop belongs to them.
  * **429 honors ``Retry-After``** when the server sent one (the
    apiserver's priority-and-fairness rejections do), falling back to
    the computed backoff otherwise.
  * **5xx and network errors (status 0)** retry within the budget.
  * Everything else (4xx) surfaces immediately.

- **Deadline propagation** — the extender's HTTP server stamps a
  per-request deadline into a thread-local scope
  (:func:`request_deadline`); the retry loop consults it and never
  sleeps past the point where the caller has already given up, raising
  :class:`DeadlineExceeded` instead of burning the webhook timeout.

- :class:`RetryingCluster` — a transparent proxy applying the policy to
  every ClusterClient request/response verb. Watches pass through
  untouched (they have their own reconnect/relist healing in the client
  and informer layers).

POST replay safety: the transport layer (incluster.py) never auto-resends
a POST on a reused-connection error — it surfaces ApiError(0) and THIS
layer decides. Retrying here is safe because every POST the framework
issues tolerates duplicates one level up: a duplicate binding POST gets
409 and the bind path treats bound-to-the-requested-node as idempotent
success; events use generateName and are best-effort; lease creation 409
is the elector's normal lost-race path.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from tpushare.k8s.client import ApiError
from tpushare.metrics import Counter, LabeledCounter
from tpushare.obs.trace import annotate_current

# process-wide (the CLAIM_CAS_RETRIES pattern): attached to the extender
# registry by register_cache_gauges so /metrics exposes them.
RETRY_ATTEMPTS = LabeledCounter(
    "tpushare_apiserver_retry_attempts_total",
    "Transient-failure retries by verb and trigger status class "
    "(each count is one EXTRA round-trip beyond the first attempt)",
    ("verb", "status"))
RETRY_BUDGET_EXHAUSTED = LabeledCounter(
    "tpushare_retry_budget_exhausted_total",
    "Operations that failed after spending their whole retry budget "
    "(sustained growth = the apiserver is down harder than the budget "
    "assumes; alert alongside breaker_state)",
    ("verb",))
DEADLINE_EXCEEDED_TOTAL = Counter(
    "tpushare_request_deadline_exceeded_total",
    "Apiserver operations abandoned because the caller's request "
    "deadline left no room for another attempt")


class DeadlineExceeded(ApiError):
    """The per-request deadline expired before the operation could
    complete (or before another retry attempt would fit). Status 504 so
    existing ApiError handling (rollback, failure accounting) engages;
    callers that care (BindHandler) distinguish it by type."""

    def __init__(self, message: str = "request deadline exceeded"):
        super().__init__(504, message)


# -- per-request deadline scope (thread-local, like stats.api_origin) ---------

_local = threading.local()


def current_deadline() -> float | None:
    """Monotonic deadline of the active request scope, or None."""
    return getattr(_local, "deadline", None)


def deadline_remaining(clock: Callable[[], float] = time.monotonic
                       ) -> float | None:
    """Seconds left in the active request scope (may be negative), or
    None when no deadline is stamped."""
    d = current_deadline()
    return None if d is None else d - clock()


class request_deadline:
    """Stamp a deadline over everything this thread does inside the
    scope::

        with request_deadline(9.0):
            handler.handle(args)   # retries stop before t0 + 9.0

    Nested scopes only ever SHORTEN the deadline (an inner scope cannot
    outlive its caller's patience). Usable as a context manager."""

    def __init__(self, seconds: float | None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._seconds = seconds
        self._clock = clock
        self._prev: float | None = None

    def __enter__(self) -> "request_deadline":
        self._prev = getattr(_local, "deadline", None)
        if self._seconds is not None:
            mine = self._clock() + self._seconds
            _local.deadline = mine if self._prev is None \
                else min(self._prev, mine)
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._prev is None:
            if hasattr(_local, "deadline"):
                del _local.deadline
        else:
            _local.deadline = self._prev


# -- the policy ---------------------------------------------------------------

def is_retryable(e: ApiError) -> bool:
    """Transient-failure classification (see module docstring).
    DeadlineExceeded is terminal by definition even though it rides a
    5xx status, and a breaker fast-fail (no round-trip happened) must
    surface immediately instead of spinning on the local breaker."""
    if isinstance(e, DeadlineExceeded) or getattr(e, "breaker_open", False):
        return False
    return e.status == 0 or e.status == 429 or e.status >= 500


def _status_class(e: ApiError) -> str:
    if e.status == 0:
        return "network"
    if e.status == 429:
        return "429"
    return "5xx"


@dataclass
class RetryPolicy:
    """Exponential backoff + full jitter with a per-operation budget.

    ``max_attempts`` counts TOTAL attempts (first try included), so the
    write amplification of one logical operation is bounded by it — the
    invariant bench.py and the chaos soak check.
    """

    max_attempts: int = 4
    base_s: float = 0.05
    cap_s: float = 2.0
    rng: random.Random = field(default_factory=random.Random)
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic

    def backoff_s(self, attempt: int, e: ApiError | None = None) -> float:
        """Delay before attempt ``attempt + 1`` (0-based). Full jitter:
        uniform in (0, min(cap, base * 2^attempt)] — a storm of binds
        retrying after one apiserver blip must not re-arrive in
        lockstep. A 429's Retry-After overrides the computed value (the
        server knows its own overload better than our curve does)."""
        if e is not None and e.status == 429 and \
                getattr(e, "retry_after", None) is not None:
            return float(e.retry_after)
        cap = min(self.cap_s, self.base_s * (2 ** attempt))
        return self.rng.uniform(0.0, cap) if cap > 0 else 0.0

    def call(self, fn: Callable[[], Any], verb: str = "op") -> Any:
        """Run ``fn`` under the policy. Raises the last error when the
        budget is spent, the error is not transient, or the active
        request deadline leaves no room for another attempt."""
        attempt = 0
        while True:
            try:
                return fn()
            except ApiError as e:
                if not is_retryable(e):
                    raise
                attempt += 1
                if attempt >= self.max_attempts:
                    RETRY_BUDGET_EXHAUSTED.inc(verb)
                    raise
                delay = self.backoff_s(attempt - 1, e)
                remaining = deadline_remaining(self.clock)
                if remaining is not None and delay >= remaining:
                    # the caller will have given up before the retry
                    # could land: stop burning its timeout and say so
                    DEADLINE_EXCEEDED_TOTAL.inc()
                    annotate_current("retry_deadline", verb=verb,
                                     remaining_s=round(remaining, 3))
                    raise DeadlineExceeded(
                        f"{verb}: deadline leaves {remaining:.3f}s, next "
                        f"retry needs {delay:.3f}s (last error: {e})"
                    ) from e
                RETRY_ATTEMPTS.inc(verb, _status_class(e))
                annotate_current("retry", verb=verb,
                                 status=_status_class(e), attempt=attempt,
                                 backoff_s=round(delay, 4))
                if delay > 0:
                    self.sleep(delay)


# -- the proxy ----------------------------------------------------------------

# every ClusterClient request/response verb (watches excluded by design —
# their healing is reconnect+relist, not replay)
_RETRIED_VERBS = frozenset({
    "list_pods", "get_pod", "list_nodes", "get_node", "get_configmap",
    "patch_pod", "replace_pod", "bind_pod", "create_event", "patch_node",
    "put_configmap", "get_lease", "create_lease", "update_lease",
    "list_leases", "forward_post",
})


class RetryingCluster:
    """Transparent ClusterClient proxy applying ``policy`` to every
    request/response verb. Non-protocol attributes (seeding helpers,
    ``injected`` counters on a wrapped ChaosCluster, ...) pass through
    untouched, so tests can stack this over FakeCluster/ChaosCluster."""

    def __init__(self, inner: Any, policy: RetryPolicy | None = None) -> None:
        self._inner = inner
        self.policy = policy or RetryPolicy()

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._inner, name)
        if name not in _RETRIED_VERBS or not callable(attr):
            return attr

        def retried(*args: Any, **kwargs: Any) -> Any:
            return self.policy.call(lambda: attr(*args, **kwargs),
                                    verb=name)
        return retried
