"""Minimal Kubernetes client layer.

The reference leans on client-go (informers, listers, patch/bind calls —
SURVEY §2.7/§2.8). This environment has no kubernetes Python client, so
tpushare ships its own thin layer with exactly the surface the framework
needs, in two implementations:

- :class:`tpushare.k8s.fake.FakeCluster` — in-memory apiserver with watch
  streams and optimistic concurrency, the hermetic-test backend (the
  reference *could* have used client-go's fake clientset; SURVEY §4 calls
  this out as the seam to build on from day one).
- :class:`tpushare.k8s.incluster.InClusterClient` — stdlib http.client
  against the real apiserver using the pod's service-account credentials.

Everything speaks dict-shaped JSON objects; no typed model classes.
"""

from tpushare.k8s.breaker import (
    BreakerCluster,
    BreakerOpenError,
    CircuitBreaker,
    harden,
)
from tpushare.k8s.chaos import ChaosCluster
from tpushare.k8s.client import ApiError, ClusterClient, WatchEvent
from tpushare.k8s.fake import FakeCluster
from tpushare.k8s.informer import Informer, NodeLister, PodLister
from tpushare.k8s.retry import (
    DeadlineExceeded,
    RetryingCluster,
    RetryPolicy,
    request_deadline,
)
from tpushare.k8s.singleflight import Singleflight
from tpushare.k8s.stats import CountingCluster, api_origin

__all__ = ["ApiError", "ChaosCluster", "ClusterClient", "WatchEvent",
           "FakeCluster", "Informer", "NodeLister", "PodLister",
           "Singleflight", "CountingCluster", "api_origin",
           "RetryPolicy", "RetryingCluster", "DeadlineExceeded",
           "request_deadline", "CircuitBreaker", "BreakerCluster",
           "BreakerOpenError", "harden"]
