"""Replica-to-replica forward transport for owner forwarding.

When a Filter/Prioritize/Bind lands on a replica that does not own the
target shard (ha/forward.py), the request hops once to the owner over
plain HTTP. The transport is deliberately thin — one verb,
``forward_post`` — and rides the same fault-containment stack as the
apiserver client (:func:`tpushare.k8s.breaker.harden`), but with its own
per-peer breaker and a much tighter budget: a forward is an
*optimization* over the claim-CAS spillover path, so a sick peer must
fail fast into the local fallback rather than burn the webhook timeout.

Error contract: ``forward_post`` returns ``(status, body)`` for ANY
HTTP response the peer produced — a 500 from the owner is an application
verdict to relay verbatim, not a transport failure — and raises
``ApiError(0, ...)`` only when no response arrived (connect/read
failure). That keeps the breaker accounting honest (`answered` =
healthy peer) and makes retry classification fall out of the existing
``is_retryable`` rules.

Replay safety: the keep-alive pool never auto-resends a POST
(incluster.py ``_REPLAY_SAFE``); a reused-socket failure surfaces as
ApiError(0) and the retry policy replays it. That is safe for forwards
because the forwarded operations tolerate duplicates by construction —
a duplicate bind is the idempotent already-bound-here path, and
Filter/Prioritize are reads.

Lock discipline: the pool lock only guards the transport map; no lock
is ever held across a forward round-trip (the hop runs on a checked-out
transport object).
"""

from __future__ import annotations

import os
import threading
import urllib.parse

from tpushare.k8s.breaker import CircuitBreaker, harden
from tpushare.k8s.client import ApiError
from tpushare.k8s.incluster import _ConnPool
from tpushare.k8s.retry import RetryPolicy

DEFAULT_FORWARD_TIMEOUT_S = 2.0


def forward_timeout_s() -> float:
    try:
        return float(os.environ.get("TPUSHARE_FORWARD_TIMEOUT_S",
                                    DEFAULT_FORWARD_TIMEOUT_S))
    except ValueError:
        return DEFAULT_FORWARD_TIMEOUT_S


class PeerTransport:
    """One peer's keep-alive HTTP channel; the ``forward_post`` verb is
    what the retry/breaker proxies gate on."""

    def __init__(self, base_url: str,
                 timeout: float | None = None) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = forward_timeout_s() if timeout is None else timeout
        parsed = urllib.parse.urlsplit(self.base_url)
        self._pool = _ConnPool(
            parsed.hostname or "localhost",
            parsed.port or (443 if parsed.scheme == "https" else 80),
            parsed.scheme == "https", None, max_idle=4)

    def forward_post(self, path: str, body: bytes,
                     headers: dict[str, str]) -> tuple[int, bytes]:
        hdrs = {"Content-Type": "application/json",
                "Content-Length": str(len(body))}
        hdrs.update(headers)
        try:
            status, data, _ = self._pool.request(
                "POST", path, body, hdrs, self.timeout)
        except OSError as e:
            raise ApiError(0, f"peer {self.base_url}: {e}") from None
        except Exception as e:  # http.client.HTTPException et al
            raise ApiError(0, f"peer {self.base_url}: {e}") from None
        return status, data


class PeerPool:
    """Hardened transports keyed by peer URL, built lazily.

    Each peer gets its own breaker (one sick replica must not poison
    forwards to the healthy ones) with a short reset so a restarted
    replica is probed again within a couple of seconds, and a 2-attempt
    retry budget — one replay for a stale keep-alive socket, nothing
    more; the local CAS fallback is always available and cheaper than a
    third round-trip.
    """

    def __init__(self, timeout: float | None = None, *,
                 failure_threshold: int = 3,
                 reset_timeout_s: float = 2.0) -> None:
        self._timeout = timeout
        self._failure_threshold = failure_threshold
        self._reset_timeout_s = reset_timeout_s
        self._lock = threading.Lock()  # guards the map only, never I/O
        self._transports: dict[str, object] = {}

    def _get(self, base_url: str):
        with self._lock:
            t = self._transports.get(base_url)
            if t is None:
                t = harden(
                    PeerTransport(base_url, timeout=self._timeout),
                    breaker=CircuitBreaker(
                        failure_threshold=self._failure_threshold,
                        reset_timeout_s=self._reset_timeout_s),
                    policy=RetryPolicy(max_attempts=2))
                self._transports[base_url] = t
            return t

    def forward(self, base_url: str, path: str, body: bytes,
                headers: dict[str, str]) -> tuple[int, bytes]:
        """POST ``body`` to ``base_url + path``. Returns the peer's
        ``(status, body)``; raises ApiError (incl. BreakerOpenError) when
        the peer could not be reached."""
        return self._get(base_url).forward_post(path, body, headers)
