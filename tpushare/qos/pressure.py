"""Pressure-driven eviction: reclaim best-effort HBM from chips that a
guaranteed/burstable bind has pushed past physical capacity.

Oversubscription is an *admission-time* promise ("best-effort work may
borrow idle HBM") that becomes a *runtime* debt the moment higher-tier
demand lands on the borrowed chip: the chip's grant sum now exceeds its
physical HBM, and only evicting best-effort borrowers pays it down.
This monitor is the collector. It scans the cache for chips where
``used > total`` with non-best-effort usage present (pure best-effort
overcommit below the bound is the intended state, not pressure) and
deletes best-effort victims until the chip is physically whole again.

Every defense the defrag executor earned is reused verbatim:

1. **Budget governor** — ``TPUSHARE_QOS_EVICT_BUDGET`` evictions per
   ``TPUSHARE_QOS_EVICT_WINDOW_S`` rolling window, one in-flight
   eviction per node, per-node backoff (``TPUSHARE_QOS_EVICT_BACKOFF_S``)
   after a failure. An eviction storm is bounded disruption, never a
   cascade.
2. **Stamp revalidation** — the victim is planned under the node lock
   against the node's ``(epoch, counter)`` stamp; immediately before
   the delete, the live stamp is compared and the victim's identity
   (still cached, still bound here, still best-effort) re-checked. Any
   mismatch demotes the eviction un-executed; the next scan re-derives
   it from fresh state. One victim is planned per pass — an eviction
   bumps the stamp, so batching victims against one stamp would
   self-demote.
3. **Graceful degradation** — ``_FAILURE_LATCH_N`` consecutive delete
   transport failures latch the evictor-degraded flag
   (:func:`tpushare.qos.tiers.set_degraded`): ``effective_overcommit``
   collapses to 1.0, oversubscribed admissions stop fleet-wide, and
   guaranteed/burstable admissions continue on the unchanged legacy
   path. The first successful delete clears the latch.

``self._lock`` guards ONLY budget/backoff/in-flight/pressure-note
bookkeeping and is NEVER held across an eviction, a node lock, or a
solve — leftmost in the lock order (tests/test_lock_order_lint.py).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable

from tpushare.metrics import LabeledCounter
from tpushare.qos.tiers import clear_degraded, set_degraded

log = logging.getLogger("tpushare.qos")

# eviction outcomes are a CLOSED enum (label cardinality):
#   completed       — victim deleted and un-accounted; HBM reclaimed
#   failed          — the delete raised; node enters backoff
#   demoted         — the node's stamp (or the victim's identity) moved
#                     between planning and eviction; nothing was touched
#   skipped_budget  — the window's eviction budget is spent
#   skipped_backoff — the node is in post-failure backoff
#   skipped_inflight— the node already has an eviction in flight
QOS_EVICTIONS = LabeledCounter(
    "tpushare_qos_evictions_total",
    "Pressure-driven best-effort evictions by tier and outcome "
    "(completed / failed / demoted / skipped_budget / skipped_backoff / "
    "skipped_inflight). Sustained growth of 'completed' is a capacity "
    "incident — guaranteed demand is routinely landing on borrowed HBM "
    "(docs/ops.md); sustained 'failed' latches the evictor-degraded "
    "flag and stops oversubscribed admissions",
    ("tier", "outcome"))

_FAILURE_LATCH_N = 3


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class QosPressureMonitor:
    """Scans for physically oversubscribed chips and evicts best-effort
    victims under the defrag executor's budget/backoff/stamp regime."""

    def __init__(self, cache, cluster,
                 budget: int | None = None,
                 window_s: float | None = None,
                 backoff_s: float | None = None,
                 interval_s: float = 2.0,
                 time_fn: Callable[[], float] = time.monotonic) -> None:
        self._cache = cache
        self._cluster = cluster
        self.interval_s = interval_s
        self._time = time_fn
        self.budget = int(_env_float("TPUSHARE_QOS_EVICT_BUDGET", 4)) \
            if budget is None else budget
        self.window_s = _env_float("TPUSHARE_QOS_EVICT_WINDOW_S", 60.0) \
            if window_s is None else window_s
        self.backoff_s = _env_float("TPUSHARE_QOS_EVICT_BACKOFF_S", 120.0) \
            if backoff_s is None else backoff_s
        # guards ONLY the bookkeeping below; never held across an
        # eviction, a node lock or a solve (lock-order: leftmost)
        self._lock = threading.Lock()
        self._window_started: float | None = None
        self._window_used = 0
        self._backoff: dict[str, float] = {}   # node -> retry-after time
        self._inflight: set[str] = set()       # nodes with an evict running
        self._notes: set[str] = set()          # nodes prodded by admission
        self._consecutive_failures = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- pressure notes -------------------------------------------------------

    def note_pressure(self, node_name: str) -> None:
        """Admission saw (or caused) pressure on this node: scan it at
        the front of the next pass instead of waiting a full sweep."""
        with self._lock:
            self._notes.add(node_name)

    def _drain_notes(self) -> list[str]:
        with self._lock:
            notes = sorted(self._notes)
            self._notes.clear()
        return notes

    # -- budget governor (the defrag executor's, verbatim) --------------------

    def budget_state(self) -> dict[str, Any]:
        now = self._time()
        with self._lock:
            remaining = None
            if self._window_started is not None:
                remaining = max(
                    self.window_s - (now - self._window_started), 0.0)
            return {
                "budget": self.budget,
                "window_s": self.window_s,
                "used_in_window": self._window_used,
                "window_remaining_s": round(remaining, 3)
                if remaining is not None else None,
                "backoff_nodes": sorted(
                    n for n, t in self._backoff.items() if t > now),
                "inflight_nodes": sorted(self._inflight),
                "consecutive_failures": self._consecutive_failures,
            }

    def _admit(self, node_name: str) -> str | None:
        """Budget/backoff/in-flight gate; returns the skip outcome or
        None (admitted — the window slot is consumed and the node is
        marked in flight)."""
        now = self._time()
        with self._lock:
            if self._window_started is None \
                    or now - self._window_started >= self.window_s:
                self._window_started = now
                self._window_used = 0
            if self._window_used >= self.budget:
                return "skipped_budget"
            if self._backoff.get(node_name, 0.0) > now:
                return "skipped_backoff"
            if node_name in self._inflight:
                return "skipped_inflight"
            self._window_used += 1
            self._inflight.add(node_name)
            return None

    def _settle(self, node_name: str, failed: bool) -> None:
        now = self._time()
        with self._lock:
            self._inflight.discard(node_name)
            if failed:
                self._backoff[node_name] = now + self.backoff_s
            # drop expired entries so the map cannot grow unboundedly
            self._backoff = {n: t for n, t in self._backoff.items()
                             if t > now}

    # -- degraded latch -------------------------------------------------------

    def _record_transport(self, failed: bool) -> None:
        with self._lock:
            if failed:
                self._consecutive_failures += 1
                n = self._consecutive_failures
            else:
                self._consecutive_failures = 0
                n = 0
        if failed and n >= _FAILURE_LATCH_N:
            if n == _FAILURE_LATCH_N:
                log.warning(
                    "qos: %d consecutive eviction failures — latching "
                    "degraded (oversubscribed admissions stop)", n)
            set_degraded()
        elif not failed:
            clear_degraded()

    # -- one eviction, three defenses -----------------------------------------

    def _evict_one(self, node_name: str) -> str | None:
        """Plan and execute at most one eviction on this node. Returns
        the outcome, or None when the node shows no pressure."""
        from tpushare.contract import pod as podlib
        from tpushare.qos.tiers import TIER_BEST_EFFORT, pod_tier
        info = self._cache.peek_node(node_name)
        if info is None:
            return None
        plan = info.pressure_victim()
        if plan is None:
            return None
        key, hbm, chip, stamp = plan
        outcome = self._admit(node_name)
        if outcome is not None:
            QOS_EVICTIONS.inc(TIER_BEST_EFFORT, outcome)
            return outcome
        failed_transport = False
        try:
            # stamp + identity revalidation: the plan is speculative
            live = self._cache.peek_node(node_name)
            pod = self._cache.pod_by_key(key)
            if live is None or live.version != stamp \
                    or pod is None \
                    or podlib.pod_node_name(pod) != node_name \
                    or pod_tier(pod) != TIER_BEST_EFFORT:
                outcome = "demoted"
                return outcome
            ns, name = podlib.pod_namespace(pod), podlib.pod_name(pod)
            try:
                self._cluster.delete_pod(ns, name)
            except Exception as e:  # noqa: BLE001 — transport, not logic
                failed_transport = True
                outcome = "failed"
                log.warning("qos: evicting %s from %s/%d failed: %s",
                            key, node_name, chip, e)
                return outcome
            self._cache.remove_pod(pod)
            outcome = "completed"
            log.info("qos: evicted best-effort %s (%d MiB) from %s/%d "
                     "under pressure", key, hbm, node_name, chip)
            return outcome
        finally:
            self._settle(node_name, failed=outcome == "failed")
            self._record_transport(failed_transport)
            QOS_EVICTIONS.inc(TIER_BEST_EFFORT, outcome)

    def scan_node(self, node_name: str, max_evictions: int = 16) -> int:
        """Evict until this node shows no pressure, a skip outcome
        stops progress, or ``max_evictions`` is hit. Returns completed
        eviction count."""
        done = 0
        for _ in range(max_evictions):
            outcome = self._evict_one(node_name)
            if outcome is None:
                break
            if outcome != "completed":
                break
            done += 1
        return done

    def scan_once(self) -> int:
        """One full pass: prodded nodes first, then the whole fleet.
        Returns completed eviction count."""
        done = 0
        seen: set[str] = set()
        for name in self._drain_notes():
            seen.add(name)
            done += self.scan_node(name)
        for name in self._cache.node_names():
            if name not in seen:
                done += self.scan_node(name)
        return done

    # -- lifecycle ------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — the monitor must outlive
                log.exception("qos: pressure scan failed; continuing")

    def start(self) -> "QosPressureMonitor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="qos-pressure", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
