"""Per-tenant dominant-resource fairness over (chips x HBM).

DRF (Ghodsi et al., NSDI'11) generalizes max-min fairness to multiple
resource types: a tenant's *dominant share* is the larger of its
fractional claims on the fleet's two scarce resources — chips occupied
and HBM reserved. The cap (``TPUSHARE_QOS_DRF_CAP``, a fraction in
(0, 1]; 1.0 = off, the default) bounds any one namespace's dominant
share: an admission that would push a tenant past the cap is rejected
in the QoS filter branch, so a single namespace cannot monopolize the
fleet however it mixes wide-and-shallow (many chips, little HBM) with
narrow-and-deep (few chips, huge HBM) pods.

Tenancy is the pod's namespace — the one identity the scheduler always
has, already a Kubernetes isolation boundary, and low-cardinality
enough to be a metric label (``tpushare_tenant_dominant_share``).

Usage is read from each node's ``audit_snapshot()`` (confirmed grants
only — in-flight reservations are the caller's concern) and attributed
via ``cache.pod_by_key``; keys the cache no longer knows fall back to
their ``ns/name`` spelling, so a just-deleted pod cannot unattribute
its residual accounting mid-scan.
"""

from __future__ import annotations

import os
from typing import Any


def drf_cap() -> float:
    """The dominant-share cap per namespace. 1.0 (default) disables
    enforcement; values outside (0, 1] are treated as disabled."""
    from tpushare.qos.tiers import ENV_DRF_CAP
    raw = os.environ.get(ENV_DRF_CAP, "") or "1.0"
    try:
        cap = float(raw)
    except ValueError:
        return 1.0
    return cap if 0.0 < cap <= 1.0 else 1.0


def _key_namespace(cache: Any, key: str) -> str:
    pod = cache.pod_by_key(key) if cache is not None else None
    if isinstance(pod, dict):
        ns = (pod.get("metadata") or {}).get("namespace")
        if ns:
            return str(ns)
    return key.split("/", 1)[0] if "/" in key else "default"


def tenant_usage(cache: Any) -> dict[str, dict[str, float]]:
    """Per-namespace ``{"chips": n, "hbm_mib": m}`` plus the fleet
    totals under the ``"_fleet"`` pseudo-tenant. Chips count once per
    (node, chip) a tenant touches, however many of its pods share it."""
    totals_chips = 0
    totals_hbm = 0
    tenants: dict[str, dict[str, float]] = {}
    tenant_chips: dict[str, set[tuple[str, int]]] = {}
    for name in cache.node_names():
        info = cache.peek_node(name)
        if info is None:
            continue
        _, node_total = info.hbm_usage()
        totals_hbm += node_total
        _, per_chip = info.audit_snapshot()
        totals_chips += len(info.chips)
        for cid, entries in enumerate(per_chip):
            for key, hbm in entries.items():
                ns = _key_namespace(cache, key)
                t = tenants.setdefault(ns, {"chips": 0.0, "hbm_mib": 0.0})
                t["hbm_mib"] += hbm
                tenant_chips.setdefault(ns, set()).add((name, cid))
    for ns, chips in tenant_chips.items():
        tenants[ns]["chips"] = float(len(chips))
    tenants["_fleet"] = {"chips": float(totals_chips),
                         "hbm_mib": float(totals_hbm)}
    return tenants


def dominant_shares(cache: Any) -> dict[str, float]:
    """``{namespace: dominant share in [0, 1]}`` for every namespace
    with any confirmed grant. Empty fleet -> empty dict."""
    usage = tenant_usage(cache)
    fleet = usage.pop("_fleet")
    if fleet["chips"] <= 0 or fleet["hbm_mib"] <= 0:
        return {}
    return {
        ns: max(t["chips"] / fleet["chips"],
                t["hbm_mib"] / fleet["hbm_mib"])
        for ns, t in usage.items()
    }


def admission_would_exceed(cache: Any, namespace: str,
                           add_chips: int, add_hbm_mib: int,
                           cap: float | None = None) -> bool:
    """Would granting ``namespace`` another ``add_chips`` chips /
    ``add_hbm_mib`` MiB push its dominant share past the cap? Always
    False when the cap is disabled (1.0) or fleet totals are zero."""
    cap = drf_cap() if cap is None else cap
    if cap >= 1.0:
        return False
    usage = tenant_usage(cache)
    fleet = usage.pop("_fleet")
    if fleet["chips"] <= 0 or fleet["hbm_mib"] <= 0:
        return False
    t = usage.get(namespace, {"chips": 0.0, "hbm_mib": 0.0})
    share = max((t["chips"] + add_chips) / fleet["chips"],
                (t["hbm_mib"] + add_hbm_mib) / fleet["hbm_mib"])
    return share > cap
