"""The QoS tier vocabulary: annotation parsing, rank order, overcommit.

Three tiers, ranked for eviction/preemption purposes:

==============  ====  =====================================================
tier            rank  semantics
==============  ====  =====================================================
``guaranteed``     2  hard HBM reservation — NEVER violated at any
                      sampled instant on apiserver truth (the QoS
                      invariant monitor pages on it)
``burstable``      1  the legacy single-class behavior; every pod
                      without a tier annotation lands here, so a fleet
                      that never sets the annotation behaves byte-for-
                      byte as before this subsystem existed
``best-effort``    0  may be admitted into idle guaranteed/burstable
                      headroom beyond a chip's physical HBM (bounded by
                      ``TPUSHARE_QOS_OVERCOMMIT``); first evicted when
                      higher-tier demand arrives
==============  ====  =====================================================

Everything here is pure functions over pod dicts + env knobs; the only
import is ``tpushare.contract`` so the cache layer (nodeinfo, chipusage)
can use it without cycles.

The master gate is :func:`effective_overcommit`: when it returns 1.0
(the library default — the chart ships 1.25) every QoS code path in the
scheduler collapses to the legacy behavior. It also consults the
evictor-degraded latch (set by the pressure monitor after consecutive
eviction-transport failures): a dead evictor means oversubscribed
admissions must stop — admitting reclaimable work nobody can reclaim
converts "best-effort slowdown" into "guaranteed violation" — while
guaranteed/burstable admissions continue on the unchanged legacy path.
"""

from __future__ import annotations

import os
import threading
from typing import Any

from tpushare import contract

TIER_GUARANTEED = "guaranteed"
TIER_BURSTABLE = "burstable"
TIER_BEST_EFFORT = "best-effort"

TIER_RANK: dict[str, int] = {
    TIER_BEST_EFFORT: 0,
    TIER_BURSTABLE: 1,
    TIER_GUARANTEED: 2,
}
TIERS: tuple[str, ...] = (TIER_BEST_EFFORT, TIER_BURSTABLE,
                          TIER_GUARANTEED)

ENV_OVERCOMMIT = "TPUSHARE_QOS_OVERCOMMIT"
ENV_DRF_CAP = "TPUSHARE_QOS_DRF_CAP"


def pod_tier(pod: dict[str, Any] | None) -> str:
    """The pod's QoS tier from its annotation; unannotated (or
    unparseable) pods are ``burstable`` — the legacy class."""
    if not isinstance(pod, dict):
        return TIER_BURSTABLE
    ann = (pod.get("metadata") or {}).get("annotations") or {}
    raw = str(ann.get(contract.ANN_QOS_TIER, "")).strip().lower()
    return raw if raw in TIER_RANK else TIER_BURSTABLE


def tier_rank(tier: str) -> int:
    """Eviction order rank; unknown strings rank as burstable."""
    return TIER_RANK.get(tier, TIER_RANK[TIER_BURSTABLE])


def overcommit() -> float:
    """The configured overcommit factor (>= 1.0). 1.0 — the library
    default — disables oversubscription entirely."""
    raw = os.environ.get(ENV_OVERCOMMIT, "") or "1.0"
    try:
        oc = float(raw)
    except ValueError:
        return 1.0
    return oc if oc >= 1.0 else 1.0


# -- evictor-degraded latch ---------------------------------------------------
# Module-level so the pressure monitor (which owns setting it) and the
# admission path (which only reads it) need no object plumbing between
# the extender layer and the cache layer. threading.Event is atomic;
# no lock order to classify.
_degraded = threading.Event()


def set_degraded() -> None:
    """Evictor transport is down: stop oversubscribed admissions."""
    _degraded.set()


def clear_degraded() -> None:
    _degraded.clear()


def is_degraded() -> bool:
    return _degraded.is_set()


def effective_overcommit() -> float:
    """The overcommit factor admission must honor RIGHT NOW: the
    configured knob, degraded to 1.0 while the evictor latch is set.
    Every QoS branch in the scheduler gates on ``> 1.0`` of this."""
    return 1.0 if _degraded.is_set() else overcommit()
