"""QoS tiers: guaranteed / burstable / best-effort HBM classes.

Three submodules, layered so imports stay acyclic:

- :mod:`tpushare.qos.tiers` — the tier vocabulary (annotation parsing,
  rank order, overcommit knobs). Imports only ``tpushare.contract``;
  the cache layer imports it freely.
- :mod:`tpushare.qos.drf` — per-tenant dominant-resource shares over
  (chips x HBM) and the namespace cap.
- :mod:`tpushare.qos.pressure` — the pressure monitor that evicts
  best-effort victims from physically oversubscribed chips. Imports
  the cache layer, so nothing below the extender may import it; it is
  deliberately NOT re-exported here.
"""

from tpushare.qos.drf import dominant_shares, drf_cap, tenant_usage
from tpushare.qos.tiers import (
    TIER_BEST_EFFORT,
    TIER_BURSTABLE,
    TIER_GUARANTEED,
    TIER_RANK,
    TIERS,
    effective_overcommit,
    overcommit,
    pod_tier,
    tier_rank,
)

__all__ = [
    "TIER_BEST_EFFORT",
    "TIER_BURSTABLE",
    "TIER_GUARANTEED",
    "TIER_RANK",
    "TIERS",
    "dominant_shares",
    "drf_cap",
    "effective_overcommit",
    "overcommit",
    "pod_tier",
    "tenant_usage",
    "tier_rank",
]
