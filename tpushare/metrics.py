"""Tiny Prometheus-text-format metrics registry.

The reference exposes only pprof (SURVEY §5.1, §5.5: "No Prometheus
metrics") — this is one of the deliberate upgrades: the BASELINE metrics
(utilization %, fragmentation, schedule latency) are first-class exports.
No client library exists in this environment, so this implements the text
exposition format directly.
"""

from __future__ import annotations

import threading
from typing import Callable


class Counter:
    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def expose(self) -> str:
        return (f"# HELP {self.name} {self.help}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {self.value}\n")


class LabeledCounter:
    """Counter with a fixed label set, one series per label-value tuple
    (the Prometheus `name{a="x",b="y"} v` exposition). Series are created
    on first increment, so an idle verb/origin pair costs nothing."""

    def __init__(self, name: str, help_: str,
                 labelnames: tuple[str, ...]) -> None:
        self.name, self.help = name, help_
        self.labelnames = tuple(labelnames)
        self._series: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, *labelvalues: str, n: float = 1.0) -> None:
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"values {self.labelnames}, got {labelvalues!r}")
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + n

    def get(self, *labelvalues: str) -> float:
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            return self._series.get(key, 0.0)

    def snapshot(self) -> dict[tuple[str, ...], float]:
        """Copy of every series — bench/tests diff two snapshots to
        attribute counts to one measured window."""
        with self._lock:
            return dict(self._series)

    def total(self, **match: str) -> float:
        """Sum of all series whose labels match ``match`` (subset)."""
        idx = {self.labelnames.index(k): v for k, v in match.items()}
        with self._lock:
            return sum(v for key, v in self._series.items()
                       if all(key[i] == want for i, want in idx.items()))

    def expose(self) -> str:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            series = sorted(self._series.items())
        for key, v in series:
            labels = ",".join(f'{n}="{val}"'
                              for n, val in zip(self.labelnames, key))
            out.append(f"{self.name}{{{labels}}} {v}")
        return "\n".join(out) + "\n"


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics)."""

    def __init__(self, name: str, help_: str,
                 buckets: tuple[float, ...]) -> None:
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1

    def expose(self) -> str:
        with self._lock:
            counts = list(self._counts)
            s = self._sum
        total = sum(counts)
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += counts[i]
            out.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {s}")
        out.append(f"{self.name}_count {total}")
        return "\n".join(out) + "\n"


class Registry:
    def __init__(self) -> None:
        self._metrics: list = []
        self._gauges: list[tuple[str, str, Callable[[], list[tuple[str, float]]]]] = []

    def counter(self, name: str, help_: str) -> Counter:
        c = Counter(name, help_)
        self._metrics.append(c)
        return c

    def labeled_counter(self, name: str, help_: str,
                        labelnames: tuple[str, ...]) -> LabeledCounter:
        c = LabeledCounter(name, help_, labelnames)
        self._metrics.append(c)
        return c

    def register(self, metric) -> None:
        """Attach an externally owned metric (e.g. a module-level Counter
        living in a lower layer) so it exposes with its own TYPE line."""
        if metric not in self._metrics:
            self._metrics.append(metric)

    def histogram(self, name: str, help_: str,
                  buckets: tuple[float, ...]) -> Histogram:
        h = Histogram(name, help_, buckets)
        self._metrics.append(h)
        return h

    def gauge_func(self, name: str, help_: str,
                   fn: Callable[[], list[tuple[str, float]]]) -> None:
        """Gauge computed at scrape time; fn returns (labels, value) pairs
        where labels is the rendered label string ('' for none)."""
        self._gauges.append((name, help_, fn))

    def expose(self) -> str:
        parts = [m.expose() for m in self._metrics]
        for name, help_, fn in self._gauges:
            lines = [f"# HELP {name} {help_}", f"# TYPE {name} gauge"]
            try:
                for labels, value in fn():
                    lines.append(f"{name}{labels} {value}")
            except Exception:
                continue  # scrape must not fail because one gauge did
            parts.append("\n".join(lines) + "\n")
        return "".join(parts)


# latency buckets tuned around the 50 ms p50 target (BASELINE.md)
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5)
