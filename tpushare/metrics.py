"""Tiny Prometheus-text-format metrics registry.

The reference exposes only pprof (SURVEY §5.1, §5.5: "No Prometheus
metrics") — this is one of the deliberate upgrades: the BASELINE metrics
(utilization %, fragmentation, schedule latency) are first-class exports.
No client library exists in this environment, so this implements the text
exposition format directly.
"""

from __future__ import annotations

import threading
from typing import Callable

# -- label hardening ----------------------------------------------------------
# Label VALUES must stay low-cardinality: node names and closed enums
# only — never pod names, UIDs or messages (each distinct value is a
# forever-growing series in every scrape). The registry enforces it
# mechanically: values are truncated to _MAX_LABEL_LEN, and a labeled
# metric refuses to grow past its max_series cap — overflow traffic is
# folded into a single "_overflow" series and counted here, so a
# cardinality bomb degrades into one visible counter instead of an OOM.
_MAX_LABEL_LEN = 120
DEFAULT_MAX_SERIES = 1024

# declared before LabeledCounter exists; bound at module end (Python
# resolves the global at call time, and inc() can only run post-import)
METRIC_SERIES_CLAMPED: "LabeledCounter"


def _clean_label_value(v) -> str:
    v = str(v)
    if len(v) > _MAX_LABEL_LEN:
        v = v[:_MAX_LABEL_LEN]
    return v


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (backslash, quote,
    newline) — an unescaped quote in a value corrupts every line after
    it for strict parsers."""
    return (v.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


class Counter:
    def __init__(self, name: str, help_: str) -> None:
        self.name, self.help = name, help_
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def state(self) -> dict:
        """JSON-able snapshot for cross-process federation (see
        extender/federation.py): mergeable by summing."""
        with self._lock:
            return {"type": "counter", "help": self.help, "value": self._v}

    def expose(self) -> str:
        return (f"# HELP {self.name} {_escape_help(self.help)}\n"
                f"# TYPE {self.name} counter\n"
                f"{self.name} {self.value}\n")


class LabeledCounter:
    """Counter with a fixed label set, one series per label-value tuple
    (the Prometheus `name{a="x",b="y"} v` exposition). Series are created
    on first increment, so an idle verb/origin pair costs nothing."""

    def __init__(self, name: str, help_: str,
                 labelnames: tuple[str, ...],
                 max_series: int = DEFAULT_MAX_SERIES) -> None:
        self.name, self.help = name, help_
        self.labelnames = tuple(labelnames)
        self.max_series = max_series
        self._series: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, *labelvalues: str, n: float = 1.0) -> None:
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"values {self.labelnames}, got {labelvalues!r}")
        key = tuple(_clean_label_value(v) for v in labelvalues)
        clamped = False
        with self._lock:
            if key not in self._series and \
                    len(self._series) >= self.max_series:
                # cardinality bomb containment: fold the overflow into
                # one sentinel series instead of growing without bound
                key = ("_overflow",) * len(self.labelnames)
                clamped = True
            self._series[key] = self._series.get(key, 0.0) + n
        if clamped and self is not METRIC_SERIES_CLAMPED:
            METRIC_SERIES_CLAMPED.inc(self.name)

    def get(self, *labelvalues: str) -> float:
        key = tuple(str(v) for v in labelvalues)
        with self._lock:
            return self._series.get(key, 0.0)

    def snapshot(self) -> dict[tuple[str, ...], float]:
        """Copy of every series — bench/tests diff two snapshots to
        attribute counts to one measured window."""
        with self._lock:
            return dict(self._series)

    def total(self, **match: str) -> float:
        """Sum of all series whose labels match ``match`` (subset)."""
        idx = {self.labelnames.index(k): v for k, v in match.items()}
        with self._lock:
            return sum(v for key, v in self._series.items()
                       if all(key[i] == want for i, want in idx.items()))

    def state(self) -> dict:
        """JSON-able snapshot for federation: series as [labels, value]
        pairs (JSON has no tuple keys); merged by summing per key."""
        with self._lock:
            series = [[list(k), v] for k, v in sorted(self._series.items())]
        return {"type": "labeled_counter", "help": self.help,
                "labelnames": list(self.labelnames), "series": series}

    def expose(self) -> str:
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            series = sorted(self._series.items())
        for key, v in series:
            labels = ",".join(f'{n}="{_escape_label_value(val)}"'
                              for n, val in zip(self.labelnames, key))
            out.append(f"{self.name}{{{labels}}} {v}")
        return "\n".join(out) + "\n"


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics), with optional
    trace exemplars: ``observe(v, exemplar=<trace id>)`` remembers, per
    bucket, the latest trace id that landed there — so a p99 spike on a
    phase histogram points straight at a /debug/traces timeline instead
    of a needle hunt. Exemplars ride the JSON side (/debug/traces,
    :meth:`exemplars`), keeping /metrics strict text-format 0.0.4."""

    def __init__(self, name: str, help_: str,
                 buckets: tuple[float, ...]) -> None:
        self.name, self.help = name, help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._exemplars: list[tuple[str, float] | None] = \
            [None] * (len(self.buckets) + 1)
        self._lock = threading.Lock()

    def _bucket_index(self, v: float) -> int:
        for i, b in enumerate(self.buckets):
            if v <= b:
                return i
        return len(self.buckets)

    def observe(self, v: float, exemplar: str | None = None) -> None:
        i = self._bucket_index(v)
        with self._lock:
            self._sum += v
            self._counts[i] += 1
            if exemplar:
                self._exemplars[i] = (exemplar, v)

    @property
    def count(self) -> int:
        """Total observations (the _count series)."""
        with self._lock:
            return sum(self._counts)

    def exemplars(self) -> dict[str, dict[str, float | str]]:
        """Per-bucket exemplar map: {le: {"trace_id", "value"}}."""
        with self._lock:
            pairs = list(zip(list(self.buckets) + ["+Inf"],
                             self._exemplars))
        return {str(le): {"trace_id": ex[0], "value": ex[1]}
                for le, ex in pairs if ex is not None}

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile by linear interpolation inside the
        hosting bucket (the standard histogram_quantile estimate); the
        +Inf bucket answers with the largest finite bound. None when
        the histogram is empty."""
        with self._lock:
            counts = list(self._counts)
        total = sum(counts)
        if total == 0:
            return None
        rank = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.buckets):  # +Inf bucket
                    return self.buckets[-1] if self.buckets else None
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                return lo + (hi - lo) * (rank - cum) / c
            cum += c
        return self.buckets[-1] if self.buckets else None

    def state(self) -> dict:
        """JSON-able snapshot for federation: per-bucket RAW counts (not
        cumulative) plus sum — mergeable element-wise when the bucket
        layout matches (it does across replicas of one binary)."""
        with self._lock:
            return {"type": "histogram", "help": self.help,
                    "buckets": list(self.buckets),
                    "counts": list(self._counts), "sum": self._sum}

    def expose(self) -> str:
        with self._lock:
            counts = list(self._counts)
            s = self._sum
        total = sum(counts)
        out = [f"# HELP {self.name} {_escape_help(self.help)}",
               f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += counts[i]
            out.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {s}")
        out.append(f"{self.name}_count {total}")
        return "\n".join(out) + "\n"


class Registry:
    def __init__(self) -> None:
        self._metrics: list = []
        self._gauges: list[tuple[str, str, Callable[[], list[tuple[str, float]]]]] = []

    def counter(self, name: str, help_: str) -> Counter:
        c = Counter(name, help_)
        self._metrics.append(c)
        return c

    def labeled_counter(self, name: str, help_: str,
                        labelnames: tuple[str, ...],
                        max_series: int = DEFAULT_MAX_SERIES
                        ) -> LabeledCounter:
        c = LabeledCounter(name, help_, labelnames, max_series=max_series)
        self._metrics.append(c)
        return c

    def get(self, name: str):
        """The registered metric object with this name, or None (bench
        reads phase histograms back out for quantile math)."""
        for m in self._metrics:
            if getattr(m, "name", None) == name:
                return m
        return None

    def register(self, metric) -> None:
        """Attach an externally owned metric (e.g. a module-level Counter
        living in a lower layer) so it exposes with its own TYPE line."""
        if metric not in self._metrics:
            self._metrics.append(metric)

    def histogram(self, name: str, help_: str,
                  buckets: tuple[float, ...]) -> Histogram:
        h = Histogram(name, help_, buckets)
        self._metrics.append(h)
        return h

    def gauge_func(self, name: str, help_: str,
                   fn: Callable[[], list[tuple[str, float]]]) -> None:
        """Gauge computed at scrape time; fn returns (labels, value) pairs
        where labels is the rendered label string ('' for none)."""
        self._gauges.append((name, help_, fn))

    def federation_state(self) -> dict[str, dict]:
        """Every counter/histogram's mergeable snapshot, keyed by metric
        name. Scrape-time gauges are deliberately EXCLUDED: a gauge is a
        statement about THIS process's current view (cache age, pending
        depth) — summing gauges across replicas of one shared fleet
        would double-count the world. Counters and histograms are event
        streams, and events federate by addition."""
        out: dict[str, dict] = {}
        for m in self._metrics:
            state = getattr(m, "state", None)
            if callable(state):
                out[m.name] = state()
        return out

    def expose(self) -> str:
        parts = [m.expose() for m in self._metrics]
        for name, help_, fn in self._gauges:
            lines = [f"# HELP {name} {_escape_help(help_)}",
                     f"# TYPE {name} gauge"]
            try:
                for labels, value in fn():
                    lines.append(f"{name}{labels} {value}")
            except Exception:
                continue  # scrape must not fail because one gauge did
            parts.append("\n".join(lines) + "\n")
        return "".join(parts)


# -- federation merge ---------------------------------------------------------
# Pure functions over the state() snapshots above: merge_states sums N
# per-process snapshots into one fleet view; expose_merged renders it in
# the same text format a single process exposes. Both live here (not in
# extender/federation.py) so the transport — mmap segment, file, test
# fixture — stays orthogonal to the arithmetic.

def merge_states(states: list[dict[str, dict]]) -> dict[str, dict]:
    """Sum mergeable metric snapshots. Type or bucket-layout conflicts
    (a mid-rollout mixed fleet) keep the FIRST seen shape and skip the
    conflicting contribution — a partial merge beats a failed scrape."""
    merged: dict[str, dict] = {}
    series_acc: dict[str, dict[tuple, float]] = {}
    for st in states:
        if not isinstance(st, dict):
            continue
        for name, s in st.items():
            if not isinstance(s, dict) or "type" not in s:
                continue
            cur = merged.get(name)
            if cur is None:
                cur = merged[name] = {k: (list(v) if isinstance(v, list)
                                          else v) for k, v in s.items()}
                if s["type"] == "labeled_counter":
                    series_acc[name] = {tuple(k): v
                                        for k, v in s.get("series", [])}
                continue
            if cur["type"] != s["type"]:
                continue
            if s["type"] == "counter":
                cur["value"] += s.get("value", 0.0)
            elif s["type"] == "labeled_counter":
                acc = series_acc[name]
                for k, v in s.get("series", []):
                    key = tuple(k)
                    acc[key] = acc.get(key, 0.0) + v
            elif s["type"] == "histogram":
                if list(cur.get("buckets", [])) != list(s.get("buckets", [])):
                    continue
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], s.get("counts", []))]
                cur["sum"] += s.get("sum", 0.0)
    for name, acc in series_acc.items():
        merged[name]["series"] = [[list(k), v]
                                  for k, v in sorted(acc.items())]
    return merged


def expose_merged(merged: dict[str, dict]) -> str:
    """Render a merged snapshot in text exposition format, sorted by
    metric name (deterministic across scrapes of the same state)."""
    parts: list[str] = []
    for name in sorted(merged):
        s = merged[name]
        help_ = _escape_help(str(s.get("help", "")))
        if s["type"] == "counter":
            parts.append(f"# HELP {name} {help_}\n# TYPE {name} counter\n"
                         f"{name} {s.get('value', 0.0)}\n")
        elif s["type"] == "labeled_counter":
            out = [f"# HELP {name} {help_}", f"# TYPE {name} counter"]
            labelnames = s.get("labelnames", [])
            for key, v in s.get("series", []):
                labels = ",".join(
                    f'{n}="{_escape_label_value(str(val))}"'
                    for n, val in zip(labelnames, key))
                out.append(f"{name}{{{labels}}} {v}")
            parts.append("\n".join(out) + "\n")
        elif s["type"] == "histogram":
            counts = s.get("counts", [])
            buckets = s.get("buckets", [])
            total = sum(counts)
            out = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
            cum = 0
            for i, b in enumerate(buckets):
                cum += counts[i] if i < len(counts) else 0
                out.append(f'{name}_bucket{{le="{b}"}} {cum}')
            out.append(f'{name}_bucket{{le="+Inf"}} {total}')
            out.append(f"{name}_sum {s.get('sum', 0.0)}")
            out.append(f"{name}_count {total}")
            parts.append("\n".join(out) + "\n")
    return "".join(parts)


# latency buckets tuned around the 50 ms p50 target (BASELINE.md)
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5)

# process-wide: one series per metric that ever clamped, so a
# cardinality bomb is a visible, alertable event (registered on the
# extender registry by register_cache_gauges)
METRIC_SERIES_CLAMPED = LabeledCounter(
    "tpushare_metric_series_clamped_total",
    "Label tuples folded into a metric's _overflow series because the "
    "metric hit its max_series cap (alert: some label value is "
    "unbounded — pod names must never be label values)",
    ("metric",))
