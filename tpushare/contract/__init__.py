"""The resource/annotation contract between extender, device plugin, and pods.

This is the tpushare analogue of the reference's pkg/utils constants and
pod/node accessors (/root/reference/pkg/utils/const.go:3-13, pod.go, node.go):
the *only* shared vocabulary between the scheduler extender (which decides
chip placement) and the device plugin (which realizes it at container start).
Everything in here operates on plain dict-shaped Kubernetes objects (the JSON
the apiserver speaks), so it has no client dependencies and is fully covered
by golden tests.
"""

from tpushare.contract.constants import (
    RESOURCE_HBM,
    RESOURCE_COUNT,
    ANN_CHIP_IDS,
    ANN_HBM_POD,
    ANN_HBM_CHIP,
    ANN_ASSIGNED,
    ANN_ASSUME_TIME,
    ANN_TOPOLOGY,
    ANN_TRACE_CONTEXT,
    ANN_NODE_CLAIMS,
    ANN_QOS_TIER,
    ANN_GANG,
    ANN_GANG_PLAN,
    ANN_GANG_RANK,
    ANN_GANG_SIZE,
    LABEL_MESH,
    LABEL_SLICE,
    LABEL_SLICE_ORIGIN,
    LABEL_TPUSHARE_NODE,
    ENV_VISIBLE_CHIPS,
    ENV_HBM_LIMIT,
    ENV_HBM_CHIP_TOTAL,
    ENV_MEM_FRACTION,
    ENV_QOS_TIER,
    ENV_GANG_ID,
    ENV_GANG_SIZE,
    ENV_GANG_BOX,
    ENV_GANG_ORIGIN,
    ENV_GANG_LOCAL_BOX,
    ENV_GANG_LOCAL_ORIGIN,
    ENV_GANG_MEMBER_ORIGIN,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    ENV_COORDINATOR_ADDRESS,
    ENV_TPU_PROCESS_BOUNDS,
    ENV_TPU_CHIPS_PER_PROCESS_BOUNDS,
    ENV_TPU_PROCESS_ADDRESSES,
    ENV_CLOUD_TPU_TASK_ID,
    GANG_COORDINATOR_PORT,
)
from tpushare.contract.pod import (
    pod_hbm_request,
    pod_chip_count_request,
    pod_topology_request,
    chip_ids_from_annotations,
    hbm_from_annotations,
    assume_time_from_annotations,
    is_assigned,
    is_tpushare_pod,
    is_complete_pod,
    is_assigned_non_terminated,
    placement_annotations,
    placement_patch,
    assigned_patch,
    strip_placement,
    gang_membership,
    gang_plan_from_annotations,
)
from tpushare.contract.node import (
    node_hbm_capacity,
    node_chip_count,
    node_mesh_topology,
    node_slice,
    parse_origin,
    is_tpushare_node,
)

__all__ = [
    "RESOURCE_HBM", "RESOURCE_COUNT",
    "ANN_CHIP_IDS", "ANN_HBM_POD", "ANN_HBM_CHIP", "ANN_ASSIGNED",
    "ANN_ASSUME_TIME", "ANN_TOPOLOGY", "ANN_NODE_CLAIMS", "ANN_QOS_TIER",
    "LABEL_MESH", "LABEL_TPUSHARE_NODE",
    "ENV_VISIBLE_CHIPS", "ENV_HBM_LIMIT", "ENV_HBM_CHIP_TOTAL",
    "ENV_MEM_FRACTION", "ENV_QOS_TIER",
    "ENV_GANG_ID", "ENV_GANG_SIZE", "ENV_GANG_BOX", "ENV_GANG_ORIGIN",
    "ENV_GANG_LOCAL_BOX", "ENV_GANG_LOCAL_ORIGIN",
    "ENV_GANG_MEMBER_ORIGIN", "ENV_NUM_PROCESSES",
    "ENV_PROCESS_ID", "ENV_COORDINATOR_ADDRESS", "ENV_TPU_PROCESS_BOUNDS",
    "ENV_TPU_CHIPS_PER_PROCESS_BOUNDS", "ENV_TPU_PROCESS_ADDRESSES",
    "ENV_CLOUD_TPU_TASK_ID", "GANG_COORDINATOR_PORT",
    "pod_hbm_request", "pod_chip_count_request", "pod_topology_request",
    "chip_ids_from_annotations", "hbm_from_annotations",
    "assume_time_from_annotations", "is_assigned",
    "is_tpushare_pod", "is_complete_pod", "is_assigned_non_terminated",
    "placement_annotations", "placement_patch", "assigned_patch",
    "strip_placement",
    "node_hbm_capacity", "node_chip_count", "node_mesh_topology",
    "node_slice", "parse_origin", "ANN_GANG", "ANN_GANG_PLAN", "ANN_GANG_RANK",
    "ANN_GANG_SIZE", "LABEL_SLICE", "LABEL_SLICE_ORIGIN",
    "gang_membership", "gang_plan_from_annotations",
    "is_tpushare_node",
]
