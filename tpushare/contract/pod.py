"""Pod accessors and annotation codec over dict-shaped k8s objects.

Mirrors the behavior of /root/reference/pkg/utils/pod.go:

- HBM request = sum of container *limits* (pod.go:154-163 sums gpu-mem).
- Chip count = max of container limits (pod.go:167-176 takes the max).
- Lifecycle predicates match IsCompletePod / AssignedNonTerminatedPod /
  IsGPUsharingPod (pod.go:21-50).
- The placement writer emits a strategic-merge patch fragment the same way
  PatchPodAnnotationSpec does (pod.go:230-241), but with JSON-typed values.
"""

from __future__ import annotations

import json
import time
from typing import Any, Mapping

from tpushare.contract.constants import (
    ANN_ASSIGNED,
    ANN_ASSUME_TIME,
    ANN_CHIP_IDS,
    ANN_GANG,
    ANN_GANG_PLAN,
    ANN_GANG_RANK,
    ANN_GANG_SIZE,
    ANN_HBM_CHIP,
    ANN_HBM_POD,
    ANN_MESH_SHAPE,
    ANN_TOPOLOGY,
    RESOURCE_COUNT,
    RESOURCE_HBM,
)

Pod = Mapping[str, Any]


def _meta(pod: Pod) -> Mapping[str, Any]:
    return pod.get("metadata") or {}


def pod_name(pod: Pod) -> str:
    return _meta(pod).get("name", "")


def pod_namespace(pod: Pod) -> str:
    return _meta(pod).get("namespace", "default")


def pod_uid(pod: Pod) -> str:
    return _meta(pod).get("uid", "")


def pod_key(pod: Pod) -> str:
    """``namespace/name`` — the workqueue/cache key format."""
    return f"{pod_namespace(pod)}/{pod_name(pod)}"


def pod_cache_key(pod: Pod) -> str:
    """Accounting identity: the UID when present, else ``namespace/name``.

    The allocation cache (chip pod-maps, in-flight bind guard, known-pods
    registry) must key on THIS, never on the raw UID: a pod object without
    a uid (hand-seeded test objects, partially-synced caches) would
    otherwise collapse every such pod onto the one ``""`` key — each new
    placement silently REPLACING the previous pod's accounting, which let
    an HA bind storm pile 36 pods onto one chip before r3's storm test
    caught it. True UID-identity checks (bind UID recheck, StatefulSet
    same-name-recreate detection) still compare raw UIDs.
    """
    return pod_uid(pod) or pod_key(pod)


def pod_node_name(pod: Pod) -> str:
    return (pod.get("spec") or {}).get("nodeName", "")


def annotations(pod: Pod) -> Mapping[str, str]:
    return _meta(pod).get("annotations") or {}


def _containers(pod: Pod) -> list[Mapping[str, Any]]:
    return (pod.get("spec") or {}).get("containers") or []


def _limit(container: Mapping[str, Any], resource: str) -> int:
    limits = ((container.get("resources") or {}).get("limits") or {})
    v = limits.get(resource, 0)
    try:
        return int(v)
    except (TypeError, ValueError):
        return 0


# -- resource requests -------------------------------------------------------

def pod_hbm_request(pod: Pod) -> int:
    """Per-chip HBM the pod asks for: sum of container limits (MiB)."""
    return sum(_limit(c, RESOURCE_HBM) for c in _containers(pod))


def pod_chip_count_request(pod: Pod) -> int:
    """Chips the pod asks for: max across containers (reference semantics)."""
    counts = [_limit(c, RESOURCE_COUNT) for c in _containers(pod)]
    return max(counts, default=0)


def pod_topology_request(pod: Pod) -> tuple[int, ...] | None:
    """Optional pinned sub-slice shape from the pod's own annotation."""
    raw = annotations(pod).get(ANN_TOPOLOGY)
    if not raw:
        return None
    from tpushare.core.topology import MeshTopology  # single "NxM" parser
    try:
        return MeshTopology.from_label(raw).shape
    except ValueError:
        return None


def pod_mesh_shape(pod: Pod,
                   chip_count: int | None = None
                   ) -> tuple[int, ...] | None:
    """Declared JAX mesh shape (soft adjacency preference), or None.

    Unlike :func:`pod_topology_request` — a best-effort hint that
    degrades to None on garbage — a malformed mesh-shape RAISES
    ValueError: the pod author declared a performance contract, and
    silently scheduling it shape-blind would hide the misconfiguration
    until the replica's collectives run slow (the gang_membership
    precedent: surface it at Filter time). Checked: every axis a
    positive integer, and when ``chip_count`` is given the axis product
    must equal it — a "2x4" mesh on a 4-chip request is a contradiction,
    not a preference.
    """
    raw = annotations(pod).get(ANN_MESH_SHAPE)
    if raw is None:
        return None
    parts = str(raw).strip().split("x")
    try:
        shape = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"pod {pod_key(pod)}: mesh-shape {raw!r} must be "
            f"integers joined by 'x' (e.g. \"2x4\")") from None
    if not shape or any(d <= 0 for d in shape):
        raise ValueError(
            f"pod {pod_key(pod)}: mesh-shape {raw!r} has a "
            f"non-positive axis")
    product = 1
    for d in shape:
        product *= d
    if chip_count is not None and product != chip_count:
        raise ValueError(
            f"pod {pod_key(pod)}: mesh-shape {raw!r} covers {product} "
            f"chips but the pod requests {chip_count}")
    return shape


# -- lifecycle predicates ----------------------------------------------------

def is_tpushare_pod(pod: Pod) -> bool:
    """Does this pod participate in HBM-shared scheduling?

    True when it requests tpu-hbm (or tpu-count) — the filter the reference
    applies via IsGPUsharingPod (pod.go:46-50) and as the informer filter
    (controller.go:78-94).
    """
    return pod_hbm_request(pod) > 0 or pod_chip_count_request(pod) > 0


def is_complete_pod(pod: Pod) -> bool:
    """Terminal pods release their chips (pod.go:21-32 semantics)."""
    status = pod.get("status") or {}
    if _meta(pod).get("deletionTimestamp"):
        return True
    return status.get("phase") in ("Succeeded", "Failed")


def is_assigned_non_terminated(pod: Pod) -> bool:
    """Scheduled to a node and not yet terminal (pod.go:35-43 semantics)."""
    return bool(pod_node_name(pod)) and not is_complete_pod(pod)


# -- annotation codec --------------------------------------------------------

def chip_ids_from_annotations(pod: Pod) -> tuple[int, ...] | None:
    """Decode the granted chip ids, or None if the pod has no placement.

    Accepts the canonical JSON list; a malformed value decodes to None (the
    sync layer treats such pods as unplaced rather than crashing the
    scheduler, unlike a panic path).
    """
    raw = annotations(pod).get(ANN_CHIP_IDS)
    if raw is None:
        return None
    try:
        ids = json.loads(raw)
        if isinstance(ids, list) and all(
                isinstance(i, int) and not isinstance(i, bool) and i >= 0
                for i in ids) and ids:
            return tuple(ids)
    except (json.JSONDecodeError, TypeError):
        pass
    return None


def hbm_from_annotations(pod: Pod) -> int:
    """Granted per-chip HBM MiB recorded at bind time (0 if absent)."""
    raw = annotations(pod).get(ANN_HBM_POD)
    try:
        return max(int(raw), 0) if raw is not None else 0
    except (TypeError, ValueError):
        return 0


def assume_time_from_annotations(pod: Pod) -> int:
    raw = annotations(pod).get(ANN_ASSUME_TIME)
    try:
        return int(raw) if raw is not None else 0
    except (TypeError, ValueError):
        return 0


def is_assigned(pod: Pod) -> bool:
    return annotations(pod).get(ANN_ASSIGNED) == "true"


def placement_annotations(
    chip_ids: tuple[int, ...] | list[int],
    hbm_mib: int,
    chip_total_mib: int,
    box: tuple[int, ...] | None = None,
    now_ns: int | None = None,
) -> dict[str, str]:
    """The annotation set the extender writes at bind time.

    Reference equivalent: PatchPodAnnotationSpec writes _IDX/_POD/_DEV/
    _ASSIGNED=false/_ASSUME_TIME (pod.go:230-241, designs.md:82-91).
    """
    ann = {
        ANN_CHIP_IDS: json.dumps(sorted(int(i) for i in chip_ids)),
        ANN_HBM_POD: str(int(hbm_mib)),
        ANN_HBM_CHIP: str(int(chip_total_mib)),
        ANN_ASSIGNED: "false",
        ANN_ASSUME_TIME: str(time.time_ns() if now_ns is None else now_ns),
    }
    if box is not None:
        ann[ANN_TOPOLOGY] = "x".join(str(d) for d in box)
    return ann


def placement_patch(ann: Mapping[str, str],
                    resource_version: str | None = None) -> dict[str, Any]:
    """Strategic-merge-patch body updating only the annotations.

    ``resource_version`` makes the patch a CAS: Kubernetes honors
    ``metadata.resourceVersion`` inside a merge-patch body as an
    optimistic-concurrency precondition (409 on mismatch). The bind path
    MUST pass the rv it placed against — two HA replicas otherwise
    blind-overwrite each other's placement annotations, and the loser's
    rollback can erase the winner's (r3 split-brain storm finding: a
    bound pod with no placement = invisible chip occupancy).
    """
    meta: dict[str, Any] = {"annotations": dict(ann)}
    if resource_version is not None:
        meta["resourceVersion"] = resource_version
    return {"metadata": meta}


def assigned_patch() -> dict[str, Any]:
    """Patch the device plugin applies when the grant becomes real
    (designs.md:101: mark ASSIGNED true)."""
    return {"metadata": {"annotations": {ANN_ASSIGNED: "true"}}}


PLACEMENT_ANNOTATION_KEYS = (
    ANN_CHIP_IDS, ANN_HBM_POD, ANN_HBM_CHIP, ANN_ASSIGNED,
    ANN_ASSUME_TIME, ANN_TOPOLOGY,
)


def strip_placement(pod: Pod) -> dict[str, Any]:
    """Deep copy of ``pod`` with the placement annotations removed — the
    body of the stale-placement reclaim's CAS PUT (the pod keeps its
    resourceVersion, so a concurrent Allocate that patched assigned=true
    makes the PUT lose with 409)."""
    out = json.loads(json.dumps(pod))
    ann = (out.get("metadata") or {}).get("annotations")
    if ann:
        for key in PLACEMENT_ANNOTATION_KEYS:
            ann.pop(key, None)
    return out


# -- multi-host gang membership (docs/designs/multihost-gang.md) -------------

def gang_membership(pod: Pod) -> tuple[str, int, int] | None:
    """(gang_id, total_chip_count, member_rank) from the gang
    annotations, or None for a non-gang pod. Malformed gang annotations
    raise ValueError — a half-labeled gang member silently scheduled as
    a single-host pod would strand its peers (all-or-nothing is the
    point), so the error must surface at Filter time."""
    ann = annotations(pod)
    gid = ann.get(ANN_GANG)
    if gid is None:
        return None
    try:
        size = int(ann[ANN_GANG_SIZE])
        rank = int(ann[ANN_GANG_RANK])
    except (KeyError, ValueError) as e:
        raise ValueError(
            f"pod {pod_key(pod)}: gang {gid!r} annotations must carry "
            f"integer {ANN_GANG_SIZE} and "
            f"{ANN_GANG_RANK}: {e}") from None
    if size <= 0 or rank < 0:
        raise ValueError(
            f"pod {pod_key(pod)}: gang {gid!r} size {size} / rank "
            f"{rank} out of range")
    return gid, size, rank


def gang_plan_from_annotations(pod: Pod) -> dict | None:
    """The stamped authoritative plan (first bound member), or None."""
    raw = annotations(pod).get(ANN_GANG_PLAN)
    if raw is None:
        return None
    try:
        plan = json.loads(raw)
    except ValueError:
        return None
    return plan if isinstance(plan, dict) else None
