"""Shared names: extended resources, annotations, labels, env vars.

Reference contract being paralleled (/root/reference/pkg/utils/const.go:3-13):

=========================  ================================================
reference (GPU)            tpushare (TPU)
=========================  ================================================
aliyun.com/gpu-mem         aliyun.com/tpu-hbm          (MiB, extended res)
aliyun.com/gpu-count       aliyun.com/tpu-count        (chips, extended res)
ALIYUN_COM_GPU_MEM_IDX     tpushare.aliyun.com/chip-ids  (JSON int list)
ALIYUN_COM_GPU_MEM_POD     tpushare.aliyun.com/hbm-pod   (per-chip MiB ask)
ALIYUN_COM_GPU_MEM_DEV     tpushare.aliyun.com/hbm-chip  (per-chip MiB total)
..._MEM_ASSIGNED           tpushare.aliyun.com/assigned  ("false" at bind,
                                                         "true" at runtime)
..._MEM_ASSUME_TIME        tpushare.aliyun.com/assume-time (ns timestamp)
(none)                     tpushare.aliyun.com/topology  (requested box, "2x2")
NVIDIA_VISIBLE_DEVICES     TPU_VISIBLE_CHIPS (env, container)
=========================  ================================================

Two deliberate departures from the reference:

- Annotations are namespaced under ``tpushare.aliyun.com/`` instead of the
  reference's bare upper-case env-style keys (const.go:8-12) — annotation
  keys with a DNS-subdomain prefix are the k8s API convention and avoid
  collisions.
- The chip-id list is JSON (``"[0, 5]"``) rather than Go's ``fmt.Sprintf
  map`` dump (pod.go:234), so the device plugin parses it without
  stringly-typed heuristics.
"""

# -- extended resources (node capacity / pod requests) -----------------------
RESOURCE_HBM = "aliyun.com/tpu-hbm"      # schedulable unit: MiB of chip HBM
RESOURCE_COUNT = "aliyun.com/tpu-count"  # number of distinct chips

# -- pod annotations (the extender -> device-plugin channel) -----------------
_PREFIX = "tpushare.aliyun.com/"
ANN_CHIP_IDS = _PREFIX + "chip-ids"         # JSON list of chip indices
ANN_HBM_POD = _PREFIX + "hbm-pod"           # per-chip HBM granted, MiB
ANN_HBM_CHIP = _PREFIX + "hbm-chip"         # per-chip HBM total, MiB
ANN_ASSIGNED = _PREFIX + "assigned"         # "false" at bind; "true" at runtime
ANN_ASSUME_TIME = _PREFIX + "assume-time"   # bind timestamp, ns since epoch
ANN_TOPOLOGY = _PREFIX + "topology"         # granted sub-slice shape, "2x2"
# Trace context (obs/trace.py): the scheduling-cycle trace id stamped by
# Bind into the placement patch, so the device plugin's Allocate joins
# the SAME trace across the process boundary — the placement-handoff
# annotation channel doubling as the Dapper context carrier.
ANN_TRACE_CONTEXT = _PREFIX + "trace-context"
# NODE annotation: JSON map of in-flight bind claims (pod accounting key ->
# {"c": [chip ids], "h": per-chip MiB, "t": claim ns}). CAS-updated on every
# bind to serialize same-node placements across HA replicas; see
# NodeInfo._claim_chips.
ANN_NODE_CLAIMS = _PREFIX + "claims"
# QoS tier (tpushare/qos/tiers.py): "guaranteed" | "burstable" (the
# default for unannotated pods — the legacy single class) |
# "best-effort" (may oversubscribe idle HBM; first evicted under
# pressure). Set by the workload author, consumed end to end.
ANN_QOS_TIER = _PREFIX + "qos-tier"

# Declared JAX mesh shape, e.g. "2x4" (docs/perf.md "Mesh-aware
# placement"): a SOFT adjacency preference, unlike ANN_TOPOLOGY's hard
# pin — placement prefers a congruent contiguous box and scores its
# adjacency, but still admits whatever fits. The axis product must
# equal the requested chip count; malformed values are rejected at
# Filter with a distinct reason (never silently shape-blind).
ANN_MESH_SHAPE = _PREFIX + "mesh-shape"

# -- multi-host gang (slice) placement (docs/designs/multihost-gang.md) ------
# A gang is a SET of pods, one per participating host, linked by id. The
# whole gang's geometry lives on every member; the coordinator assigns
# member ranks to hosts and stamps the authoritative plan on the FIRST
# bound member (ANN_GANG_PLAN), from which the remaining binds replay.
ANN_GANG = _PREFIX + "gang"                 # gang id (e.g. JobSet uid)
ANN_GANG_SIZE = _PREFIX + "gang-size"       # TOTAL chip count of the gang
ANN_GANG_RANK = _PREFIX + "gang-rank"       # member index, 0-based
ANN_GANG_PLAN = _PREFIX + "gang-plan"       # JSON plan (first member only)

# -- node labels (published by the device plugin) ----------------------------
LABEL_TPUSHARE_NODE = "tpushare"            # "true" enables the DaemonSet
LABEL_MESH = _PREFIX + "mesh"               # host ICI mesh shape, e.g. "4x4"
# Slice membership (multi-host ICI domain): which slice this host belongs
# to and where its chip box sits in the slice's GLOBAL mesh. E.g. a
# v5e-16 host at the top-right quadrant: slice="slc0", slice-origin="0x2".
LABEL_SLICE = _PREFIX + "slice"
LABEL_SLICE_ORIGIN = _PREFIX + "slice-origin"

# -- container env (injected by the device plugin at Allocate) ---------------
ENV_VISIBLE_CHIPS = "TPU_VISIBLE_CHIPS"         # e.g. "0,1,4,5"
ENV_HBM_LIMIT = "TPUSHARE_HBM_LIMIT_MIB"        # per-chip grant, MiB
ENV_HBM_CHIP_TOTAL = "TPUSHARE_HBM_CHIP_TOTAL_MIB"
# The XLA knob that makes the grant effective inside JAX workloads — the
# analogue of the TF per_process_gpu_memory_fraction guidance in the
# reference's userguide.md:67-77:
ENV_MEM_FRACTION = "XLA_PYTHON_CLIENT_MEM_FRACTION"
# The container's QoS tier, injected at Allocate so a workload (e.g. a
# best-effort trainer) can self-select checkpoint cadence / preemption
# handling without re-reading its own pod annotations:
ENV_QOS_TIER = "TPUSHARE_QOS_TIER"
# The granted chip box's dims ("2x2" label form), injected at Allocate
# when the granted chips form a contiguous axis-aligned sub-box of the
# host mesh (absent for scatter grants). TPU_VISIBLE_CHIPS lists chips
# in ascending id order, which is row-major over this box — together
# they let a replica lay its JAX Mesh axes along physical ICI adjacency
# (workloads/serve.py compose_mesh_devices) instead of trusting
# enumeration order to be geometry:
ENV_PLACEMENT_BOX = "TPUSHARE_PLACEMENT_BOX"

# -- gang runtime env (injected at Allocate for gang members, r5) ------------
# The scheduling half of a gang ends at the stamped plan annotations; the
# runtime half starts here: Allocate turns the plan geometry into the env
# a multi-host JAX/libtpu process needs, so a JobSet can form the gang's
# mesh without hand-wiring env (the reference's Allocate is likewise
# where placement becomes env, designs.md:95-101).
ENV_GANG_ID = "TPUSHARE_GANG_ID"
ENV_GANG_SIZE = "TPUSHARE_GANG_SIZE"            # TOTAL chips in the gang
ENV_GANG_BOX = "TPUSHARE_GANG_BOX"              # global box, "2x4"
ENV_GANG_ORIGIN = "TPUSHARE_GANG_ORIGIN"        # global origin in slice
ENV_GANG_LOCAL_BOX = "TPUSHARE_GANG_LOCAL_BOX"  # this host's share box
ENV_GANG_LOCAL_ORIGIN = "TPUSHARE_GANG_LOCAL_ORIGIN"
# where this member's chip box sits inside the GANG box (slice-origin
# label + host-local origin - gang origin):
ENV_GANG_MEMBER_ORIGIN = "TPUSHARE_GANG_MEMBER_ORIGIN"
# The standard JAX multi-controller contract (jax.distributed.initialize
# reads these names from the environment):
ENV_NUM_PROCESSES = "NUM_PROCESSES"             # = gang host count
ENV_PROCESS_ID = "PROCESS_ID"                   # = gang rank
ENV_COORDINATOR_ADDRESS = "COORDINATOR_ADDRESS"
# libtpu's own sub-slice contract (TPU_PROCESS_BOUNDS-class): how the
# member processes tile the gang's global box, and each process's chip
# box — comma-separated, padded to 3 axes the way libtpu spells them.
ENV_TPU_PROCESS_BOUNDS = "TPU_PROCESS_BOUNDS"
ENV_TPU_CHIPS_PER_PROCESS_BOUNDS = "TPU_CHIPS_PER_PROCESS_BOUNDS"
ENV_TPU_PROCESS_ADDRESSES = "TPU_PROCESS_ADDRESSES"
ENV_CLOUD_TPU_TASK_ID = "CLOUD_TPU_TASK_ID"
# jax.distributed's default coordinator port; samples/6-gang.yaml binds
# its headless-Service coordinator on the same number
GANG_COORDINATOR_PORT = 8476

# -- unhealthy-chip configmap (operator-maintained, kube-system) -------------
# reference: configmap "unhealthy-gpu-<node>" key "gpus" = CSV device ids
# (/root/reference/pkg/cache/nodeinfo.go:406-431, configmap.go:20-34)
UNHEALTHY_CM_NAMESPACE = "kube-system"
UNHEALTHY_CM_PREFIX = "unhealthy-tpu-"      # configmap name: prefix + node
UNHEALTHY_CM_KEY = "chips"                  # CSV chip indices
