"""Node accessors: capacity, chip count, mesh topology.

Reference equivalents: GetTotalGPUMemory / GetGPUCountInNode read
``node.Status.Capacity`` (/root/reference/pkg/utils/node.go:11-30);
IsGPUSharingNode is "capacity > 0" (node.go:6-8). The mesh label is new —
the reference's device array is geometry-free.
"""

from __future__ import annotations

from typing import Any, Mapping

from tpushare.contract.constants import (
    LABEL_MESH,
    LABEL_SLICE,
    LABEL_SLICE_ORIGIN,
    RESOURCE_COUNT,
    RESOURCE_HBM,
)
from tpushare.core.topology import MeshTopology

Node = Mapping[str, Any]


def node_name(node: Node) -> str:
    return (node.get("metadata") or {}).get("name", "")


def _capacity(node: Node) -> Mapping[str, Any]:
    status = node.get("status") or {}
    # allocatable preferred; capacity as fallback (kubelet reports both)
    return status.get("allocatable") or status.get("capacity") or {}


def node_hbm_capacity(node: Node) -> int:
    """Total schedulable HBM MiB on the node (all chips)."""
    try:
        return int(_capacity(node).get(RESOURCE_HBM, 0))
    except (TypeError, ValueError):
        return 0


def node_chip_count(node: Node) -> int:
    try:
        return int(_capacity(node).get(RESOURCE_COUNT, 0))
    except (TypeError, ValueError):
        return 0


def is_tpushare_node(node: Node) -> bool:
    return node_hbm_capacity(node) > 0


def node_mesh_topology(node: Node) -> MeshTopology | None:
    """Host ICI mesh from the device plugin's label, if published.

    Returns None for unlabeled nodes; callers fall back to
    MeshTopology.for_chip_count (and a malformed label behaves like no
    label rather than poisoning the scheduler).
    """
    labels = (node.get("metadata") or {}).get("labels") or {}
    raw = labels.get(LABEL_MESH)
    if not raw:
        return None
    try:
        topo = MeshTopology.from_label(raw)
    except ValueError:
        return None
    count = node_chip_count(node)
    if count and topo.num_chips != count:
        return None  # stale label; geometry no longer trustworthy
    return topo


def parse_origin(raw: str) -> tuple[int, ...] | None:
    """THE slice-origin grammar: non-negative "RxC" coordinates (same
    encoding as the mesh label). One parser shared by the device plugin
    (startup validation) and the scheduler (node_slice) so the two
    sides cannot drift into a publish-what-the-other-rejects split."""
    try:
        origin = tuple(int(p) for p in raw.lower().split("x"))
    except (AttributeError, ValueError):
        return None
    return origin if all(o >= 0 for o in origin) else None


def node_slice(node: Node) -> tuple[str, tuple[int, ...]] | None:
    """(slice_id, host_box_origin) from the slice labels, or None for a
    single-host node (docs/designs/multihost-gang.md). A malformed
    origin behaves like no slice membership (the node still schedules
    single-host work; gang placement just cannot use it)."""
    labels = (node.get("metadata") or {}).get("labels") or {}
    sid = labels.get(LABEL_SLICE)
    raw = labels.get(LABEL_SLICE_ORIGIN)
    if not sid or raw is None:
        return None
    origin = parse_origin(raw)
    if origin is None:
        return None
    return sid, origin
