"""Cross-process metrics federation over a shared-memory segment.

``--procs N`` serving (extender/__main__.py, SO_REUSEPORT) runs N
independent replicas of the whole server; the kernel load-balances
accepted connections across them. Each replica keeps its own metrics
registry, so a scrape of ``/metrics`` sees only the ~1/N of traffic that
landed on the answering process — fleet counters (binds, native serves,
black-box events) appear to undercount by the replica factor.

This module federates them without any network hop: every replica
claims one slot in a small mmap'd segment (keyed by uid + port, so
replicas of one server group share it and different servers don't) and
periodically publishes its registry's mergeable snapshot
(metrics.Registry.federation_state) into its slot under a seqlock.
``GET /metrics/federated`` on ANY replica then merges the live local
registry with every peer slot and exposes the sum in the same text
format — one scrape, the whole fleet.

Crash tolerance: a slot is claimed once (pid + a random nonce) and
written only by its owner. When a replica dies, its slot simply stops
updating — the last published snapshot stays readable and keeps being
merged (counters are monotone; freezing loses the tail, never the
history). A FUTURE replica may reclaim a dead slot only when no empty
slot remains, so the frozen tail survives as long as the segment has
room. The seqlock (odd = write in progress) means a reader never
observes a torn payload: it retries a few times, then skips the slot.

Only counters and histograms federate; scrape-time gauges are
per-process statements and stay local (see Registry.federation_state).

Knobs: ``TPUSHARE_FEDERATION=0`` disables the whole layer;
``TPUSHARE_FEDERATION_PERIOD_S`` (default 1.0) is the publish cadence;
``TPUSHARE_FEDERATION_PATH`` overrides the segment path.

Lock discipline (tests/test_lock_order_lint.py): ``self._lock`` guards
the mmap handle and publish/read plumbing — memory and local-file work
only, NEVER held across an apiserver call, a ring drain, or a journal
flush.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import tempfile
import threading
import time
from typing import Any

MAGIC = b"TPUSHFED"
VERSION = 1

# header: magic, version, nslots, slot_size, zero pad -> 32 bytes
_HEADER = struct.Struct("<8sIII12x")
# slot header: pid, nonce, seqlock seq, payload len -> 32 bytes
_SLOT = struct.Struct("<qqqq")

DEFAULT_NSLOTS = 32
DEFAULT_SLOT_SIZE = 256 * 1024  # payload is the whole registry as JSON


def enabled() -> bool:
    return os.environ.get("TPUSHARE_FEDERATION", "1") != "0"


def default_path(port: int) -> str:
    override = os.environ.get("TPUSHARE_FEDERATION_PATH")
    if override:
        return override
    return os.path.join(tempfile.gettempdir(),
                        f"tpushare-fed-{os.getuid()}-{port}.seg")


def _flock(fh, exclusive: bool):
    try:
        import fcntl
        fcntl.flock(fh.fileno(),
                    fcntl.LOCK_EX if exclusive else fcntl.LOCK_SH)
        return True
    except (ImportError, OSError):
        return False  # best effort: claim races are pid-arbitrated anyway


def _funlock(fh) -> None:
    try:
        import fcntl
        fcntl.flock(fh.fileno(), fcntl.LOCK_UN)
    except (ImportError, OSError):
        pass


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False


class FederationSegment:
    """One replica's handle on the shared segment: claims a slot at
    start(), publishes the registry snapshot periodically, merges every
    slot on demand."""

    def __init__(self, registry, port: int, *, path: str | None = None,
                 nslots: int = DEFAULT_NSLOTS,
                 slot_size: int = DEFAULT_SLOT_SIZE,
                 period_s: float | None = None) -> None:
        if period_s is None:
            period_s = float(os.environ.get(
                "TPUSHARE_FEDERATION_PERIOD_S", "1.0"))
        self.registry = registry
        self.path = path or default_path(port)
        self.nslots = nslots
        self.slot_size = slot_size
        self.period_s = period_s
        self.pid = os.getpid()
        # nonce disambiguates pid reuse across slot generations; derived
        # from urandom, not time (replay-safe, fork-safe)
        self.nonce = int.from_bytes(os.urandom(7), "little") or 1
        self.slot: int | None = None
        # mmap handle + publish/read plumbing; memory + local file only
        self._lock = threading.Lock()
        self._fh = None
        self._mm: mmap.mmap | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._publishes = 0
        self._publish_errors = 0

    # -- segment plumbing ------------------------------------------------

    def _size(self) -> int:
        return _HEADER.size + self.nslots * self.slot_size

    def _slot_off(self, i: int) -> int:
        return _HEADER.size + i * self.slot_size

    def _open(self) -> bool:
        """Map the segment, creating/initializing it if needed (under an
        exclusive flock so two racing replicas don't both format)."""
        size = self._size()
        fh = open(self.path, "a+b")
        locked = _flock(fh, exclusive=True)
        try:
            fh.seek(0, os.SEEK_END)
            fresh = fh.tell() < size
            if fresh:
                fh.truncate(size)
            mm = mmap.mmap(fh.fileno(), size)
            magic, ver, nslots, slot_size = _HEADER.unpack_from(mm, 0)
            if magic != MAGIC or ver != VERSION or \
                    nslots != self.nslots or slot_size != self.slot_size:
                if not fresh and magic == MAGIC:
                    # an existing segment with a different geometry wins:
                    # adopt it rather than clobber peers' slots
                    if ver == VERSION and nslots > 0 and slot_size > 0:
                        self.nslots, self.slot_size = nslots, slot_size
                        if len(mm) < self._size():
                            mm.close()
                            fh.truncate(self._size())
                            mm = mmap.mmap(fh.fileno(), self._size())
                    else:
                        mm.close()
                        fh.close()
                        return False
                else:
                    mm[:] = b"\x00" * len(mm)
                    _HEADER.pack_into(mm, 0, MAGIC, VERSION,
                                      self.nslots, self.slot_size)
            self._fh, self._mm = fh, mm
            return True
        finally:
            if locked:
                _funlock(fh)
            if self._fh is None:
                fh.close()

    def _claim(self) -> int | None:
        """Pick a slot: empty first, then a dead owner's (reclaiming a
        frozen slot only under segment pressure — see module doc)."""
        mm = self._mm
        locked = _flock(self._fh, exclusive=True)
        try:
            empty, dead = None, None
            for i in range(self.nslots):
                pid, _, _, _ = _SLOT.unpack_from(mm, self._slot_off(i))
                if pid == 0 and empty is None:
                    empty = i
                elif pid != 0 and dead is None and not _pid_alive(pid):
                    dead = i
            slot = empty if empty is not None else dead
            if slot is None:
                return None
            off = self._slot_off(slot)
            _SLOT.pack_into(mm, off, self.pid, self.nonce, 0, 0)
            return slot
        finally:
            if locked:
                _funlock(self._fh)

    # -- publishing ------------------------------------------------------

    def publish_once(self) -> bool:
        """Seqlock-write the current registry snapshot into our slot."""
        with self._lock:
            mm, slot = self._mm, self.slot
            if mm is None or slot is None:
                return False
            try:
                payload = json.dumps(
                    {"pid": self.pid, "nonce": self.nonce,
                     "t": round(time.time(), 3),
                     "state": self.registry.federation_state()},
                    separators=(",", ":")).encode()
            except Exception:  # noqa: BLE001 — scrape-side must survive
                self._publish_errors += 1
                return False
            if len(payload) > self.slot_size - _SLOT.size:
                self._publish_errors += 1
                return False
            off = self._slot_off(slot)
            pid, nonce, seq, _ = _SLOT.unpack_from(mm, off)
            if pid != self.pid or nonce != self.nonce:
                return False  # slot was reclaimed out from under us
            _SLOT.pack_into(mm, off, self.pid, self.nonce, seq + 1, 0)
            mm[off + _SLOT.size:off + _SLOT.size + len(payload)] = payload
            _SLOT.pack_into(mm, off, self.pid, self.nonce, seq + 2,
                            len(payload))
            self._publishes += 1
            return True

    # -- reading + merging -----------------------------------------------

    def read_slots(self) -> list[dict[str, Any]]:
        """Every claimed slot's last published snapshot (self included),
        torn or unparseable payloads skipped."""
        out: list[dict[str, Any]] = []
        with self._lock:
            mm = self._mm
            if mm is None:
                return out
            for i in range(self.nslots):
                off = self._slot_off(i)
                for _ in range(8):  # seqlock retry budget
                    pid, nonce, seq, length = _SLOT.unpack_from(mm, off)
                    if pid == 0 or length <= 0:
                        break
                    if seq % 2:  # write in progress
                        time.sleep(0.0005)
                        continue
                    raw = bytes(mm[off + _SLOT.size:
                                   off + _SLOT.size + length])
                    pid2, nonce2, seq2, _ = _SLOT.unpack_from(mm, off)
                    if (pid2, nonce2, seq2) != (pid, nonce, seq):
                        continue  # torn read: retry
                    try:
                        payload = json.loads(raw)
                    except ValueError:
                        break
                    if isinstance(payload, dict):
                        payload["slot"] = i
                        payload["alive"] = _pid_alive(pid)
                        out.append(payload)
                    break
        return out

    def merged_state(self) -> tuple[dict[str, dict], dict[str, Any]]:
        """(merged metric state, meta) across the live LOCAL registry
        and every OTHER slot — local truth is always current; peers are
        at most one publish period stale."""
        from tpushare.metrics import merge_states
        slots = self.read_slots()
        states = [self.registry.federation_state()]
        replicas = [{"pid": self.pid, "slot": self.slot,
                     "alive": True, "self": True}]
        for s in slots:
            if s.get("pid") == self.pid and s.get("nonce") == self.nonce:
                continue  # our slot: the live registry already covers it
            states.append(s.get("state") or {})
            replicas.append({"pid": s.get("pid"), "slot": s.get("slot"),
                             "alive": bool(s.get("alive")),
                             "t": s.get("t"), "self": False})
        return merge_states(states), {
            "path": self.path,
            "replicas": replicas,
            "replica_count": len(replicas),
        }

    def merged_text(self) -> str:
        """GET /metrics/federated: the fleet-wide sum, text format."""
        from tpushare.metrics import expose_merged
        merged, _ = self.merged_state()
        return expose_merged(merged)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> bool:
        """Map + claim + start the publish thread. False (and inert)
        when the segment can't be set up — federation is an overlay; the
        server must come up without it."""
        with self._lock:
            if self._mm is None:
                try:
                    if not self._open():
                        return False
                    self.slot = self._claim()
                except OSError:
                    self._mm = None
                    return False
                if self.slot is None:
                    return False
        self.publish_once()
        if self._thread is None:
            self._stop.clear()
            t = threading.Thread(target=self._run, daemon=True,
                                 name="tpushare-federation")
            self._thread = t
            t.start()
        return True

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.publish_once()
            except Exception:  # noqa: BLE001 — publisher must not die
                self._publish_errors += 1

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)
        # final publish so the frozen slot carries the complete history
        try:
            self.publish_once()
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            mm, self._mm = self._mm, None
            fh, self._fh = self._fh, None
            if mm is not None:
                mm.close()
            if fh is not None:
                fh.close()

    def stats(self) -> dict[str, Any]:
        with self._lock:
            mapped = self._mm is not None
        return {
            "enabled": mapped,
            "path": self.path,
            "slot": self.slot,
            "pid": self.pid,
            "nslots": self.nslots,
            "slot_size": self.slot_size,
            "period_s": self.period_s,
            "publishes": self._publishes,
            "publish_errors": self._publish_errors,
        }
