"""Native wire-plane table: the ABI v6 zero-Python steady state.

PR 14's wirecache made a digest-hit Filter serve skip JSON entirely, but
every request still round-trips the Python selector loop under the GIL:
parse the head, build a header dict, hop to a pool thread, look the
response up, hop back. This module closes that last gap. The selector
loop hands a connection's raw bytes to one GIL-released C call
(placement.cpp tpushare_wire_probe) that frames the request, digests the
NodeNames span and the body remainder, and copies back a pre-encoded
response — the steady-state serve path is one native call.

The table is a CACHE OF THE PYTHON PATH, never an independent encoder:

- :meth:`install` is called by ``wirecache._finish`` after a fresh
  encode, with the exact response body the Python path just served and
  the mutation stamp it was computed under. The native entry's bytes are
  therefore byte-identical to a Python serve by construction.
- a probe carries the caller's CURRENT mutation stamp (read immediately
  before the call, ``stamp_fn``); the C side serves only on stamp
  equality. Any fleet mutation between sync and probe moves the stamp,
  so the entry misses and the request falls back to the Python path —
  never a stale serve (tests/test_nativewire.py proves the seam).
- matching is by exact request bytes (span digest + remainder digest +
  verb), deliberately NARROWER than the Python response cache's
  signature-level match: the native side answers only what it has
  literally seen before; anything novel is Python's problem.

``TPUSHARE_NO_NATIVE_WIRE=1`` disables the whole path (engine-side knob,
see engine._wire_lib); a stale pre-v6 ``.so`` degrades the same way.
Under ``TPUSHARE_WIRE_VERIFY=1`` a native hit is NOT served directly:
the expected bytes are pinned on the connection, the Python path
recomputes, and a divergence counts into ``tpushare_wire_stale_serves``
while the recomputed truth is what goes out (httpserver._work).

Lock discipline (tests/test_lock_order_lint.py): ``self._lock`` (rank 7,
one above the wirecache's rank-6 lock — installs arrive from
``_finish`` AFTER it released the wirecache lock) guards table lifecycle
and install bookkeeping for a few instructions at a time. It is NEVER
held across a native probe: the probe runs lock-free on the selector
loop thread against the C table's own internal mutex, so a worker-side
install can never stall the serve path.
"""

from __future__ import annotations

import ctypes
import os
import threading
import time

from tpushare.metrics import Histogram, LabeledCounter

WIRE_NATIVE_SERVES = LabeledCounter(
    "tpushare_wire_native_serves_total",
    "Digest-path serve outcomes at the native probe: native (served "
    "GIL-released), fallback (eligible but cold/stamp-moved, Python "
    "served), bypass (not a fast-path request)",
    ("outcome",))
WIRE_NATIVE_PROBE_SECONDS = Histogram(
    "tpushare_wire_native_probe_seconds",
    "Native serve time of one tpushare_wire_probe call (frame + digest "
    "+ table lookup + response copy). With the black-box pump running "
    "the samples are the ring's GIL-released tick deltas (actual native "
    "time); otherwise the Python-side perf_counter envelope",
    buckets=(2e-6, 5e-6, 1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 1e-3,
             5e-3, 2.5e-2))

# True while a blackbox RingPump is draining the ABI v8 event ring: the
# pump observes the ring's per-probe tick deltas into the histogram
# above, so the serve path must NOT also observe its (wider,
# GIL-reacquisition-polluted) perf_counter envelope — one serve, one
# sample. Flipped by RingPump.start/stop; plain bool read, GIL-atomic.
RING_LATENCY_ACTIVE = False

# probe return protocol (placement.cpp tpushare_wire_probe)
PROBE_HIT = 1
PROBE_MISS = 0
PROBE_ERROR = -1
PROBE_INCOMPLETE = -2
PROBE_GROW = -3
PROBE_BYPASS = -4

_VERBS = {"filter": 0, "prioritize": 1}

_OUT_INITIAL = 256 * 1024  # grows on PROBE_GROW; 50k-name bodies ~2 MiB


class NativeWireTable:
    """One resident digest→response table per server process.

    ``stamp_fn`` is ``SchedulerCache.mutation_stamp`` — the same clock
    the wirecache response cache keys currency on.
    """

    def __init__(self, stamp_fn, *, wirecache_enabled: bool = True,
                 verify: bool | None = None) -> None:
        from tpushare.core.native import engine
        self._stamp_fn = stamp_fn
        self._lib = engine._wire_lib() if wirecache_enabled else None
        self.enabled = self._lib is not None
        if verify is None:
            verify = os.environ.get("TPUSHARE_WIRE_VERIFY", "") == "1"
        self.verify = verify
        # lifecycle + install bookkeeping; NEVER held across a probe
        self._lock = threading.Lock()
        self._table = (self._lib.tpushare_wire_table_create()
                       if self.enabled else None)
        if self._table is None:
            self.enabled = False
        # probe scratch — selector-loop-thread only, grown on demand
        self._out = ctypes.create_string_buffer(_OUT_INITIAL)
        self._out_len = ctypes.c_int64(0)
        self._consumed = ctypes.c_int64(0)

    # -- worker side: delta-sync from the Python wirecache --------------------

    def install(self, span_digest: bytes, rem_digest: bytes, verb: str,
                stamp: int, body: bytes) -> None:
        """Sync one freshly Python-encoded response into the table.

        ``body`` is the exact payload ``wirecache._finish`` just stored;
        the resident entry is the full HTTP response those bytes produce
        on the keep-alive path, so a hit is a pure memcpy."""
        vid = _VERBS.get(verb)
        if vid is None or not self.enabled:
            return
        from tpushare.extender.httpserver import _response
        resp = _response(200, body, "application/json")
        with self._lock:
            table = self._table
            if table is None:
                return
            self._lib.tpushare_wire_install(
                table, span_digest, rem_digest, vid, stamp, resp,
                len(resp))

    # -- loop side: the probe itself ------------------------------------------

    def probe_request(self, inbuf: bytearray):
        """One native probe over a connection's raw input buffer.

        Returns ``(rc, response_bytes | None, consumed)``. Counts the
        outcome (native/fallback/bypass) and times the call. Selector
        loop thread ONLY (owns the scratch buffer)."""
        table = self._table
        if table is None or not inbuf:
            return PROBE_BYPASS, None, 0
        stamp = self._stamp_fn()
        # zero-copy view of the bytearray; released when req goes away
        req = (ctypes.c_char * len(inbuf)).from_buffer(inbuf)
        t0 = time.perf_counter()
        rc = self._lib.tpushare_wire_probe(
            table, req, len(inbuf), stamp, self._out, len(self._out),
            ctypes.byref(self._out_len), ctypes.byref(self._consumed))
        if rc == PROBE_GROW:
            self._out = ctypes.create_string_buffer(
                int(self._out_len.value) + 4096)
            rc = self._lib.tpushare_wire_probe(
                table, req, len(inbuf), stamp, self._out, len(self._out),
                ctypes.byref(self._out_len), ctypes.byref(self._consumed))
        del req
        if not RING_LATENCY_ACTIVE:
            WIRE_NATIVE_PROBE_SECONDS.observe(time.perf_counter() - t0)
        if rc == PROBE_HIT:
            WIRE_NATIVE_SERVES.inc("native")
            return (PROBE_HIT, self._out.raw[:self._out_len.value],
                    int(self._consumed.value))
        if rc == PROBE_MISS:
            WIRE_NATIVE_SERVES.inc("fallback")
        elif rc in (PROBE_BYPASS, PROBE_ERROR):
            WIRE_NATIVE_SERVES.inc("bypass")
        return rc, None, 0

    def check_verify(self, expected: bytes, actual: bytes) -> None:
        """TPUSHARE_WIRE_VERIFY tripwire: the native hit's bytes vs the
        Python path's recompute for the same request. A divergence is
        the bug class this knob exists to catch — count it loudly; the
        recomputed truth is what was served."""
        if expected != actual:
            from tpushare.extender.wirecache import WIRE_STALE_SERVES
            WIRE_STALE_SERVES.inc()

    # -- lifecycle + observability --------------------------------------------

    def clear(self) -> None:
        with self._lock:
            if self._table is not None:
                self._lib.tpushare_wire_clear(self._table)

    def close(self) -> None:
        """Destroy the C table. Only call after the serving loop has
        stopped — probes read the handle lock-free."""
        with self._lock:
            table, self._table = self._table, None
            self.enabled = False
            if table is not None:
                self._lib.tpushare_wire_table_destroy(table)

    def stats(self) -> dict:
        """Occupancy + outcome counters for /inspect/wire and bench."""
        out = {"enabled": self.enabled, "verify": self.verify}
        raw = (ctypes.c_int64 * 8)()
        with self._lock:
            if self._table is None:
                return out
            self._lib.tpushare_wire_stats(self._table, raw)
        probes = int(raw[2])
        out.update({
            "entries": int(raw[0]),
            "capacity": int(raw[1]),
            "probes": probes,
            "hits": int(raw[3]),
            "misses": int(raw[4]),
            "stamp_misses": int(raw[5]),
            "installs": int(raw[6]),
            "evictions": int(raw[7]),
            "hit_rate": round(int(raw[3]) / probes, 4) if probes else None,
        })
        return out
