"""Compatibility shim: the metrics primitives moved to
:mod:`tpushare.metrics` (they are layer-neutral — the cache layer's
CLAIM_CAS_RETRIES counter needs them without importing the HTTP
package, whose __init__ pulls handlers -> cache and cycles)."""

from tpushare.metrics import (  # noqa: F401
    LATENCY_BUCKETS,
    Counter,
    Histogram,
    Registry,
)

__all__ = ["Counter", "Histogram", "Registry", "LATENCY_BUCKETS"]
