"""Scheduler-extender HTTP service.

The webhook the unmodified kube-scheduler calls during Filter and Bind
(reference: pkg/routes + pkg/scheduler, wire types vendored at
vendor/k8s.io/kubernetes/pkg/scheduler/api/types.go:258-302). URL scheme:

    POST /tpushare-scheduler/filter     ExtenderArgs -> ExtenderFilterResult
    POST /tpushare-scheduler/bind       ExtenderBindingArgs -> ExtenderBindingResult
    GET  /tpushare-scheduler/inspect[/<node>]   allocation tree JSON
    GET  /version
    GET  /healthz
    GET  /metrics                       Prometheus text format
    GET  /debug/threads | /debug/profile?seconds=N   (pprof analogue)

Registered via config/scheduler-policy-config.json (legacy Policy API) or
config/kube-scheduler-config.yaml (KubeSchedulerConfiguration extenders
stanza) with nodeCacheCapable:true and managedResources [aliyun.com/tpu-hbm,
aliyun.com/tpu-count], so the scheduler sends node *names* and delegates the
bind verb (reference scheduler-policy-config.json:5-18).
"""

from tpushare.extender.handlers import BindHandler, FilterHandler, InspectHandler
from tpushare.extender.server import ExtenderServer

__all__ = ["BindHandler", "FilterHandler", "InspectHandler", "ExtenderServer"]
