"""HTTP server wiring for the extender (reference pkg/routes/routes.go).

Routing is front-end-agnostic: :meth:`ExtenderServer.handle_get` /
:meth:`ExtenderServer.handle_post` map a path + raw body to
``(status, payload bytes, content type)``, and two interchangeable front
ends drive them — the selector/event-loop server (extender/httpserver.py,
the default: one loop thread owns every socket, a bounded worker pool
runs the handlers) and the legacy stdlib ThreadingHTTPServer
(``TPUSHARE_SERVER=threaded``, thread per connection). Bind failures
return HTTP 500 with the ExtenderBindingResult body (routes.go:139-143
does the same), which makes the default scheduler retry after its
timeout.

Owner forwarding (ha/forward.py): when active-active sharding is wired,
a Filter/Prioritize/Bind landing on a non-owning replica hops once to
the shard owner and the owner's verdict is relayed verbatim; the
loop-guard header degrades mid-rebalance disagreement to the claim-CAS
fallback instead of ping-ponging.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import tpushare
from tpushare.extender.handlers import (
    BindHandler,
    FilterHandler,
    InspectHandler,
    PreemptHandler,
    PrioritizeHandler,
)
from tpushare.extender.metrics import Registry
from tpushare.extender.wirecache import WireEncoded
from tpushare.ha.forward import FORWARD_HEADER, ForwardRouter

log = logging.getLogger("tpushare.extender.http")

PREFIX = "/tpushare-scheduler"

_POST_ROUTES = {
    f"{PREFIX}/filter": "filter",
    f"{PREFIX}/prioritize": "prioritize",
    f"{PREFIX}/preempt": "preempt",
    f"{PREFIX}/bind": "bind",
}


def _enc(status: int, body: Any,
         content_type: str = "application/json") -> tuple[int, bytes, str]:
    data = (json.dumps(body).encode()
            if content_type == "application/json" else body.encode())
    return status, data, content_type


class ExtenderServer:
    # per-request deadline default: safely under the kube-scheduler's
    # extender httpTimeout (30 s in config/kube-scheduler-config.yaml) —
    # retries stop, and the webhook answers, BEFORE the caller hangs up
    DEFAULT_REQUEST_DEADLINE_S = 25.0

    def __init__(self, cache, cluster, registry: Registry | None = None,
                 host: str = "0.0.0.0", port: int = 39999,
                 allow_debug_seed: bool = False,
                 elector=None, informer=None, breaker=None,
                 request_deadline_s: float | None = None,
                 sharding=None) -> None:
        self.registry = registry or Registry()
        self._cache = cache
        self._informer = informer
        # apiserver circuit breaker (k8s/breaker.py): Bind fail-fasts
        # while it is open, Filter/Prioritize count degraded serves, and
        # /readyz reports its state. None = no degraded-mode wiring.
        self._breaker = breaker
        if request_deadline_s is None:
            request_deadline_s = float(os.environ.get(
                "TPUSHARE_REQUEST_DEADLINE_S",
                self.DEFAULT_REQUEST_DEADLINE_S))
        self.request_deadline_s = request_deadline_s
        staleness_fn = informer.staleness_s if informer is not None else None
        # observability (obs/, docs/observability.md): the process-wide
        # cycle tracer + its flight recorder behind /debug/traces, and
        # the per-decision audit store behind /inspect/explain/<pod>
        from tpushare.obs import ExplainStore
        from tpushare.obs.trace import TRACER
        self.tracer = TRACER
        self.explain = ExplainStore()
        # fleet-health layer (obs/fleetwatch.py): fragmentation/
        # utilization gauges + the continuous drift auditor behind
        # GET /inspect/fleet; its scorecard consumes the decision-audit
        # stream via the ExplainStore observer hook. The background
        # thread starts with the server (TPUSHARE_FLEETWATCH=0 opts out).
        from tpushare.obs.fleetwatch import FleetWatch
        self.fleetwatch = FleetWatch(cache, cluster=cluster,
                                     informer=informer)
        # fleet black box (obs/blackbox.py, ABI v8): the ring pump
        # drains native fast-path events — GIL-released wire serves,
        # cycle solves, gang solves — back into the phase histograms,
        # the flight recorder and the explain store, so the zero-Python
        # steady state stops being invisible. Inert on a pre-v8 .so or
        # TPUSHARE_BLACKBOX=0.
        from tpushare.obs.blackbox import RingPump
        self.blackbox = RingPump(explain=self.explain,
                                 recorder=self.tracer.recorder)
        # incident journal (obs/journal.py): every admitted/rejected/
        # bound pod as a replayable decision record, fed off the explain
        # decision stream. Enabled by TPUSHARE_JOURNAL_DIR; replay with
        # `python -m tpushare.sim --replay <dir>`.
        from tpushare.obs.explain import FanoutObserver
        from tpushare.obs.journal import DecisionJournal
        self.journal = None
        jdir = os.environ.get("TPUSHARE_JOURNAL_DIR")
        if jdir:
            try:
                self.journal = DecisionJournal(
                    jdir, fleet_info=self._journal_fleet_info())
            except OSError as e:
                log.error("decision journal disabled: %s", e)
        self.explain.observer = FanoutObserver(self.fleetwatch.scorecard,
                                               self.journal)
        # cross-process metrics federation (extender/federation.py):
        # created at start() once the port is known — SO_REUSEPORT
        # replicas of one port share a segment
        self.federation = None
        self.fleetwatch.attach(self.registry)
        # multi-host gang placement (docs/designs/multihost-gang.md):
        # engages only for pods carrying the gang annotations, on nodes
        # labeled into slices — zero cost otherwise. Constructed before
        # the defrag controller, whose whole-slice moves re-solve LIVE
        # gangs through the coordinator's one-shot solve.
        from tpushare.cache.gang import GangCoordinator
        self.gang = GangCoordinator(cache)
        # fragmentation-pressure forecast (defrag/forecast.py): folds
        # fleetwatch's cached stranded-gap trend into the Prioritize
        # binpack-vs-scatter blend so admission stops CREATING the
        # fragmentation defrag pays migrations to undo.
        # TPUSHARE_FRAG_WEIGHT=0 disables the blend byte-identically.
        from tpushare.defrag.forecast import FragForecast
        self.frag_forecast = FragForecast(fleetwatch=self.fleetwatch)
        self.frag_forecast.attach(self.registry)
        # live defragmentation (defrag/): the repack rebalancer consumes
        # the same capacity-index stranded-gap picture the fleetwatch
        # gauges publish and acts on it under a migration budget, behind
        # GET /inspect/defrag. Background thread starts with the server
        # (TPUSHARE_DEFRAG=0 opts out); decisions land in the explain
        # audit and the cycle tracer like any scheduling verdict. Moves
        # run as bounded-pause checkpoint sessions via the workload-side
        # migrator seam (workloads/migrate.py).
        from tpushare.defrag import DefragController
        from tpushare.workloads.migrate import default_migrator
        self.defrag = DefragController(cache, cluster=cluster,
                                       explain=self.explain,
                                       gang=self.gang,
                                       migrator=default_migrator())
        self.defrag.attach(self.registry)
        # QoS tiers (tpushare/qos/, ISSUE 17): the pressure monitor
        # reclaims best-effort HBM when higher-tier demand lands on an
        # oversubscribed chip, behind GET /inspect/qos. Its background
        # thread only starts when TPUSHARE_QOS_OVERCOMMIT > 1 — a
        # single-class fleet pays nothing.
        from tpushare.qos.pressure import QosPressureMonitor
        self.qos_pressure = QosPressureMonitor(cache, cluster)
        # batched decision cycles (cache/batch.py): same-signature pods
        # arriving within TPUSHARE_BATCH_WINDOW_MS coalesce into one
        # multi-pod native solve. Window 0 (the default) disables the
        # layer entirely — quiet deployments pay nothing.
        from tpushare.cache.batch import BatchPlanner
        self.batcher = BatchPlanner(cache)
        # wire-plane cache (extender/wirecache.py): digest-keyed decode
        # of the fleet-size NodeNames list + pre-encoded responses,
        # stamp-revalidated against cache mutations. TPUSHARE_NO_WIRECACHE=1
        # opts out; TPUSHARE_WIRE_VERIFY=1 recomputes every hit.
        from tpushare.extender.wirecache import WireCache
        self.wirecache = WireCache(cache)
        # native wire table (extender/nativewire.py, ABI v6): the
        # selector loop serves byte-identical repeats of digest-hit
        # requests GIL-released; wirecache._finish delta-syncs fresh
        # encodes into it under the same mutation-stamp protocol.
        # Degrades to pure-Python serving on a pre-v6 .so or
        # TPUSHARE_NO_NATIVE_WIRE=1.
        from tpushare.extender.nativewire import NativeWireTable
        self.nativewire = NativeWireTable(
            cache.mutation_stamp,
            wirecache_enabled=self.wirecache.enabled,
            verify=self.wirecache.verify)
        self.wirecache.native = self.nativewire
        self.filter_handler = FilterHandler(cache, self.registry,
                                            gang=self.gang, breaker=breaker,
                                            staleness_fn=staleness_fn,
                                            tracer=self.tracer,
                                            explain=self.explain,
                                            batcher=self.batcher,
                                            wire=self.wirecache)
        self.prioritize_handler = PrioritizeHandler(
            cache, self.registry, breaker=breaker, tracer=self.tracer,
            explain=self.explain, wire=self.wirecache,
            forecast=self.frag_forecast)
        self.preempt_handler = PreemptHandler(cache, self.registry)
        # HA (an elector is wired): binds also CAS a per-node claim so two
        # replicas in a stale-leader window cannot co-place onto one chip;
        # single-replica mode skips the two extra apiserver round-trips.
        # An informer (k8s/informer.py, lifecycle owned by the caller)
        # serves Bind's pod fetch from its watch-warmed lister instead of
        # a per-bind apiserver GET.
        self.bind_handler = BindHandler(
            cache, cluster, self.registry,
            ha_claims=elector is not None or sharding is not None,
            gang=self.gang,
            pod_lister=informer.pods if informer is not None else None,
            breaker=breaker, tracer=self.tracer, explain=self.explain,
            sharding=sharding)
        self.inspect_handler = InspectHandler(cache)
        if breaker is not None:
            from tpushare.k8s.breaker import register_breaker_gauge
            register_breaker_gauge(self.registry, breaker)
        if informer is not None:
            # staleness as a first-class scrape (was /readyz-only): the
            # bound on how stale a degraded-mode Filter verdict can be
            self.registry.gauge_func(
                "tpushare_informer_staleness_seconds",
                "Seconds since the informer last applied a watch event "
                "or relist (the staleness bound on degraded-mode "
                "verdicts; alert when it grows past the relist period)",
                lambda: [("", round(informer.staleness_s(), 3))]
                if informer.staleness_s() is not None else [])
        self.host, self.port = host, port
        self._httpd: ThreadingHTTPServer | None = None
        # development-mode only (--fake-nodes): lets an operator seed pods
        # into the in-memory cluster so the full filter->bind cycle can be
        # driven with curl; never enabled against a real apiserver
        self._seed_cluster = cluster if allow_debug_seed else None
        # HA: when an elector is wired, only the leader replica may Bind
        # (Filter/Inspect stay readable on every replica — their caches are
        # watch-warmed). None = single-replica mode, always leader.
        self._elector = elector
        # active-active sharding (ha/sharding.py) SUPERSEDES the leader
        # gate: every replica binds (lock-free on its own shard, claim
        # CAS on spillover), owned-subset cache views track the ring,
        # and the defrag controller runs only on the ring leader so
        # exactly one planner acts fleet-wide.
        self._sharding = sharding
        if sharding is not None:
            sharding.attach(self.registry)
            self.defrag.gate = sharding.is_ring_leader
        # owner forwarding (ha/forward.py): active-active only — it
        # routes on the same ring. No peers advertised = no-op.
        self.forwarder = ForwardRouter(sharding) \
            if sharding is not None else None
        self._serve_done: threading.Event | None = None

    # -- request routing (shared by both front ends) --------------------------

    def handle_post(self, path: str, raw: bytes,
                    headers=None) -> tuple[int, bytes, str]:
        """Route one POST: ``(status, payload bytes, content type)``.

        Front-end-agnostic — the threaded handler, the selector worker
        pool, and a peer's forwarded request all land here. ``headers``
        only needs a case-insensitive-enough ``get`` (the loop-guard
        header is looked up by its canonical name).
        """
        wctx = None
        try:
            if self.wirecache is not None and _POST_ROUTES.get(path) in (
                    "filter", "prioritize"):
                # digest-cached decode: a steady-storm repeat of the same
                # fleet-size candidate list parses ~0 of its bytes
                args, wctx = self.wirecache.decode(raw)
            else:
                args = json.loads(raw) if raw else {}
        except json.JSONDecodeError as e:
            return _enc(400, {"error": f"bad JSON: {e}"})
        try:
            # stamp the per-request deadline: every retry loop
            # underneath (k8s/retry.py) consults it — the forward hop
            # included — and stops before the scheduler's httpTimeout
            from tpushare.k8s.retry import request_deadline
            with request_deadline(self.request_deadline_s):
                return self._post_routed(path, raw, args, headers, wctx)
        except Exception as e:  # noqa: BLE001 — webhook must answer
            log.error("POST %s crashed: %s\n%s", path, e,
                      traceback.format_exc())
            return _enc(500, {"Error": f"internal error: {e}"})

    def _post_routed(self, path: str, raw: bytes, args: Any,
                     headers, wctx=None) -> tuple[int, bytes, str]:
        route = _POST_ROUTES.get(path)
        if route in ("filter", "prioritize", "bind") and \
                self.forwarder is not None:
            fwd = self.forwarder.maybe_forward(
                route, path, raw, args,
                headers.get(FORWARD_HEADER) if headers is not None
                else None)
            if fwd is not None:
                # the owner's verdict, relayed verbatim
                return fwd[0], fwd[1], "application/json"
        if route == "filter":
            out = self.filter_handler.handle(args, wire_ctx=wctx)
            if isinstance(out, WireEncoded):
                return 200, out.body, "application/json"
            return _enc(200, out)
        if route == "prioritize":
            out = self.prioritize_handler.handle(args, wire_ctx=wctx)
            if isinstance(out, WireEncoded):
                return 200, out.body, "application/json"
            return _enc(200, out)
        if route == "preempt":
            return _enc(200, self.preempt_handler.handle(args))
        if route == "bind":
            # active-active (sharding wired): EVERY replica binds —
            # lock-free on its shard, claim-CAS on spillover — so the
            # leader gate applies only to the legacy active-passive
            # elector mode
            if self._sharding is None and self._elector is not None \
                    and not self._elector.is_leader():
                # retryable: the default scheduler re-binds after its
                # timeout and reaches the leader
                return _enc(503, {"Error": "not the leader; retry"})
            result = self.bind_handler.handle(
                args, forwarded_from=(headers.get(FORWARD_HEADER)
                                      if headers is not None else None))
            # reference returns 500 on bind failure (routes.go:139)
            return _enc(500 if result.get("Error") else 200, result)
        if path == "/debug/pods" and self._seed_cluster:
            return _enc(201, self._seed_cluster.create_pod(args))
        return _enc(404, {"error": f"no route {path}"})

    def handle_get(self, path: str) -> tuple[int, bytes, str]:
        try:
            return self._get_routed(path)
        except Exception as e:  # noqa: BLE001
            log.error("GET %s crashed: %s", path, e)
            return _enc(500, {"error": str(e)})

    def _get_routed(self, path: str) -> tuple[int, bytes, str]:
        if path == "/version":
            info = {"version": tpushare.__version__}
            if self._elector is not None:
                info["leader"] = self._elector.is_leader()
                info["identity"] = self._elector.identity
            return _enc(200, info)
        if path == "/healthz":
            # liveness only: the process is up and serving. Everything
            # state-dependent belongs to /readyz — restarting a pod
            # because the APISERVER browned out would make the outage
            # strictly worse.
            return _enc(200, "ok", content_type="text/plain")
        if path == "/readyz":
            ready, body = self.readiness()
            return _enc(200 if ready else 503, body)
        if path == "/metrics":
            return _enc(200, self.registry.expose(),
                        content_type="text/plain; version=0.0.4")
        if path in ("/metrics/federated", f"{PREFIX}/metrics/federated"):
            # fleet-wide counters/histograms: local live registry merged
            # with every peer replica's published snapshot. With no
            # federation segment this degenerates to the local registry
            # in the merged (sorted, gauge-free) rendering.
            from tpushare.metrics import expose_merged, merge_states
            if self.federation is not None:
                text = self.federation.merged_text()
            else:
                text = expose_merged(merge_states(
                    [self.registry.federation_state()]))
            return _enc(200, text,
                        content_type="text/plain; version=0.0.4")
        if path.startswith("/debug/traces") or \
                path.startswith(f"{PREFIX}/debug/traces"):
            limit = None
            if "n=" in path:
                try:
                    limit = int(path.split("n=")[1])
                except ValueError:
                    pass
            return _enc(200, self.tracer.recorder.dump(limit=limit))
        if path.startswith("/inspect/explain") or \
                path.startswith(f"{PREFIX}/inspect/explain"):
            return self._serve_explain(path)
        if path.split("?", 1)[0] in ("/inspect/fleet",
                                     f"{PREFIX}/inspect/fleet"):
            snap = self.fleetwatch.snapshot()
            if "federated=1" in path:
                snap["federation"] = self.federation_snapshot()
            return _enc(200, snap)
        if path in ("/inspect/journal", f"{PREFIX}/inspect/journal"):
            return _enc(200, self.journal_snapshot())
        if path in ("/inspect/defrag", f"{PREFIX}/inspect/defrag"):
            return _enc(200, self.defrag.snapshot())
        if path in ("/inspect/gang", f"{PREFIX}/inspect/gang"):
            return _enc(200, self.gang.snapshot())
        if path in ("/inspect/wire", f"{PREFIX}/inspect/wire"):
            return _enc(200, self.wire_snapshot())
        if path in ("/inspect/qos", f"{PREFIX}/inspect/qos"):
            return _enc(200, self.qos_snapshot())
        if path in ("/inspect/ring", f"{PREFIX}/inspect/ring"):
            if self._sharding is not None:
                return _enc(200, self._sharding.snapshot())
            return _enc(200, {
                "enabled": False,
                "mode": ("leader-elect" if self._elector is not None
                         else "single-replica"),
            })
        if path in (f"{PREFIX}/inspect", f"{PREFIX}/inspect/"):
            return _enc(200, self.inspect_handler.handle())
        if path.startswith(f"{PREFIX}/inspect/"):
            node = path[len(f"{PREFIX}/inspect/"):]
            out = self.inspect_handler.handle(node)
            return _enc(404 if "error" in out else 200, out)
        if path == "/debug/threads":
            return _enc(200, _thread_dump(), content_type="text/plain")
        if path.startswith("/debug/profile"):
            seconds = 1.0
            if "seconds=" in path:
                try:
                    seconds = min(float(path.split("seconds=")[1]), 30.0)
                except ValueError:
                    pass
            return _enc(200, _profile(seconds), content_type="text/plain")
        if path.startswith("/debug/heap"):
            top = 25
            if "top=" in path:
                try:
                    top = min(int(path.split("top=")[1]), 200)
                except ValueError:
                    pass
            return _enc(200, _heap_profile(top), content_type="text/plain")
        return _enc(404, {"error": f"no route {path}"})

    def _serve_explain(self, path: str) -> tuple[int, bytes, str]:
        """/inspect/explain       -> list of audited pods
           /inspect/explain/<pod> -> that pod's decision history
                                     (<pod> = uid, namespace/name or name)
        """
        if path.startswith(PREFIX):
            path = path[len(PREFIX):]
        selector = path[len("/inspect/explain"):].strip("/")
        if not selector:
            return _enc(200, {"pods": self.explain.pods()})
        out = self.explain.get(selector)
        if out is None:
            return _enc(404, {
                "error": f"no decision record for {selector!r} "
                         "(kept for the last "
                         f"{self.explain.max_pods} pods x "
                         f"{self.explain.cycles_per_pod} cycles)"})
        return _enc(200, out)

    # -- legacy thread-per-connection front end -------------------------------

    def _make_handler(server_self):  # noqa: N805 — closure over the server
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # kube-scheduler reuses keep-alive connections to its
            # extenders; without TCP_NODELAY the headers-then-body write
            # pattern stalls ~40ms per webhook call on Nagle + delayed-ACK
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # route into logging, not stderr
                log.debug("%s %s", self.address_string(), fmt % args)

            def _send(self, out: tuple[int, bytes, str]) -> None:
                status, data, content_type = out
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                # ALWAYS drain the body first: these are HTTP/1.1
                # keep-alive connections, and replying with unread
                # Content-Length bytes in the socket would make the
                # leftover body parse as the next request line
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                self._send(server_self.handle_post(
                    self.path, raw, self.headers))

            def do_GET(self):
                self._send(server_self.handle_get(self.path))

        return Handler

    # -- readiness ------------------------------------------------------------

    def readiness(self) -> tuple[bool, dict[str, Any]]:
        """The /readyz verdict + report.

        Ready = the startup cache replay completed and the informer's
        initial sync happened (when one is wired) — the two conditions
        under which a served verdict cannot oversubscribe. Breaker state
        and informer staleness are REPORTED but do not gate readiness:
        an open circuit means degraded mode (Filter still serves from
        cache; Bind fail-fasts with an honest error), and flipping the
        replica unready then would take even the degraded service away.
        """
        cache_built = bool(getattr(self._cache, "built", True))
        informer_synced = (self._informer.synced
                          if self._informer is not None else None)
        staleness = (self._informer.staleness_s()
                     if self._informer is not None else None)
        breaker_state = (self._breaker.state
                         if self._breaker is not None else None)
        ready = cache_built and informer_synced is not False
        return ready, {
            "ready": ready,
            "cache_built": cache_built,
            "informer_synced": informer_synced,
            "informer_staleness_s": (round(staleness, 3)
                                     if staleness is not None else None),
            "breaker_state": breaker_state,
            "degraded": breaker_state == "open",
        }

    # -- lifecycle ------------------------------------------------------------

    def _start_fleetwatch(self) -> None:
        if os.environ.get("TPUSHARE_FLEETWATCH", "1") != "0":
            self.fleetwatch.start()
        if self.defrag.enabled():
            self.defrag.start()
        from tpushare.qos.tiers import overcommit
        if overcommit() > 1.0:
            self.qos_pressure.start()
        self.blackbox.start()  # no-op without an ABI v8 .so
        if self.journal is not None:
            self.journal.start()
        from tpushare.extender import federation as fedlib
        if fedlib.enabled():
            fed = fedlib.FederationSegment(self.registry, self.port)
            if fed.start():
                self.federation = fed

    def start(self, http_workers: int | None = None) -> int:
        """Bind and serve on background threads; returns the bound port.

        The selector/event-loop front end (extender/httpserver.py) is
        the default; ``TPUSHARE_SERVER=threaded`` keeps the legacy
        stdlib thread-per-connection server.
        """
        from tpushare.core import native as native_engine
        native_engine.warmup()  # first Filter must not pay engine cold-start
        if os.environ.get("TPUSHARE_SERVER", "selector") == "threaded":
            self._httpd = ThreadingHTTPServer(
                (self.host, self.port), self._make_handler())
            self.port = self._httpd.server_address[1]
            t = threading.Thread(target=self._httpd.serve_forever,
                                 name="tpushare-http", daemon=True)
            t.start()
        else:
            from tpushare.extender.httpserver import SelectorHTTPServer
            self._httpd = SelectorHTTPServer(
                self.host, self.port,
                handle_get=self.handle_get, handle_post=self.handle_post,
                max_workers=http_workers,
                native_wire=self.nativewire)
            self.port = self._httpd.start()
            httpd = self._httpd
            self.registry.gauge_func(
                "tpushare_http_open_connections",
                "Open keep-alive connections held by the event-loop "
                "front end (each costs a buffer, not a thread)",
                lambda: [("", float(httpd.open_connections()))])
            self.registry.gauge_func(
                "tpushare_http_busy_workers",
                "Front-end worker-pool threads currently inside a "
                "handler (sustained == pool size means requests are "
                "queueing; raise TPUSHARE_HTTP_WORKERS)",
                lambda: [("", float(httpd.busy_workers()))])
        self._start_fleetwatch()
        log.info("extender listening on %s:%d", self.host, self.port)
        return self.port

    def serve_forever(self) -> None:
        self.start()
        self._serve_done = threading.Event()
        self._serve_done.wait()

    def stop(self) -> None:
        self.qos_pressure.stop()
        self.defrag.stop()
        self.fleetwatch.stop()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # serving is down: drain the ring tail, flush the journal, and
        # leave our federation slot frozen with the complete history
        self.blackbox.stop()
        if self.journal is not None:
            self.journal.stop()
        if self.federation is not None:
            self.federation.stop()
        # after the loop thread is down: probes read the handle lock-free
        self.nativewire.close()
        if self._serve_done is not None:
            self._serve_done.set()

    def qos_snapshot(self) -> dict:
        """GET /inspect/qos: the QoS tier plane in one read — knobs and
        their effective values, per-tier fleet usage, oversubscribed
        nodes, the eviction budget/backoff/degraded state, and every
        tenant's DRF dominant share (tpushare-inspect qos)."""
        from tpushare.qos.drf import dominant_shares, drf_cap
        from tpushare.qos.tiers import (
            effective_overcommit, is_degraded, overcommit)
        by_tier: dict[str, int] = {}
        oversub_nodes: dict[str, int] = {}
        reclaimable = 0
        total = 0
        for name in self._cache.node_names():
            info = self._cache.peek_node(name)
            if info is None:
                continue
            u = info.qos_usage()
            for t, mib in u["by_tier_hbm_mib"].items():
                by_tier[t] = by_tier.get(t, 0) + mib
            if u["oversubscribed_hbm_mib"] > 0:
                oversub_nodes[name] = u["oversubscribed_hbm_mib"]
            reclaimable += u["reclaimable_hbm_mib"]
            total += u["total_hbm_mib"]
        return {
            "overcommit": overcommit(),
            "effective_overcommit": effective_overcommit(),
            "evictor_degraded": is_degraded(),
            "drf_cap": drf_cap(),
            "fleet": {
                "by_tier_hbm_mib": by_tier,
                "reclaimable_hbm_mib": reclaimable,
                "total_hbm_mib": total,
                "oversubscribed_hbm_mib": sum(oversub_nodes.values()),
            },
            "oversubscribed_nodes": oversub_nodes,
            "eviction": self.qos_pressure.budget_state(),
            "tenant_dominant_share": {
                ns: round(s, 6)
                for ns, s in sorted(dominant_shares(self._cache).items())},
        }

    def _journal_fleet_info(self) -> dict[str, Any] | None:
        """Best-effort fleet geometry for the journal header, in the
        sim/replay vocabulary (sim.replay.DEFAULT_FLEET keys). None when
        the cache hasn't seen a node yet — replay falls back to
        defaults, the journal stays valid."""
        try:
            names = self._cache.node_names()
            if not names:
                return None
            info = self._cache.peek_node(names[0])
            if info is None:
                return {"n_nodes": len(names)}
            mesh = getattr(getattr(info, "topology", None), "shape", None)
            return {
                "n_nodes": len(names),
                "chips_per_node": int(info.chip_count),
                "hbm_per_chip_mib": int(info.hbm_per_chip),
                "mesh": list(mesh) if mesh and len(mesh) > 1 else None,
            }
        except Exception:  # noqa: BLE001 — header info is best-effort
            return None

    def federation_snapshot(self) -> dict:
        """/inspect/fleet?federated=1 payload: who is publishing into
        the segment and the fleet-wide merged counter totals."""
        if self.federation is None:
            return {"enabled": False, "replica_count": 1}
        merged, meta = self.federation.merged_state()
        totals: dict[str, Any] = {}
        for name in sorted(merged):
            s = merged[name]
            if s["type"] == "counter":
                totals[name] = s["value"]
            elif s["type"] == "labeled_counter":
                totals[name] = sum(v for _, v in s.get("series", []))
            elif s["type"] == "histogram":
                totals[name] = {"count": sum(s.get("counts", [])),
                                "sum": round(s.get("sum", 0.0), 6)}
        return {
            "enabled": True,
            "replica_count": meta["replica_count"],
            "replicas": meta["replicas"],
            "merged_totals": totals,
        }

    def journal_snapshot(self) -> dict:
        """GET /inspect/journal: the whole black-box plane in one read —
        ring pump state, decision-journal files/counters, federation
        slot state (tpushare-inspect journal)."""
        journal = ({"enabled": True, **self.journal.stats()}
                   if self.journal is not None else {"enabled": False})
        federation = (self.federation.stats()
                      if self.federation is not None
                      else {"enabled": False})
        return {
            "blackbox": self.blackbox.stats(),
            "journal": journal,
            "federation": federation,
        }

    def wire_snapshot(self) -> dict:
        """GET /inspect/wire: the whole wire plane in one read — Python
        digest/response-cache occupancy plus the native table's
        occupancy, hit rate and serve outcomes (tpushare-inspect wire)."""
        from tpushare.extender.nativewire import WIRE_NATIVE_SERVES
        from tpushare.extender.wirecache import (
            WIRE_DIGEST, WIRE_RESPONSES, WIRE_STALE_SERVES)
        wc = self.wirecache
        digests, responses = wc.occupancy()
        return {
            "wirecache": {
                "enabled": wc.enabled,
                "verify": wc.verify,
                "digests": digests,
                "max_digests": wc.MAX_DIGESTS,
                "responses": responses,
                "digest_outcomes": {k[0]: v for k, v
                                    in WIRE_DIGEST.snapshot().items()},
                "response_outcomes": {
                    f"{verb}/{outcome}": v for (verb, outcome), v
                    in WIRE_RESPONSES.snapshot().items()},
                "stale_serves": WIRE_STALE_SERVES.value,
            },
            "native": self.nativewire.stats(),
            "native_outcomes": {k[0]: v for k, v
                                in WIRE_NATIVE_SERVES.snapshot().items()},
        }


def _thread_dump() -> str:
    """Goroutine-dump analogue of the reference's pprof mount
    (pkg/routes/pprof.go:10-22)."""
    lines = []
    for tid, frame in sys._current_frames().items():
        name = next((t.name for t in threading.enumerate()
                     if t.ident == tid), str(tid))
        lines.append(f"--- thread {name} ({tid}) ---")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    return "\n".join(lines) + "\n"


def _heap_profile(top: int = 25) -> str:
    """Heap-profile analogue of pprof's /debug/pprof/heap
    (/root/reference/pkg/routes/pprof.go:10-22) via tracemalloc.

    First call arms tracing and returns a baseline notice; subsequent
    calls report the top allocation sites since then. Tracing stays on
    once armed (a few % overhead) — same operational model as Go's
    always-on heap profiler.
    """
    import tracemalloc
    if not tracemalloc.is_tracing():
        tracemalloc.start(25)
        return ("# tracemalloc armed; heap snapshots available from the "
                "next request on\n")
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("traceback")
    total = sum(s.size for s in stats)
    lines = [f"# live traced heap: {total / 1024:.1f} KiB in "
             f"{sum(s.count for s in stats)} blocks; top {top} sites"]
    for s in stats[:top]:
        lines.append(f"{s.size / 1024:10.1f} KiB  {s.count:6d} blocks")
        for frame in s.traceback.format(limit=4):
            lines.append("    " + frame.strip())
    return "\n".join(lines) + "\n"


def _profile(seconds: float, interval: float = 0.005) -> str:
    """Sampling profile across ALL threads for N seconds (pprof /profile).

    cProfile only instruments the calling thread (which would just be this
    handler sleeping); instead we sample sys._current_frames() and
    aggregate stack suffixes — a flat statistical view of where the
    scheduler actually spends time under load.
    """
    counts: dict[str, int] = {}
    samples = 0
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = traceback.extract_stack(frame, limit=6)
            key = " <- ".join(f"{f.name}:{f.lineno} ({f.filename.rsplit('/', 1)[-1]})"
                              for f in reversed(stack))
            counts[key] = counts.get(key, 0) + 1
            samples += 1
        time.sleep(interval)
    lines = [f"# {samples} samples over {seconds}s at {interval * 1e3:.0f}ms"]
    for key, n in sorted(counts.items(), key=lambda kv: -kv[1])[:40]:
        lines.append(f"{n:6d}  {key}")
    return "\n".join(lines) + "\n"
