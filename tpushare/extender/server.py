"""HTTP server wiring for the extender (reference pkg/routes/routes.go).

stdlib ThreadingHTTPServer: every scheduler webhook call is handled on its
own thread over the lock-scoped cache, replacing the reference's
httprouter + net/http stack. Bind failures return HTTP 500 with the
ExtenderBindingResult body (routes.go:139-143 does the same), which makes
the default scheduler retry after its timeout.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import tpushare
from tpushare.extender.handlers import (
    BindHandler,
    FilterHandler,
    InspectHandler,
    PreemptHandler,
    PrioritizeHandler,
)
from tpushare.extender.metrics import Registry

log = logging.getLogger("tpushare.extender.http")

PREFIX = "/tpushare-scheduler"


class ExtenderServer:
    # per-request deadline default: safely under the kube-scheduler's
    # extender httpTimeout (30 s in config/kube-scheduler-config.yaml) —
    # retries stop, and the webhook answers, BEFORE the caller hangs up
    DEFAULT_REQUEST_DEADLINE_S = 25.0

    def __init__(self, cache, cluster, registry: Registry | None = None,
                 host: str = "0.0.0.0", port: int = 39999,
                 allow_debug_seed: bool = False,
                 elector=None, informer=None, breaker=None,
                 request_deadline_s: float | None = None,
                 sharding=None) -> None:
        self.registry = registry or Registry()
        self._cache = cache
        self._informer = informer
        # apiserver circuit breaker (k8s/breaker.py): Bind fail-fasts
        # while it is open, Filter/Prioritize count degraded serves, and
        # /readyz reports its state. None = no degraded-mode wiring.
        self._breaker = breaker
        if request_deadline_s is None:
            import os
            request_deadline_s = float(os.environ.get(
                "TPUSHARE_REQUEST_DEADLINE_S",
                self.DEFAULT_REQUEST_DEADLINE_S))
        self.request_deadline_s = request_deadline_s
        staleness_fn = informer.staleness_s if informer is not None else None
        # observability (obs/, docs/observability.md): the process-wide
        # cycle tracer + its flight recorder behind /debug/traces, and
        # the per-decision audit store behind /inspect/explain/<pod>
        from tpushare.obs import ExplainStore
        from tpushare.obs.trace import TRACER
        self.tracer = TRACER
        self.explain = ExplainStore()
        # fleet-health layer (obs/fleetwatch.py): fragmentation/
        # utilization gauges + the continuous drift auditor behind
        # GET /inspect/fleet; its scorecard consumes the decision-audit
        # stream via the ExplainStore observer hook. The background
        # thread starts with the server (TPUSHARE_FLEETWATCH=0 opts out).
        from tpushare.obs.fleetwatch import FleetWatch
        self.fleetwatch = FleetWatch(cache, cluster=cluster,
                                     informer=informer)
        self.explain.observer = self.fleetwatch.scorecard
        self.fleetwatch.attach(self.registry)
        # live defragmentation (defrag/): the repack rebalancer consumes
        # the same capacity-index stranded-gap picture the fleetwatch
        # gauges publish and acts on it under a migration budget, behind
        # GET /inspect/defrag. Background thread starts with the server
        # (TPUSHARE_DEFRAG=0 opts out); decisions land in the explain
        # audit and the cycle tracer like any scheduling verdict.
        from tpushare.defrag import DefragController
        self.defrag = DefragController(cache, cluster=cluster,
                                       explain=self.explain)
        self.defrag.attach(self.registry)
        # multi-host gang placement (docs/designs/multihost-gang.md):
        # engages only for pods carrying the gang annotations, on nodes
        # labeled into slices — zero cost otherwise
        from tpushare.cache.gang import GangCoordinator
        self.gang = GangCoordinator(cache)
        # batched decision cycles (cache/batch.py): same-signature pods
        # arriving within TPUSHARE_BATCH_WINDOW_MS coalesce into one
        # multi-pod native solve. Window 0 (the default) disables the
        # layer entirely — quiet deployments pay nothing.
        from tpushare.cache.batch import BatchPlanner
        self.batcher = BatchPlanner(cache)
        self.filter_handler = FilterHandler(cache, self.registry,
                                            gang=self.gang, breaker=breaker,
                                            staleness_fn=staleness_fn,
                                            tracer=self.tracer,
                                            explain=self.explain,
                                            batcher=self.batcher)
        self.prioritize_handler = PrioritizeHandler(cache, self.registry,
                                                    breaker=breaker,
                                                    tracer=self.tracer,
                                                    explain=self.explain)
        self.preempt_handler = PreemptHandler(cache, self.registry)
        # HA (an elector is wired): binds also CAS a per-node claim so two
        # replicas in a stale-leader window cannot co-place onto one chip;
        # single-replica mode skips the two extra apiserver round-trips.
        # An informer (k8s/informer.py, lifecycle owned by the caller)
        # serves Bind's pod fetch from its watch-warmed lister instead of
        # a per-bind apiserver GET.
        self.bind_handler = BindHandler(
            cache, cluster, self.registry,
            ha_claims=elector is not None or sharding is not None,
            gang=self.gang,
            pod_lister=informer.pods if informer is not None else None,
            breaker=breaker, tracer=self.tracer, explain=self.explain,
            sharding=sharding)
        self.inspect_handler = InspectHandler(cache)
        if breaker is not None:
            from tpushare.k8s.breaker import register_breaker_gauge
            register_breaker_gauge(self.registry, breaker)
        if informer is not None:
            # staleness as a first-class scrape (was /readyz-only): the
            # bound on how stale a degraded-mode Filter verdict can be
            self.registry.gauge_func(
                "tpushare_informer_staleness_seconds",
                "Seconds since the informer last applied a watch event "
                "or relist (the staleness bound on degraded-mode "
                "verdicts; alert when it grows past the relist period)",
                lambda: [("", round(informer.staleness_s(), 3))]
                if informer.staleness_s() is not None else [])
        self.host, self.port = host, port
        self._httpd: ThreadingHTTPServer | None = None
        # development-mode only (--fake-nodes): lets an operator seed pods
        # into the in-memory cluster so the full filter->bind cycle can be
        # driven with curl; never enabled against a real apiserver
        self._seed_cluster = cluster if allow_debug_seed else None
        # HA: when an elector is wired, only the leader replica may Bind
        # (Filter/Inspect stay readable on every replica — their caches are
        # watch-warmed). None = single-replica mode, always leader.
        self._elector = elector
        # active-active sharding (ha/sharding.py) SUPERSEDES the leader
        # gate: every replica binds (lock-free on its own shard, claim
        # CAS on spillover), owned-subset cache views track the ring,
        # and the defrag controller runs only on the ring leader so
        # exactly one planner acts fleet-wide.
        self._sharding = sharding
        if sharding is not None:
            sharding.attach(self.registry)
            self.defrag.gate = sharding.is_ring_leader

    # -- request routing ------------------------------------------------------

    def _make_handler(server_self):  # noqa: N805 — closure over the server
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # kube-scheduler reuses keep-alive connections to its
            # extenders; without TCP_NODELAY the headers-then-body write
            # pattern stalls ~40ms per webhook call on Nagle + delayed-ACK
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # route into logging, not stderr
                log.debug("%s %s", self.address_string(), fmt % args)

            def _reply(self, code: int, body: Any,
                       content_type: str = "application/json") -> None:
                data = (json.dumps(body).encode()
                        if content_type == "application/json"
                        else body.encode())
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _read_json(self) -> Any:
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                return json.loads(raw) if raw else {}

            def do_POST(self):
                try:
                    # ALWAYS drain the body first: these are HTTP/1.1
                    # keep-alive connections, and replying with unread
                    # Content-Length bytes in the socket would make the
                    # leftover body parse as the next request line
                    args = self._read_json()
                    # stamp the per-request deadline: every retry loop
                    # underneath this handler (k8s/retry.py) consults it
                    # and stops before the scheduler's httpTimeout fires
                    from tpushare.k8s.retry import request_deadline
                    with request_deadline(server_self.request_deadline_s):
                        self._do_post_routed(args)
                except json.JSONDecodeError as e:
                    self._reply(400, {"error": f"bad JSON: {e}"})
                except Exception as e:  # noqa: BLE001 — webhook must answer
                    log.error("POST %s crashed: %s\n%s", self.path, e,
                              traceback.format_exc())
                    self._reply(500, {"Error": f"internal error: {e}"})

            def _do_post_routed(self, args):
                if self.path == f"{PREFIX}/filter":
                    self._reply(200, server_self.filter_handler.handle(args))
                elif self.path == f"{PREFIX}/prioritize":
                    self._reply(
                        200,
                        server_self.prioritize_handler.handle(args))
                elif self.path == f"{PREFIX}/preempt":
                    self._reply(
                        200, server_self.preempt_handler.handle(args))
                elif self.path == f"{PREFIX}/bind":
                    # active-active (sharding wired): EVERY replica
                    # binds — lock-free on its shard, claim-CAS on
                    # spillover — so the leader gate applies only to
                    # the legacy active-passive elector mode
                    if server_self._sharding is None and \
                            server_self._elector is not None and \
                            not server_self._elector.is_leader():
                        # retryable: the default scheduler re-binds
                        # after its timeout and reaches the leader
                        self._reply(503, {
                            "Error": "not the leader; retry"})
                        return
                    result = server_self.bind_handler.handle(args)
                    # reference returns 500 on bind failure (routes.go:139)
                    self._reply(500 if result.get("Error") else 200, result)
                elif self.path == "/debug/pods" and server_self._seed_cluster:
                    pod = server_self._seed_cluster.create_pod(args)
                    self._reply(201, pod)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_GET(self):
                try:
                    if self.path == "/version":
                        info = {"version": tpushare.__version__}
                        if server_self._elector is not None:
                            info["leader"] = server_self._elector.is_leader()
                            info["identity"] = server_self._elector.identity
                        self._reply(200, info)
                    elif self.path == "/healthz":
                        # liveness only: the process is up and serving.
                        # Everything state-dependent belongs to /readyz —
                        # restarting a pod because the APISERVER browned
                        # out would make the outage strictly worse.
                        self._reply(200, "ok", content_type="text/plain")
                    elif self.path == "/readyz":
                        ready, body = server_self.readiness()
                        self._reply(200 if ready else 503, body)
                    elif self.path == "/metrics":
                        self._reply(200, server_self.registry.expose(),
                                    content_type="text/plain; version=0.0.4")
                    elif self.path.startswith("/debug/traces") or \
                            self.path.startswith(f"{PREFIX}/debug/traces"):
                        limit = None
                        if "n=" in self.path:
                            try:
                                limit = int(self.path.split("n=")[1])
                            except ValueError:
                                pass
                        self._reply(200, server_self.tracer.recorder
                                    .dump(limit=limit))
                    elif self.path.startswith("/inspect/explain") or \
                            self.path.startswith(f"{PREFIX}/inspect/explain"):
                        self._serve_explain()
                    elif self.path == "/inspect/fleet" or \
                            self.path == f"{PREFIX}/inspect/fleet":
                        self._reply(200,
                                    server_self.fleetwatch.snapshot())
                    elif self.path == "/inspect/defrag" or \
                            self.path == f"{PREFIX}/inspect/defrag":
                        self._reply(200, server_self.defrag.snapshot())
                    elif self.path == "/inspect/ring" or \
                            self.path == f"{PREFIX}/inspect/ring":
                        if server_self._sharding is not None:
                            self._reply(200,
                                        server_self._sharding.snapshot())
                        else:
                            self._reply(200, {
                                "enabled": False,
                                "mode": ("leader-elect"
                                         if server_self._elector
                                         is not None
                                         else "single-replica"),
                            })
                    elif self.path == f"{PREFIX}/inspect" or \
                            self.path == f"{PREFIX}/inspect/":
                        self._reply(200, server_self.inspect_handler.handle())
                    elif self.path.startswith(f"{PREFIX}/inspect/"):
                        node = self.path[len(f"{PREFIX}/inspect/"):]
                        out = server_self.inspect_handler.handle(node)
                        self._reply(404 if "error" in out else 200, out)
                    elif self.path == "/debug/threads":
                        self._reply(200, _thread_dump(),
                                    content_type="text/plain")
                    elif self.path.startswith("/debug/profile"):
                        seconds = 1.0
                        if "seconds=" in self.path:
                            try:
                                seconds = min(float(
                                    self.path.split("seconds=")[1]), 30.0)
                            except ValueError:
                                pass
                        self._reply(200, _profile(seconds),
                                    content_type="text/plain")
                    elif self.path.startswith("/debug/heap"):
                        top = 25
                        if "top=" in self.path:
                            try:
                                top = min(int(
                                    self.path.split("top=")[1]), 200)
                            except ValueError:
                                pass
                        self._reply(200, _heap_profile(top),
                                    content_type="text/plain")
                    else:
                        self._reply(404, {"error": f"no route {self.path}"})
                except Exception as e:  # noqa: BLE001
                    log.error("GET %s crashed: %s", self.path, e)
                    self._reply(500, {"error": str(e)})

            def _serve_explain(self):
                """/inspect/explain            -> list of audited pods
                   /inspect/explain/<pod>      -> that pod's decision
                                                  history (<pod> = uid,
                                                  namespace/name or name)
                """
                path = self.path
                if path.startswith(PREFIX):
                    path = path[len(PREFIX):]
                selector = path[len("/inspect/explain"):].strip("/")
                if not selector:
                    self._reply(200,
                                {"pods": server_self.explain.pods()})
                    return
                out = server_self.explain.get(selector)
                if out is None:
                    self._reply(404, {
                        "error": f"no decision record for {selector!r} "
                                 "(kept for the last "
                                 f"{server_self.explain.max_pods} pods x "
                                 f"{server_self.explain.cycles_per_pod} "
                                 "cycles)"})
                    return
                self._reply(200, out)

        return Handler

    # -- readiness ------------------------------------------------------------

    def readiness(self) -> tuple[bool, dict[str, Any]]:
        """The /readyz verdict + report.

        Ready = the startup cache replay completed and the informer's
        initial sync happened (when one is wired) — the two conditions
        under which a served verdict cannot oversubscribe. Breaker state
        and informer staleness are REPORTED but do not gate readiness:
        an open circuit means degraded mode (Filter still serves from
        cache; Bind fail-fasts with an honest error), and flipping the
        replica unready then would take even the degraded service away.
        """
        cache_built = bool(getattr(self._cache, "built", True))
        informer_synced = (self._informer.synced
                          if self._informer is not None else None)
        staleness = (self._informer.staleness_s()
                     if self._informer is not None else None)
        breaker_state = (self._breaker.state
                         if self._breaker is not None else None)
        ready = cache_built and informer_synced is not False
        return ready, {
            "ready": ready,
            "cache_built": cache_built,
            "informer_synced": informer_synced,
            "informer_staleness_s": (round(staleness, 3)
                                     if staleness is not None else None),
            "breaker_state": breaker_state,
            "degraded": breaker_state == "open",
        }

    # -- lifecycle ------------------------------------------------------------

    def _start_fleetwatch(self) -> None:
        import os
        if os.environ.get("TPUSHARE_FLEETWATCH", "1") != "0":
            self.fleetwatch.start()
        if self.defrag.enabled():
            self.defrag.start()

    def start(self) -> int:
        """Bind and serve on a background thread; returns the bound port."""
        from tpushare.core import native as native_engine
        native_engine.warmup()  # first Filter must not pay engine cold-start
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), self._make_handler())
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="tpushare-http", daemon=True)
        t.start()
        self._start_fleetwatch()
        log.info("extender listening on %s:%d", self.host, self.port)
        return self.port

    def serve_forever(self) -> None:
        from tpushare.core import native as native_engine
        native_engine.warmup()
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), self._make_handler())
        self._start_fleetwatch()
        log.info("extender listening on %s:%d", self.host, self.port)
        self._httpd.serve_forever()

    def stop(self) -> None:
        self.defrag.stop()
        self.fleetwatch.stop()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()


def _thread_dump() -> str:
    """Goroutine-dump analogue of the reference's pprof mount
    (pkg/routes/pprof.go:10-22)."""
    lines = []
    for tid, frame in sys._current_frames().items():
        name = next((t.name for t in threading.enumerate()
                     if t.ident == tid), str(tid))
        lines.append(f"--- thread {name} ({tid}) ---")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    return "\n".join(lines) + "\n"


def _heap_profile(top: int = 25) -> str:
    """Heap-profile analogue of pprof's /debug/pprof/heap
    (/root/reference/pkg/routes/pprof.go:10-22) via tracemalloc.

    First call arms tracing and returns a baseline notice; subsequent
    calls report the top allocation sites since then. Tracing stays on
    once armed (a few % overhead) — same operational model as Go's
    always-on heap profiler.
    """
    import tracemalloc
    if not tracemalloc.is_tracing():
        tracemalloc.start(25)
        return ("# tracemalloc armed; heap snapshots available from the "
                "next request on\n")
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("traceback")
    total = sum(s.size for s in stats)
    lines = [f"# live traced heap: {total / 1024:.1f} KiB in "
             f"{sum(s.count for s in stats)} blocks; top {top} sites"]
    for s in stats[:top]:
        lines.append(f"{s.size / 1024:10.1f} KiB  {s.count:6d} blocks")
        for frame in s.traceback.format(limit=4):
            lines.append("    " + frame.strip())
    return "\n".join(lines) + "\n"


def _profile(seconds: float, interval: float = 0.005) -> str:
    """Sampling profile across ALL threads for N seconds (pprof /profile).

    cProfile only instruments the calling thread (which would just be this
    handler sleeping); instead we sample sys._current_frames() and
    aggregate stack suffixes — a flat statistical view of where the
    scheduler actually spends time under load.
    """
    counts: dict[str, int] = {}
    samples = 0
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = traceback.extract_stack(frame, limit=6)
            key = " <- ".join(f"{f.name}:{f.lineno} ({f.filename.rsplit('/', 1)[-1]})"
                              for f in reversed(stack))
            counts[key] = counts.get(key, 0) + 1
            samples += 1
        time.sleep(interval)
    lines = [f"# {samples} samples over {seconds}s at {interval * 1e3:.0f}ms"]
    for key, n in sorted(counts.items(), key=lambda kv: -kv[1])[:40]:
        lines.append(f"{n:6d}  {key}")
    return "\n".join(lines) + "\n"
